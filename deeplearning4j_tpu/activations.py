"""Activation function catalog.

Mirrors the reference activation enum/impl set (reference:
``nd4j-api org.nd4j.linalg.activations.Activation`` as consumed throughout
``deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf/layers/*``).
Activations are referenced by name in layer configs so configurations stay
JSON-serializable; each name maps to a pure jax function suitable for tracing
inside a jitted train step (XLA fuses these into the surrounding matmuls, so
there is no per-activation kernel dispatch as in the reference's libnd4j ops).
"""

from __future__ import annotations

import re
from typing import Callable, Union

import jax
import jax.numpy as jnp

Array = jax.Array
ActivationFn = Callable[[Array], Array]


def identity(x: Array) -> Array:
    return x


def relu(x: Array) -> Array:
    return jax.nn.relu(x)


def relu6(x: Array) -> Array:
    return jnp.minimum(jax.nn.relu(x), 6.0)


def leakyrelu(x: Array, alpha: float = 0.01) -> Array:
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def elu(x: Array, alpha: float = 1.0) -> Array:
    return jax.nn.elu(x, alpha=alpha)


def selu(x: Array) -> Array:
    return jax.nn.selu(x)


def sigmoid(x: Array) -> Array:
    return jax.nn.sigmoid(x)


def hardsigmoid(x: Array) -> Array:
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def tanh(x: Array) -> Array:
    return jnp.tanh(x)


def hardtanh(x: Array) -> Array:
    return jnp.clip(x, -1.0, 1.0)


def rationaltanh(x: Array) -> Array:
    # tanh approximation: 1.7159 * tanh(2x/3) with rational inner approx
    # (reference ActivationRationalTanh semantics).
    a = 1.7159
    y = a * _rational_tanh_inner(2.0 * x / 3.0)
    return y


def _rational_tanh_inner(x: Array) -> Array:
    ax = jnp.abs(x)
    approx = jnp.sign(x) * (1.0 - 1.0 / (1.0 + ax + x * x + 1.41645 * x**4))
    return approx


def rectifiedtanh(x: Array) -> Array:
    return jnp.maximum(0.0, jnp.tanh(x))


def softmax(x: Array) -> Array:
    return jax.nn.softmax(x, axis=-1)


def logsoftmax(x: Array) -> Array:
    return jax.nn.log_softmax(x, axis=-1)


def softplus(x: Array) -> Array:
    return jax.nn.softplus(x)


def softsign(x: Array) -> Array:
    return jax.nn.soft_sign(x)


def cube(x: Array) -> Array:
    return x * x * x


def swish(x: Array) -> Array:
    return jax.nn.silu(x)


def gelu(x: Array) -> Array:
    return jax.nn.gelu(x)


def mish(x: Array) -> Array:
    return x * jnp.tanh(jax.nn.softplus(x))


def thresholdedrelu(x: Array, theta: float = 1.0) -> Array:
    return jnp.where(x > theta, x, 0.0)


def rrelu(x: Array, lower: float = 1.0 / 8.0, upper: float = 1.0 / 3.0) -> Array:
    """Randomized leaky ReLU; deterministic (mean slope) form.

    The reference's RReLU samples a slope per element at train time; under a
    jitted functional step we use the mean slope (its inference behavior) —
    stochastic slope sampling belongs to a dropout-style noise layer instead.
    """
    alpha = (lower + upper) / 2.0
    return jax.nn.leaky_relu(x, negative_slope=alpha)


_REGISTRY: dict[str, ActivationFn] = {
    "identity": identity,
    "linear": identity,
    "relu": relu,
    "relu6": relu6,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "sigmoid": sigmoid,
    "hardsigmoid": hardsigmoid,
    "tanh": tanh,
    "hardtanh": hardtanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softmax": softmax,
    "logsoftmax": logsoftmax,
    "softplus": softplus,
    "softsign": softsign,
    "cube": cube,
    "swish": swish,
    "gelu": gelu,
    "mish": mish,
    "thresholdedrelu": thresholdedrelu,
    "rrelu": rrelu,
}


def get(name_or_fn: Union[str, ActivationFn, None]) -> ActivationFn:
    """Resolve an activation by name (case-insensitive) or pass through a
    callable. ``leakyrelu(alpha)`` / ``thresholdedrelu(theta)`` parse a
    parameter from the name — keeps activation configs JSON-serializable
    strings (reference: ``ActivationLReLU(alpha)`` objects)."""
    if name_or_fn is None:
        return identity
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower().replace("_", "")
    m = re.fullmatch(r"(leakyrelu|thresholdedrelu)\(([-+0-9.e]+)\)", key)
    if m:
        p = float(m.group(2))
        if m.group(1) == "leakyrelu":
            return lambda x: jax.nn.leaky_relu(x, negative_slope=p)
        return lambda x: jnp.where(x > p, x, 0.0)
    if key not in _REGISTRY:
        raise ValueError(
            f"Unknown activation '{name_or_fn}'. Known: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[key]


def names() -> list[str]:
    return sorted(_REGISTRY)
