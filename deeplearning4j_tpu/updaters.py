"""Per-parameter gradient updaters (optimizers).

Parity with the reference's ``IUpdater`` configs + nd4j ``GradientUpdater``
kernels (reference: ``nn/api/Updater``-consumed configs — Sgd, Adam, AdaMax,
AdaDelta, AdaGrad, AMSGrad, Nadam, Nesterovs, NoOp, RmsProp — applied by
``nn/updater/UpdaterBlock.java:105``). Here each updater is a
JSON-serializable config with two pure methods:

- ``init_state(param)`` → pytree of state arrays (zeros, matching shapes)
- ``apply(grad, state, t)`` → ``(update, new_state)`` where the train step
  performs ``params = params - update`` (the functional equivalent of the
  reference's in-place ``params.subi(update)``,
  ``optimize/solvers/StochasticGradientDescent.java:78``).

The step counter ``t`` is a traced int32 (1-based at first apply) so bias
corrections (Adam family) compile into the jitted step. Learning-rate
schedules evaluate inside the trace (see ``schedules.py``).

Per-layer updater overrides, gradient normalization/clipping ("preApply",
reference ``nn/updater/BaseMultiLayerUpdater.java:322``) and l1/l2/weight-
decay application live at the network level in ``nn/updater_graph.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.schedules import FixedSchedule, Schedule, as_schedule

Array = jax.Array
State = Dict[str, Array]


class Updater:
    """Base updater config. Subclasses define DEFAULTS and the math."""

    has_learning_rate = True

    def __init__(self, learning_rate: Union[float, Schedule, None] = None):
        if self.has_learning_rate:
            default = getattr(self, "DEFAULT_LR", 1e-3)
            self.learning_rate: Optional[Schedule] = as_schedule(
                default if learning_rate is None else learning_rate
            )
        else:
            self.learning_rate = None

    # -- functional interface -------------------------------------------------
    def init_state(self, param: Array) -> State:
        return {}

    def apply(self, grad: Array, state: State, t: Array, iteration: Array, epoch: Array) -> Tuple[Array, State]:
        raise NotImplementedError

    def lr(self, iteration, epoch) -> Array:
        assert self.learning_rate is not None
        return self.learning_rate.value_at(iteration, epoch)

    def fixed_learning_rate(self) -> Optional[float]:
        """The lr as a plain float iff it is a FixedSchedule (the only
        schedule the tuner's vmapped population engine can rebind to a
        traced per-trial value), else None — also None for lr-less
        updaters (AdaDelta, NoOp)."""
        if self.learning_rate is None or not isinstance(
                self.learning_rate, FixedSchedule):
            return None
        return float(self.learning_rate.value)

    def with_learning_rate(self, lr: Union[float, Schedule]) -> "Updater":
        """Copy of this updater with the learning rate replaced (no-op
        copy for lr-less updaters) — hyperparameter-override hook for the
        tuner's search spaces."""
        import copy

        u = copy.deepcopy(self)
        if u.has_learning_rate:
            u.learning_rate = as_schedule(lr)
        return u

    # -- serde ---------------------------------------------------------------
    def to_dict(self) -> dict:
        d: Dict[str, Any] = {"@class": type(self).__name__}
        for k, v in self.__dict__.items():
            if isinstance(v, Schedule):
                d[k] = {"@schedule": True, **v.to_dict()}
            else:
                d[k] = v
        return d

    @staticmethod
    def from_dict(d: dict) -> "Updater":
        d = dict(d)
        cls = _UPDATERS[d.pop("@class")]
        obj = cls.__new__(cls)
        for k, v in d.items():
            if isinstance(v, dict) and v.get("@schedule"):
                v = dict(v)
                v.pop("@schedule")
                v = Schedule.from_dict(v)
            setattr(obj, k, v)
        return obj

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __repr__(self):
        return f"{type(self).__name__}({self.__dict__})"


class Sgd(Updater):
    DEFAULT_LR = 1e-1

    def apply(self, grad, state, t, iteration, epoch):
        return self.lr(iteration, epoch) * grad, state


class NoOp(Updater):
    """Pass the raw gradient through unchanged (reference nd4j NoOp)."""

    has_learning_rate = False

    def __init__(self):
        super().__init__()

    def apply(self, grad, state, t, iteration, epoch):
        return grad, state


class Nesterovs(Updater):
    """Nesterov accelerated gradient, reference NesterovsUpdater semantics:

    v' = mu*v - lr*g ;  update = mu*v - (1+mu)*v'  (subtracted from params)
    """

    DEFAULT_LR = 0.1

    def __init__(self, learning_rate=None, momentum: Union[float, Schedule] = 0.9):
        super().__init__(learning_rate)
        self.momentum = as_schedule(momentum)

    def init_state(self, param):
        return {"v": jnp.zeros_like(param)}

    def apply(self, grad, state, t, iteration, epoch):
        mu = self.momentum.value_at(iteration, epoch)
        v_prev = state["v"]
        v = mu * v_prev - self.lr(iteration, epoch) * grad
        update = mu * v_prev - (1.0 + mu) * v
        return update, {"v": v}


class Adam(Updater):
    DEFAULT_LR = 1e-3

    def __init__(self, learning_rate=None, beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = float(beta1), float(beta2), float(epsilon)

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}

    def apply(self, grad, state, t, iteration, epoch):
        b1, b2 = self.beta1, self.beta2
        m = b1 * state["m"] + (1 - b1) * grad
        v = b2 * state["v"] + (1 - b2) * grad * grad
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        alpha = self.lr(iteration, epoch) * jnp.sqrt(1 - b2**tf) / (1 - b1**tf)
        update = alpha * m / (jnp.sqrt(v) + self.epsilon)
        return update, {"m": m, "v": v}


class AdaMax(Updater):
    DEFAULT_LR = 1e-3

    def __init__(self, learning_rate=None, beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = float(beta1), float(beta2), float(epsilon)

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "u": jnp.zeros_like(param)}

    def apply(self, grad, state, t, iteration, epoch):
        b1 = self.beta1
        m = b1 * state["m"] + (1 - b1) * grad
        u = jnp.maximum(self.beta2 * state["u"], jnp.abs(grad))
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        update = self.lr(iteration, epoch) / (1 - b1**tf) * m / (u + self.epsilon)
        return update, {"m": m, "u": u}


class Nadam(Updater):
    DEFAULT_LR = 1e-3

    def __init__(self, learning_rate=None, beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = float(beta1), float(beta2), float(epsilon)

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}

    def apply(self, grad, state, t, iteration, epoch):
        b1, b2 = self.beta1, self.beta2
        m = b1 * state["m"] + (1 - b1) * grad
        v = b2 * state["v"] + (1 - b2) * grad * grad
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        m_hat = m / (1 - b1 ** (tf + 1.0))
        g_hat = grad / (1 - b1**tf)
        v_hat = v / (1 - b2**tf)
        update = (
            self.lr(iteration, epoch)
            * (b1 * m_hat + (1 - b1) * g_hat)
            / (jnp.sqrt(v_hat) + self.epsilon)
        )
        return update, {"m": m, "v": v}


class AMSGrad(Updater):
    DEFAULT_LR = 1e-3

    def __init__(self, learning_rate=None, beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = float(beta1), float(beta2), float(epsilon)

    def init_state(self, param):
        return {
            "m": jnp.zeros_like(param),
            "v": jnp.zeros_like(param),
            "v_hat": jnp.zeros_like(param),
        }

    def apply(self, grad, state, t, iteration, epoch):
        b1, b2 = self.beta1, self.beta2
        m = b1 * state["m"] + (1 - b1) * grad
        v = b2 * state["v"] + (1 - b2) * grad * grad
        v_hat = jnp.maximum(state["v_hat"], v)
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        alpha = self.lr(iteration, epoch) * jnp.sqrt(1 - b2**tf) / (1 - b1**tf)
        update = alpha * m / (jnp.sqrt(v_hat) + self.epsilon)
        return update, {"m": m, "v": v, "v_hat": v_hat}


class AdaGrad(Updater):
    DEFAULT_LR = 1e-1

    def __init__(self, learning_rate=None, epsilon: float = 1e-6):
        super().__init__(learning_rate)
        self.epsilon = float(epsilon)

    def init_state(self, param):
        return {"h": jnp.zeros_like(param)}

    def apply(self, grad, state, t, iteration, epoch):
        h = state["h"] + grad * grad
        update = self.lr(iteration, epoch) * grad / (jnp.sqrt(h) + self.epsilon)
        return update, {"h": h}


class AdaDelta(Updater):
    has_learning_rate = False

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6):
        super().__init__()
        self.rho, self.epsilon = float(rho), float(epsilon)

    def init_state(self, param):
        return {"msg": jnp.zeros_like(param), "msdx": jnp.zeros_like(param)}

    def apply(self, grad, state, t, iteration, epoch):
        rho, eps = self.rho, self.epsilon
        msg = rho * state["msg"] + (1 - rho) * grad * grad
        update = grad * jnp.sqrt(state["msdx"] + eps) / jnp.sqrt(msg + eps)
        msdx = rho * state["msdx"] + (1 - rho) * update * update
        return update, {"msg": msg, "msdx": msdx}


class RmsProp(Updater):
    DEFAULT_LR = 1e-1

    def __init__(self, learning_rate=None, rms_decay: float = 0.95, epsilon: float = 1e-8):
        super().__init__(learning_rate)
        self.rms_decay, self.epsilon = float(rms_decay), float(epsilon)

    def init_state(self, param):
        return {"r": jnp.zeros_like(param)}

    def apply(self, grad, state, t, iteration, epoch):
        r = self.rms_decay * state["r"] + (1 - self.rms_decay) * grad * grad
        update = self.lr(iteration, epoch) * grad / (jnp.sqrt(r + self.epsilon))
        return update, {"r": r}


_UPDATERS = {
    c.__name__: c
    for c in [Sgd, NoOp, Nesterovs, Adam, AdaMax, Nadam, AMSGrad, AdaGrad, AdaDelta, RmsProp]
}


def get(name_or_obj: Union[str, Updater]) -> Updater:
    if isinstance(name_or_obj, Updater):
        return name_or_obj
    key = str(name_or_obj).lower()
    for name, cls in _UPDATERS.items():
        if name.lower() == key:
            return cls()
    raise ValueError(f"Unknown updater '{name_or_obj}'. Known: {sorted(_UPDATERS)}")
