"""Weight initialization schemes.

Parity with the reference's ``WeightInit`` enum and ``WeightInitUtil``
(reference: ``deeplearning4j-nn/.../nn/weights/WeightInit.java``,
``nn/weights/WeightInitUtil.java``): schemes are selected by name in layer
configs, parameterized by fan-in/fan-out computed from the layer shape, and
drawn with an explicit jax PRNG key (the functional replacement for the
reference's global ND4J RNG).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

Array = jax.Array


class Distribution:
    """JSON-serializable distribution for WeightInit.DISTRIBUTION.

    Mirrors reference ``nn/conf/distribution/*`` (Normal, Uniform, Constant,
    LogNormal, TruncatedNormal, Orthogonal, Binomial subset).
    """

    def __init__(self, kind: str, **kwargs):
        self.kind = kind.lower()
        self.kwargs = kwargs

    def sample(self, rng: jax.Array, shape: Sequence[int], dtype=jnp.float32) -> Array:
        k = self.kind
        p = self.kwargs
        if k == "normal" or k == "gaussian":
            return p.get("mean", 0.0) + p.get("std", 1.0) * jax.random.normal(
                rng, shape, dtype
            )
        if k == "uniform":
            return jax.random.uniform(
                rng, shape, dtype, minval=p.get("lower", -1.0), maxval=p.get("upper", 1.0)
            )
        if k == "constant":
            return jnp.full(shape, p.get("value", 0.0), dtype)
        if k == "lognormal":
            return jnp.exp(
                p.get("mean", 0.0)
                + p.get("std", 1.0) * jax.random.normal(rng, shape, dtype)
            )
        if k == "truncated_normal":
            return p.get("mean", 0.0) + p.get("std", 1.0) * jax.random.truncated_normal(
                rng, -2.0, 2.0, shape, dtype
            )
        if k == "orthogonal":
            return _orthogonal(rng, shape, gain=p.get("gain", 1.0), dtype=dtype)
        raise ValueError(f"Unknown distribution kind '{self.kind}'")

    def to_dict(self) -> dict:
        return {"kind": self.kind, **self.kwargs}

    @staticmethod
    def from_dict(d: dict) -> "Distribution":
        d = dict(d)
        return Distribution(d.pop("kind"), **d)

    def __eq__(self, other):
        return (
            isinstance(other, Distribution)
            and self.kind == other.kind
            and self.kwargs == other.kwargs
        )

    def __repr__(self):
        return f"Distribution({self.kind!r}, {self.kwargs})"


def _orthogonal(rng, shape, gain=1.0, dtype=jnp.float32) -> Array:
    if len(shape) < 2:
        raise ValueError("orthogonal init needs >=2 dims")
    rows = shape[0]
    cols = int(math.prod(shape[1:]))
    n = max(rows, cols)
    a = jax.random.normal(rng, (n, n), dtype)
    q, r = jnp.linalg.qr(a)
    q = q * jnp.sign(jnp.diagonal(r))
    return gain * q[:rows, :cols].reshape(shape)


def init_weights(
    rng: jax.Array,
    shape: Sequence[int],
    fan_in: float,
    fan_out: float,
    scheme: Union[str, Distribution] = "xavier",
    distribution: Optional[Distribution] = None,
    dtype=jnp.float32,
) -> Array:
    """Draw a weight tensor per the named scheme.

    Scheme semantics follow reference ``WeightInitUtil.initWeights``:
      - xavier: N(0, 2/(fanIn+fanOut))
      - xavier_uniform: U(+-sqrt(6/(fanIn+fanOut)))
      - xavier_fan_in: N(0, 1/fanIn)
      - xavier_legacy: N(0, 1/(shape[0]*shape[1]))
      - relu: N(0, 2/fanIn) (He)
      - relu_uniform: U(+-sqrt(6/fanIn))
      - lecun_normal: N(0, 1/fanIn)
      - lecun_uniform: U(+-sqrt(3/fanIn))
      - sigmoid_uniform: U(+-4*sqrt(6/(fanIn+fanOut)))
      - uniform: U(+-1/sqrt(fanIn))  (legacy DL4J default uniform)
      - normal: N(0, 1/sqrt(fanIn))
      - zero / ones / identity / distribution / var_scaling_*
    """
    if isinstance(scheme, Distribution):
        return scheme.sample(rng, shape, dtype)
    s = str(scheme).lower()
    fan_in = max(float(fan_in), 1.0)
    fan_out = max(float(fan_out), 1.0)

    if s == "distribution":
        if distribution is None:
            raise ValueError("WeightInit 'distribution' requires a Distribution")
        return distribution.sample(rng, shape, dtype)
    if s == "zero":
        return jnp.zeros(shape, dtype)
    if s == "ones":
        return jnp.ones(shape, dtype)
    if s == "identity":
        if len(shape) == 2 and shape[0] == shape[1]:
            return jnp.eye(shape[0], dtype=dtype)
        raise ValueError("identity init requires a square 2-d shape")
    if s == "xavier":
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(rng, shape, dtype)
    if s in ("xavier_uniform", "xavieruniform"):
        lim = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -lim, lim)
    if s in ("xavier_fan_in", "xavierfanin"):
        std = math.sqrt(1.0 / fan_in)
        return std * jax.random.normal(rng, shape, dtype)
    if s in ("xavier_legacy", "xavierlegacy"):
        std = math.sqrt(1.0 / (shape[0] * shape[1])) if len(shape) >= 2 else math.sqrt(1.0 / shape[0])
        return std * jax.random.normal(rng, shape, dtype)
    if s == "relu":
        std = math.sqrt(2.0 / fan_in)
        return std * jax.random.normal(rng, shape, dtype)
    if s in ("relu_uniform", "reluuniform"):
        lim = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -lim, lim)
    if s in ("lecun_normal", "lecunnormal"):
        std = math.sqrt(1.0 / fan_in)
        return std * jax.random.normal(rng, shape, dtype)
    if s in ("lecun_uniform", "lecununiform"):
        lim = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -lim, lim)
    if s in ("sigmoid_uniform", "sigmoiduniform"):
        lim = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -lim, lim)
    if s == "uniform":
        lim = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(rng, shape, dtype, -lim, lim)
    if s == "normal":
        std = 1.0 / math.sqrt(fan_in)
        return std * jax.random.normal(rng, shape, dtype)
    if s in ("var_scaling_normal_fan_in", "varscalingnormalfanin"):
        return math.sqrt(1.0 / fan_in) * jax.random.normal(rng, shape, dtype)
    if s in ("var_scaling_normal_fan_out", "varscalingnormalfanout"):
        return math.sqrt(1.0 / fan_out) * jax.random.normal(rng, shape, dtype)
    if s in ("var_scaling_normal_fan_avg", "varscalingnormalfanavg"):
        return math.sqrt(2.0 / (fan_in + fan_out)) * jax.random.normal(rng, shape, dtype)
    if s in ("var_scaling_uniform_fan_in", "varscalinguniformfanin"):
        lim = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -lim, lim)
    if s in ("var_scaling_uniform_fan_out", "varscalinguniformfanout"):
        lim = math.sqrt(3.0 / fan_out)
        return jax.random.uniform(rng, shape, dtype, -lim, lim)
    if s in ("var_scaling_uniform_fan_avg", "varscalinguniformfanavg"):
        lim = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -lim, lim)
    if s == "orthogonal":
        return _orthogonal(rng, shape, dtype=dtype)
    raise ValueError(f"Unknown weight init scheme '{scheme}'")
