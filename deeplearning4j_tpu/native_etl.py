"""ctypes bindings for the native ETL library (native/etl.cpp) — the
C++ host-runtime half the reference gets from DataVec/libnd4j
(SURVEY.md §2.9). Auto-builds with ``make -C native`` on first use when a
toolchain is present; every entry point has a numpy fallback so the pure-
Python install keeps working.

API (all return numpy arrays; inputs are converted as needed):
- ``u8_to_f32(arr_u8, scale=1/255, bias=0.0)``
- ``standardize(arr_f32, mean, std)``          (in-place-free)
- ``one_hot(ids_i32, num_classes)``
- ``parse_float_line(line: str, delim=',')``
- ``available()`` → bool — whether the native path is active
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdl4jtpu_etl.so")

_lib = None
_lock = threading.Lock()
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO_PATH) and os.path.exists(
            os.path.join(_NATIVE_DIR, "Makefile")
        ):
            import warnings

            try:
                # one-time build; subsequent loads hit the cached .so.
                # Build failures are REPORTED (the numpy fallback keeps
                # things working, but silently-slow is a debugging trap).
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR], check=True,
                    capture_output=True, timeout=60,
                )
            except subprocess.CalledProcessError as e:
                warnings.warn(
                    "native ETL build failed; using numpy fallbacks. "
                    f"stderr: {e.stderr.decode(errors='replace')[-400:]}",
                    stacklevel=3,
                )
                return None
            except (OSError, subprocess.SubprocessError) as e:
                warnings.warn(
                    f"native ETL build unavailable ({e}); using numpy "
                    "fallbacks",
                    stacklevel=3,
                )
                return None
        if not os.path.exists(_SO_PATH):
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        c_f32p = ctypes.POINTER(ctypes.c_float)
        c_u8p = ctypes.POINTER(ctypes.c_uint8)
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        lib.u8_to_f32_scale.argtypes = [c_u8p, c_f32p, ctypes.c_int64,
                                        ctypes.c_float, ctypes.c_float]
        lib.standardize_f32.argtypes = [c_f32p, ctypes.c_int64,
                                        ctypes.c_float, ctypes.c_float]
        lib.one_hot_f32.argtypes = [c_i32p, ctypes.c_int64, ctypes.c_int64,
                                    c_f32p]
        lib.one_hot_f32.restype = ctypes.c_int64
        lib.parse_floats.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_char, c_f32p, ctypes.c_int64]
        lib.parse_floats.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def u8_to_f32(arr: np.ndarray, scale: float = 1.0 / 255.0,
              bias: float = 0.0) -> np.ndarray:
    arr = np.ascontiguousarray(arr, np.uint8)
    lib = _load()
    if lib is None:
        return arr.astype(np.float32) * scale + bias
    out = np.empty(arr.shape, np.float32)
    lib.u8_to_f32_scale(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), _fptr(out),
        arr.size, ctypes.c_float(scale), ctypes.c_float(bias),
    )
    return out


def standardize(arr: np.ndarray, mean: float, std: float) -> np.ndarray:
    out = np.ascontiguousarray(arr, np.float32).copy()
    inv = 1.0 / max(float(std), 1e-12)
    lib = _load()
    if lib is None:
        return (out - mean) * inv
    lib.standardize_f32(_fptr(out), out.size, ctypes.c_float(mean),
                        ctypes.c_float(inv))
    return out


def one_hot(ids: np.ndarray, num_classes: int) -> np.ndarray:
    ids = np.ascontiguousarray(ids, np.int32)
    lib = _load()
    if lib is None:
        out = np.zeros((ids.size, num_classes), np.float32)
        valid = (ids >= 0) & (ids < num_classes)
        out[np.arange(ids.size)[valid], ids[valid]] = 1.0
        return out.reshape(*ids.shape, num_classes)
    out = np.zeros((ids.size, num_classes), np.float32)
    lib.one_hot_f32(
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), ids.size,
        num_classes, _fptr(out),
    )
    return out.reshape(*ids.shape, num_classes)


def parse_float_line(line: str, delim: str = ",",
                     max_values: int = 4096) -> np.ndarray:
    lib = _load()
    if lib is None:
        return np.asarray(
            [float(v) for v in line.split(delim) if v.strip()], np.float32
        )
    raw = line.encode("utf-8")
    # grow the buffer when saturated: results must match the unbounded
    # numpy fallback regardless of record width
    while True:
        out = np.empty((max_values,), np.float32)
        n = lib.parse_floats(raw, len(raw), ctypes.c_char(delim.encode()),
                             _fptr(out), max_values)
        if n < max_values:
            return out[:n].copy()
        max_values *= 2
