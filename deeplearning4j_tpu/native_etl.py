"""ctypes bindings for the native ETL library (native/etl.cpp) — the
C++ host-runtime half the reference gets from DataVec/libnd4j
(SURVEY.md §2.9). Auto-builds with ``make -C native`` on first use when a
toolchain is present; every entry point has a numpy fallback so the pure-
Python install keeps working.

API (all return numpy arrays; inputs are converted as needed):
- ``u8_to_f32(arr_u8, scale=1/255, bias=0.0)``
- ``standardize(arr_f32, mean, std)``          (in-place-free)
- ``one_hot(ids_i32, num_classes)``
- ``parse_float_line(line: str, delim=',')``
- ``available()`` → bool — whether the native path is active
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdl4jtpu_etl.so")

_lib = None
_lock = threading.Lock()
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")):
            import warnings

            try:
                # make is incremental (target depends on etl.cpp), so run
                # it unconditionally: a stale .so from an older source
                # would otherwise silently lack newer kernels forever.
                # Build failures are REPORTED (the numpy fallback keeps
                # things working, but silently-slow is a debugging trap).
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR], check=True,
                    capture_output=True, timeout=60,
                )
            except subprocess.CalledProcessError as e:
                have_so = os.path.exists(_SO_PATH)
                warnings.warn(
                    "native ETL build failed; "
                    + ("loading the EXISTING (possibly stale) library"
                       if have_so else "using numpy fallbacks")
                    + f". stderr: {e.stderr.decode(errors='replace')[-400:]}",
                    stacklevel=3,
                )
                if not have_so:
                    return None
            except (OSError, subprocess.SubprocessError) as e:
                have_so = os.path.exists(_SO_PATH)
                warnings.warn(
                    f"native ETL build unavailable ({e}); "
                    + ("loading the EXISTING (possibly stale) library"
                       if have_so else "using numpy fallbacks"),
                    stacklevel=3,
                )
                if not have_so:
                    return None
        if not os.path.exists(_SO_PATH):
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        c_f32p = ctypes.POINTER(ctypes.c_float)
        c_u8p = ctypes.POINTER(ctypes.c_uint8)
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        lib.u8_to_f32_scale.argtypes = [c_u8p, c_f32p, ctypes.c_int64,
                                        ctypes.c_float, ctypes.c_float]
        lib.standardize_f32.argtypes = [c_f32p, ctypes.c_int64,
                                        ctypes.c_float, ctypes.c_float]
        lib.one_hot_f32.argtypes = [c_i32p, ctypes.c_int64, ctypes.c_int64,
                                    c_f32p]
        lib.one_hot_f32.restype = ctypes.c_int64
        lib.parse_floats.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_char, c_f32p, ctypes.c_int64]
        lib.parse_floats.restype = ctypes.c_int64
        try:  # NLP batch kernels (added after the first .so shipped —
            # a stale build simply keeps the numpy fallbacks for these)
            lib.skipgram_pairs_i32.argtypes = [c_i32p, ctypes.c_int64,
                                               c_i32p, c_i32p, c_i32p]
            lib.skipgram_pairs_i32.restype = ctypes.c_int64
            lib.cbow_windows_i32.argtypes = [c_i32p, ctypes.c_int64, c_i32p,
                                             ctypes.c_int64, c_i32p, c_f32p]
        except AttributeError:
            lib.skipgram_pairs_i32 = None
            lib.cbow_windows_i32 = None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def u8_to_f32(arr: np.ndarray, scale: float = 1.0 / 255.0,
              bias: float = 0.0) -> np.ndarray:
    arr = np.ascontiguousarray(arr, np.uint8)
    lib = _load()
    if lib is None:
        return arr.astype(np.float32) * scale + bias
    out = np.empty(arr.shape, np.float32)
    lib.u8_to_f32_scale(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), _fptr(out),
        arr.size, ctypes.c_float(scale), ctypes.c_float(bias),
    )
    return out


def standardize(arr: np.ndarray, mean: float, std: float) -> np.ndarray:
    out = np.ascontiguousarray(arr, np.float32).copy()
    inv = 1.0 / max(float(std), 1e-12)
    lib = _load()
    if lib is None:
        return (out - mean) * inv
    lib.standardize_f32(_fptr(out), out.size, ctypes.c_float(mean),
                        ctypes.c_float(inv))
    return out


def one_hot(ids: np.ndarray, num_classes: int) -> np.ndarray:
    ids = np.ascontiguousarray(ids, np.int32)
    lib = _load()
    if lib is None:
        out = np.zeros((ids.size, num_classes), np.float32)
        valid = (ids >= 0) & (ids < num_classes)
        out[np.arange(ids.size)[valid], ids[valid]] = 1.0
        return out.reshape(*ids.shape, num_classes)
    out = np.zeros((ids.size, num_classes), np.float32)
    lib.one_hot_f32(
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), ids.size,
        num_classes, _fptr(out),
    )
    return out.reshape(*ids.shape, num_classes)


def parse_float_line(line: str, delim: str = ",",
                     max_values: int = 4096) -> np.ndarray:
    lib = _load()
    if lib is None:
        return np.asarray(
            [float(v) for v in line.split(delim) if v.strip()], np.float32
        )
    raw = line.encode("utf-8")
    # grow the buffer when saturated: results must match the unbounded
    # numpy fallback regardless of record width
    while True:
        out = np.empty((max_values,), np.float32)
        n = lib.parse_floats(raw, len(raw), ctypes.c_char(delim.encode()),
                             _fptr(out), max_values)
        if n < max_values:
            return out[:n].copy()
        max_values *= 2


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def skipgram_pairs(ids: np.ndarray, half_windows: np.ndarray):
    """(centers, contexts) int32 pairs with per-position window shrink —
    the reference's native AggregateSkipGram batch-building role. Numpy/
    Python fallback matches exactly."""
    ids = np.ascontiguousarray(ids, np.int32)
    half_windows = np.ascontiguousarray(half_windows, np.int32)
    n = ids.size
    if n < 2:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    lib = _load()
    if lib is not None and getattr(lib, "skipgram_pairs_i32", None) is not None:
        cap = int(2 * n * max(int(half_windows.max()), 1))
        cs = np.empty((cap,), np.int32)
        xs = np.empty((cap,), np.int32)
        k = lib.skipgram_pairs_i32(_i32ptr(ids), n, _i32ptr(half_windows),
                                   _i32ptr(cs), _i32ptr(xs))
        return cs[:k].copy(), xs[:k].copy()
    cs_l, xs_l = [], []
    for i in range(n):
        b = int(half_windows[i])
        lo, hi = max(0, i - b), min(n, i + b + 1)
        for j in range(lo, hi):
            if j != i:
                cs_l.append(ids[i])
                xs_l.append(ids[j])
    return np.asarray(cs_l, np.int32), np.asarray(xs_l, np.int32)


def cbow_windows(ids: np.ndarray, half_windows: np.ndarray, width: int):
    """Left-packed CBOW context windows: (ctx (n, width) int32,
    mask (n, width) float32)."""
    ids = np.ascontiguousarray(ids, np.int32)
    half_windows = np.ascontiguousarray(half_windows, np.int32)
    n = ids.size
    ctx = np.zeros((n, width), np.int32)
    mask = np.zeros((n, width), np.float32)
    if n < 2:
        return ctx, mask
    lib = _load()
    if lib is not None and getattr(lib, "cbow_windows_i32", None) is not None:
        lib.cbow_windows_i32(_i32ptr(ids), n, _i32ptr(half_windows), width,
                             _i32ptr(ctx), _fptr(mask))
        return ctx, mask
    for i in range(n):
        b = int(half_windows[i])
        js = [j for j in range(max(0, i - b), min(n, i + b + 1)) if j != i]
        js = js[:width]
        ctx[i, :len(js)] = ids[js]
        mask[i, :len(js)] = 1.0
    return ctx, mask
