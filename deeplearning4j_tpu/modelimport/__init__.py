"""Model import (reference ``deeplearning4j-modelimport``)."""

from deeplearning4j_tpu.modelimport.keras import KerasModelImport

__all__ = ["KerasModelImport"]
