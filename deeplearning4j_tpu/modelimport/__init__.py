"""Model import (reference ``deeplearning4j-modelimport``, SURVEY.md §2.6).

Keras format-support matrix (round 3):

| Format                                   | Status                     |
|------------------------------------------|----------------------------|
| Keras 2.x full-model ``.h5``             | yes (Hdf5Archive)          |
| Keras 3.x legacy full-model ``.h5``      | yes (Hdf5Archive)          |
| Keras 3.x native ``.keras`` zip          | yes (KerasZipArchive;      |
|                                          | positional vars renamed)   |
| weights-only ``.h5`` / ``.weights.h5``   | only with an architecture  |
|                                          | JSON (see next row)        |
| architecture-JSON + weights pair         | yes — pass ``weights_path``|
|                                          | (reference two-arg         |
|                                          | importKerasModelAndWeights)|
| ``channels_first`` data format           | yes — imported into the    |
|                                          | NHWC runtime (feed NHWC    |
|                                          | inputs; Keras-1 flatten    |
|                                          | row order auto-permuted)   |
| uncompiled model, non-inferable loss     | loud error; pass           |
|                                          | ``default_loss=...``       |

Layer coverage: 46 registered mappers (see keras/mappers.py); golden-
output parity tests in tests/test_keras_import.py.
"""

from deeplearning4j_tpu.modelimport.keras import KerasModelImport
from deeplearning4j_tpu.modelimport.dl4j import (
    restore_java_multi_layer_network,
    write_java_model,
)

__all__ = [
    "KerasModelImport",
    "restore_java_multi_layer_network",
    "write_java_model",
]
