"""Interop with the Java stack's model-zip format (reference
``util/ModelSerializer.java``): load Java-produced zips, export zips the
Java stack can read. See ``loader.py`` for the format contract."""

from deeplearning4j_tpu.modelimport.dl4j.loader import (  # noqa: F401
    load_java_configuration,
    restore_java_multi_layer_network,
    write_java_model,
)
from deeplearning4j_tpu.modelimport.dl4j import nd4j_bin  # noqa: F401

__all__ = [
    "load_java_configuration",
    "restore_java_multi_layer_network",
    "write_java_model",
    "nd4j_bin",
]
