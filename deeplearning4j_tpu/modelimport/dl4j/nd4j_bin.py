"""ND4J binary array stream format (``Nd4j.write``/``Nd4j.read``).

The Java stack's ``ModelSerializer`` stores ``coefficients.bin`` /
``updaterState.bin`` by calling ``Nd4j.write(INDArray, DataOutputStream)``
(reference ``deeplearning4j-nn/src/main/java/org/deeplearning4j/util/
ModelSerializer.java:118-135``). That writes two ND4J ``DataBuffer``
streams back to back — the shape-info buffer then the data buffer — each
in the ``BaseDataBuffer.write`` wire layout:

    writeUTF(allocationMode)   # java modified-UTF8: u16 length + bytes
    writeInt(length)           # element count (writeLong for LONG_SHAPE /
                               #  MIXED_DATA_TYPES era buffers)
    writeUTF(dataType)         # "INT" | "LONG" | "FLOAT" | "DOUBLE" | "HALF"
    <length elements, big-endian>

The shape-info buffer for a rank-R array is the standard ND4J shape
descriptor: ``[rank, *shape, *stride, offset, elementWiseStride,
orderChar]`` (length 2R+4, order stored as the ASCII code of 'c'/'f').

ND4J (the reference's tensor runtime) is a separate source tree not
vendored here, so this module is written to the wire layout as consumed
by ``BaseDataBuffer.read`` across the 0.9.x–1.0.0-beta era the reference
targets: the reader below is deliberately tolerant (int- and long-length
headers, any known allocation-mode tag), and the writer emits the
narrow-int 0.9.x/1.0.0-alpha form that every era can read back.
"""

from __future__ import annotations

import struct
from typing import BinaryIO, Tuple

import numpy as np

# AllocationMode tags that have appeared in BaseDataBuffer headers.
# LONG_SHAPE / MIXED_DATA_TYPES era headers switch the length field to i64.
_INT_LEN_MODES = {"HEAP", "JAVACPP", "DIRECT"}
_LONG_LEN_MODES = {"LONG_SHAPE", "MIXED_DATA_TYPES"}

_DTYPES = {
    "INT": (">i4", np.int32),
    "LONG": (">i8", np.int64),
    "FLOAT": (">f4", np.float32),
    "DOUBLE": (">f8", np.float64),
    "HALF": (">f2", np.float16),
}
_NP_TO_ND4J = {
    np.dtype(np.int32): "INT",
    np.dtype(np.int64): "LONG",
    np.dtype(np.float32): "FLOAT",
    np.dtype(np.float64): "DOUBLE",
    np.dtype(np.float16): "HALF",
}


def _read_utf(f: BinaryIO) -> str:
    """java.io.DataInputStream.readUTF: u16 byte-length + modified UTF-8
    (pure-ASCII for every tag we care about)."""
    raw = f.read(2)
    if len(raw) < 2:
        raise EOFError("truncated ND4J stream (UTF length)")
    (n,) = struct.unpack(">H", raw)
    data = f.read(n)
    if len(data) < n:
        raise EOFError("truncated ND4J stream (UTF body)")
    return data.decode("utf-8")


def _write_utf(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack(">H", len(b)))
    f.write(b)


def read_buffer(f: BinaryIO) -> Tuple[np.ndarray, str]:
    """Read one DataBuffer; returns (1-D numpy array, allocation_mode)."""
    mode = _read_utf(f)
    if mode in _LONG_LEN_MODES:
        (length,) = struct.unpack(">q", f.read(8))
    elif mode in _INT_LEN_MODES:
        (length,) = struct.unpack(">i", f.read(4))
    else:
        raise ValueError(
            f"Unknown ND4J allocation mode {mode!r} — not an Nd4j.write "
            f"stream, or a newer wire format than this reader understands")
    dtype_name = _read_utf(f)
    if dtype_name not in _DTYPES:
        raise ValueError(f"Unknown ND4J data type {dtype_name!r}")
    be, np_t = _DTYPES[dtype_name]
    nbytes = length * np.dtype(be).itemsize
    raw = f.read(nbytes)
    if len(raw) < nbytes:
        raise EOFError(
            f"truncated ND4J stream: wanted {nbytes} data bytes, got "
            f"{len(raw)}")
    return np.frombuffer(raw, dtype=be).astype(np_t), mode


def write_buffer(f: BinaryIO, arr: np.ndarray, mode: str = "HEAP") -> None:
    arr = np.ascontiguousarray(arr).reshape(-1)
    name = _NP_TO_ND4J.get(arr.dtype)
    if name is None:
        raise TypeError(f"No ND4J data type for numpy dtype {arr.dtype}")
    _write_utf(f, mode)
    f.write(struct.pack(">i", arr.size))
    _write_utf(f, name)
    f.write(arr.astype(_DTYPES[name][0]).tobytes())


def read_array(f: BinaryIO) -> np.ndarray:
    """``Nd4j.read``: shape-info buffer + data buffer → numpy array with
    the stored shape/order applied."""
    shape_info, _ = read_buffer(f)
    shape_info = shape_info.astype(np.int64)
    rank = int(shape_info[0])
    if len(shape_info) < 2 * rank + 4:
        raise ValueError(
            f"shape-info buffer too short for rank {rank}: "
            f"{len(shape_info)} elements")
    shape = tuple(int(s) for s in shape_info[1:1 + rank])
    order = chr(int(shape_info[2 * rank + 3]))
    if order not in ("c", "f"):
        raise ValueError(f"Bad order char {order!r} in shape info")
    data, _ = read_buffer(f)
    n = int(np.prod(shape)) if rank else 1
    if data.size != n:
        raise ValueError(
            f"data buffer has {data.size} elements for shape {shape}")
    return data.reshape(shape, order=order)


def write_array(f: BinaryIO, arr: np.ndarray, order: str = "c") -> None:
    """``Nd4j.write``: emit shape-info + data buffers for ``arr``."""
    arr = np.asarray(arr)
    rank = arr.ndim
    shape = arr.shape
    # strides in elements for the chosen logical order
    strides = []
    acc = 1
    if order == "c":
        for s in reversed(shape):
            strides.insert(0, acc)
            acc *= s
    else:
        for s in shape:
            strides.append(acc)
            acc *= s
    info = np.asarray(
        [rank, *shape, *strides, 0, 1, ord(order)], dtype=np.int32)
    write_buffer(f, info)
    write_buffer(f, np.asarray(arr).reshape(-1, order=order.upper()))
