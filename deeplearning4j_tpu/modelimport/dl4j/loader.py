"""Cross-stack DL4J model-zip interop: load (and export) models in the
Java stack's on-disk format.

Reference layout (``deeplearning4j-nn/.../util/ModelSerializer.java:
39-135``): a zip with ``configuration.json`` (Jackson JSON of
MultiLayerConfiguration), ``coefficients.bin`` (``Nd4j.write`` of the
single flattened parameter row-vector), optional ``updaterState.bin`` and
``normalizer.bin``.

Configuration JSON conventions (this is the *Java* schema, distinct from
this package's own ``@class`` serde):

- layers are Jackson WRAPPER_OBJECT polymorphic — ``{"dense": {...}}`` —
  with type names from the ``@JsonSubTypes`` registry on
  ``nn/conf/layers/Layer.java:54-88``;
- ``IActivation`` / ``ILossFunction`` / ``IUpdater`` values are
  class-name polymorphic — ``{"@class": "org.nd4j.linalg...."}`` — the
  form ``nn/conf/serde/BaseNetConfigDeserializer.java`` post-processes;
- enums (WeightInit, PoolingType, ConvolutionMode, BackpropType,
  OptimizationAlgorithm) are plain strings.

Parameter flattening (``coefficients.bin``) follows each layer's
ParamInitializer view layout, concatenated in layer order:

- Dense/Output/Embedding: ``W`` (nIn·nOut, **'f' order** of (nIn,nOut))
  then ``b`` (nOut) — ``params/DefaultParamInitializer.java:104-128``,
  gradient view ``reshape('f', nIn, nOut)``;
- Convolution: ``b`` (nOut) FIRST, then ``W`` (**'c' order** of
  (nOut,nIn,kH,kW)) — ``params/ConvolutionParamInitializer.java:
  105-132,170-200`` ("c order is used specifically for the CNN weights");
- BatchNormalization: gamma, beta, mean, var (each nOut; gamma/beta
  absent when lockGammaBeta) — ``params/BatchNormalizationParamInitializer
  .java:80-115``;
- LSTM: ``W`` (nIn,4n 'f'), ``RW`` (n,4n 'f'), ``b`` (4n), gate column
  order IFOG = [input, forget, output, modulation] —
  ``params/LSTMParamInitializer.java:104-170`` (matches this package's
  [i, f, o, g] packing exactly).

Java updater state (``updaterState.bin``) uses the Java stack's updater
view layout and is NOT mapped — restored models get fresh optimizer
state, the reference's own ``loadUpdater=false`` path.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.modelimport.dl4j import nd4j_bin

# ----------------------------------------------------------------------
# name maps: Java wire names <-> this package's registries
# ----------------------------------------------------------------------

_ACTIVATION_MAP = {
    "ActivationReLU": "relu", "ActivationReLU6": "relu6",
    "ActivationSigmoid": "sigmoid", "ActivationTanH": "tanh",
    "ActivationSoftmax": "softmax", "ActivationIdentity": "identity",
    "ActivationLReLU": "leakyrelu", "ActivationELU": "elu",
    "ActivationSELU": "selu", "ActivationGELU": "gelu",
    "ActivationSoftPlus": "softplus", "ActivationSoftSign": "softsign",
    "ActivationHardSigmoid": "hardsigmoid",
    "ActivationHardTanH": "hardtanh", "ActivationCube": "cube",
    "ActivationRationalTanh": "rationaltanh",
    "ActivationRectifiedTanh": "rectifiedtanh",
    "ActivationSwish": "swish", "ActivationMish": "mish",
    "ActivationThresholdedReLU": "thresholdedrelu",
}
_ACTIVATION_EXPORT = {v: k for k, v in _ACTIVATION_MAP.items()}

_LOSS_MAP = {
    "LossMCXENT": "mcxent", "LossNegativeLogLikelihood":
        "negativeloglikelihood", "LossMSE": "mse", "LossBinaryXENT":
        "xent", "LossL1": "l1", "LossL2": "l2", "LossMAE": "mae",
    "LossMAPE": "mape", "LossMSLE": "msle", "LossHinge": "hinge",
    "LossSquaredHinge": "squared_hinge", "LossPoisson": "poisson",
    "LossKLD": "kld", "LossCosineProximity": "cosine_proximity",
    "LossWasserstein": "wasserstein",
}
_LOSS_EXPORT = {v: k for k, v in _LOSS_MAP.items()}

_ACT_PKG = "org.nd4j.linalg.activations.impl."
_LOSS_PKG = "org.nd4j.linalg.lossfunctions.impl."
_UPD_PKG = "org.nd4j.linalg.learning.config."


def _map_activation(node) -> str:
    if node is None:
        return "identity"
    if isinstance(node, str):  # legacy pre-IActivation string form
        return node.lower()
    cls = node.get("@class", "").rsplit(".", 1)[-1]
    if cls not in _ACTIVATION_MAP:
        raise ValueError(f"Unsupported Java activation {cls!r}")
    return _ACTIVATION_MAP[cls]


def _map_loss(node) -> str:
    if isinstance(node, str):
        return node.lower()
    cls = node.get("@class", "").rsplit(".", 1)[-1]
    if cls not in _LOSS_MAP:
        raise ValueError(f"Unsupported Java loss function {cls!r}")
    return _LOSS_MAP[cls]


def _map_updater(node):
    """``IUpdater`` @class JSON → this package's Updater."""
    from deeplearning4j_tpu import updaters as U

    if node is None:
        return None
    cls = node.get("@class", "").rsplit(".", 1)[-1]
    lr = node.get("learningRate", 1e-3)
    if cls == "Sgd":
        return U.Sgd(lr)
    if cls == "Adam":
        return U.Adam(lr, beta1=node.get("beta1", 0.9),
                      beta2=node.get("beta2", 0.999),
                      epsilon=node.get("epsilon", 1e-8))
    if cls == "AdaMax":
        return U.AdaMax(lr, beta1=node.get("beta1", 0.9),
                        beta2=node.get("beta2", 0.999),
                        epsilon=node.get("epsilon", 1e-8))
    if cls == "Nadam":
        return U.Nadam(lr, beta1=node.get("beta1", 0.9),
                       beta2=node.get("beta2", 0.999),
                       epsilon=node.get("epsilon", 1e-8))
    if cls == "AMSGrad":
        return U.AMSGrad(lr, beta1=node.get("beta1", 0.9),
                         beta2=node.get("beta2", 0.999),
                         epsilon=node.get("epsilon", 1e-8))
    if cls == "Nesterovs":
        return U.Nesterovs(lr, momentum=node.get("momentum", 0.9))
    if cls == "AdaGrad":
        return U.AdaGrad(lr, epsilon=node.get("epsilon", 1e-6))
    if cls == "AdaDelta":
        return U.AdaDelta(rho=node.get("rho", 0.95),
                          epsilon=node.get("epsilon", 1e-6))
    if cls == "RmsProp":
        return U.RmsProp(lr, rms_decay=node.get("rmsDecay", 0.95),
                         epsilon=node.get("epsilon", 1e-8))
    if cls == "NoOp":
        return U.NoOp()
    raise ValueError(f"Unsupported Java updater {cls!r}")


def _export_updater(u) -> dict:
    from deeplearning4j_tpu import updaters as U

    def _lr(x):
        lr = getattr(x, "learning_rate", None)
        return float(lr) if isinstance(lr, (int, float)) else 1e-3

    if isinstance(u, U.Sgd):
        return {"@class": _UPD_PKG + "Sgd", "learningRate": _lr(u)}
    if isinstance(u, (U.Adam, U.AdaMax, U.Nadam, U.AMSGrad)):
        name = type(u).__name__
        return {"@class": _UPD_PKG + name, "learningRate": _lr(u),
                "beta1": u.beta1, "beta2": u.beta2, "epsilon": u.epsilon}
    if isinstance(u, U.Nesterovs):
        m = u.momentum if isinstance(u.momentum, (int, float)) else 0.9
        return {"@class": _UPD_PKG + "Nesterovs", "learningRate": _lr(u),
                "momentum": m}
    if isinstance(u, U.AdaGrad):
        return {"@class": _UPD_PKG + "AdaGrad", "learningRate": _lr(u),
                "epsilon": u.epsilon}
    if isinstance(u, U.AdaDelta):
        return {"@class": _UPD_PKG + "AdaDelta", "rho": u.rho,
                "epsilon": u.epsilon}
    if isinstance(u, U.RmsProp):
        return {"@class": _UPD_PKG + "RmsProp", "learningRate": _lr(u),
                "rmsDecay": u.rms_decay, "epsilon": u.epsilon}
    if isinstance(u, U.NoOp):
        return {"@class": _UPD_PKG + "NoOp"}
    raise ValueError(f"No Java export mapping for updater {type(u).__name__}")


def _map_weight_init(name: Optional[str]) -> str:
    if not name:
        return "xavier"
    return name.lower()


def _pair(v) -> List[int]:
    if isinstance(v, (list, tuple)):
        return [int(v[0]), int(v[1] if len(v) > 1 else v[0])]
    return [int(v), int(v)]


# ----------------------------------------------------------------------
# per-layer translation: Java JSON node -> (our Layer, param slicer)
# ----------------------------------------------------------------------

def _base_kwargs(node: dict) -> dict:
    from deeplearning4j_tpu.regularization import RegularizationConf

    kw = {}
    if node.get("layerName"):
        kw["name"] = node["layerName"]
    upd = _map_updater(node.get("iUpdater"))
    if upd is not None:
        kw["updater"] = upd
    l1 = float(node.get("l1") or 0.0)
    l2 = float(node.get("l2") or 0.0)
    if l1 or l2:
        kw["regularization"] = RegularizationConf(
            l1=l1, l2=l2, l1_bias=float(node.get("l1Bias") or 0.0),
            l2_bias=float(node.get("l2Bias") or 0.0))
    return kw


def _ff_kwargs(node: dict) -> dict:
    kw = _base_kwargs(node)
    kw["n_in"] = int(node["nIn"])
    kw["n_out"] = int(node["nOut"])
    kw["activation"] = _map_activation(node.get("activationFn"))
    kw["weight_init"] = _map_weight_init(node.get("weightInit"))
    bias_init = node.get("biasInit")
    if bias_init is not None and not _is_nan(bias_init):
        kw["bias_init"] = float(bias_init)
    return kw


def _is_nan(v) -> bool:
    try:
        return v != v
    except Exception:  # noqa: BLE001 — exotic value type; not NaN
        return False


def _take(flat: np.ndarray, pos: int, n: int) -> Tuple[np.ndarray, int]:
    if pos + n > flat.size:
        raise ValueError(
            f"coefficients.bin too short: wanted {pos + n} values, "
            f"have {flat.size}")
    return flat[pos:pos + n], pos + n


def _dense_like(cls_name: str):
    def build(node):
        from deeplearning4j_tpu.nn.conf import layers as L

        kw = _ff_kwargs(node)
        if cls_name in ("OutputLayer", "RnnOutputLayer", "LossLayer"):
            kw["loss"] = _map_loss(node.get("lossFn", "mcxent"))
        if cls_name == "LossLayer":
            kw.pop("n_in", None), kw.pop("n_out", None)
        layer = getattr(L, cls_name)(**kw)

        has_bias = bool(node.get("hasBias", True))

        def slicer(flat, pos, params, state):
            n_in, n_out = int(node["nIn"]), int(node["nOut"])
            w, pos = _take(flat, pos, n_in * n_out)
            params["W"] = w.reshape((n_in, n_out), order="F")
            if has_bias:
                b, pos = _take(flat, pos, n_out)
                params["b"] = b
            else:
                # hasBias=false zips store no bias values — consuming
                # them would mis-slice every subsequent parameter
                params["b"] = np.zeros((n_out,), flat.dtype)
            return pos

        return layer, (None if cls_name == "LossLayer" else slicer)
    return build


def _build_conv(node):
    from deeplearning4j_tpu.nn.conf import layers as L

    kw = _ff_kwargs(node)
    kw["kernel_size"] = _pair(node["kernelSize"])
    kw["stride"] = _pair(node.get("stride", 1))
    kw["padding"] = _pair(node.get("padding", 0))
    kw["convolution_mode"] = (node.get("convolutionMode")
                              or "Truncate").lower()
    if "dilation" in node and node["dilation"]:
        kw["dilation"] = _pair(node["dilation"])
    kw["has_bias"] = bool(node.get("hasBias", True))
    layer = L.ConvolutionLayer(**kw)

    def slicer(flat, pos, params, state):
        n_in, n_out = int(node["nIn"]), int(node["nOut"])
        kh, kw_ = kw["kernel_size"]
        if kw["has_bias"]:
            b, pos = _take(flat, pos, n_out)  # bias FIRST (see module doc)
            params["b"] = b
        w, pos = _take(flat, pos, n_out * n_in * kh * kw_)
        # 'c'-order (nOut,nIn,kH,kW) OIHW -> our HWIO (kH,kW,nIn,nOut)
        params["W"] = np.transpose(
            w.reshape((n_out, n_in, kh, kw_), order="C"), (2, 3, 1, 0))
        return pos

    return layer, slicer


def _build_subsampling(node):
    from deeplearning4j_tpu.nn.conf import layers as L

    kw = _base_kwargs(node)
    kw.pop("updater", None)  # no params
    kw["pooling_type"] = (node.get("poolingType") or "MAX").lower()
    kw["kernel_size"] = _pair(node.get("kernelSize", 2))
    kw["stride"] = _pair(node.get("stride", 2))
    kw["padding"] = _pair(node.get("padding", 0))
    kw["convolution_mode"] = (node.get("convolutionMode")
                              or "Truncate").lower()
    if node.get("pnorm"):
        kw["pnorm"] = int(node["pnorm"])
    return L.SubsamplingLayer(**kw), None


def _build_batchnorm(node):
    from deeplearning4j_tpu.nn.conf import layers as L

    kw = _base_kwargs(node)
    kw["decay"] = float(node.get("decay", 0.9))
    kw["eps"] = float(node.get("eps", 1e-5))
    kw["gamma"] = float(node.get("gamma", 1.0))
    kw["beta"] = float(node.get("beta", 0.0))
    lock = bool(node.get("lockGammaBeta", False))
    kw["lock_gamma_beta"] = lock
    layer = L.BatchNormalization(**kw)
    n_out = int(node["nOut"])

    def slicer(flat, pos, params, state):
        if not lock:
            g, pos = _take(flat, pos, n_out)
            b, pos = _take(flat, pos, n_out)
            params["gamma"] = g
            params["beta"] = b
        mean, pos = _take(flat, pos, n_out)
        var, pos = _take(flat, pos, n_out)
        state["mean"] = mean  # running stats live in layer STATE here
        state["var"] = var
        return pos

    return layer, slicer


def _build_lstm(node):
    from deeplearning4j_tpu.nn.conf import layers as L

    kw = _ff_kwargs(node)
    kw["forget_gate_bias_init"] = float(node.get("forgetGateBiasInit", 1.0))
    if node.get("gateActivationFn") is not None:
        kw["gate_activation"] = _map_activation(node["gateActivationFn"])
    layer = L.LSTM(**kw)

    def slicer(flat, pos, params, state):
        n_in, n = int(node["nIn"]), int(node["nOut"])
        w, pos = _take(flat, pos, n_in * 4 * n)
        rw, pos = _take(flat, pos, n * 4 * n)
        b, pos = _take(flat, pos, 4 * n)
        # IFOG columns == our [i, f, o, g] packing: no gate permutation
        params["Wx"] = w.reshape((n_in, 4 * n), order="F")
        params["Wh"] = rw.reshape((n, 4 * n), order="F")
        params["b"] = b
        return pos

    return layer, slicer


def _build_embedding(node):
    from deeplearning4j_tpu.nn.conf import layers as L

    kw = _ff_kwargs(node)
    has_bias = bool(node.get("hasBias", True))
    layer = L.EmbeddingLayer(**kw)

    def slicer(flat, pos, params, state):
        n_in, n_out = int(node["nIn"]), int(node["nOut"])
        w, pos = _take(flat, pos, n_in * n_out)
        params["W"] = w.reshape((n_in, n_out), order="F")
        if has_bias:
            b, pos = _take(flat, pos, n_out)
            params["b"] = b
        return pos

    return layer, slicer


def _build_activation(node):
    from deeplearning4j_tpu.nn.conf import layers as L

    return L.ActivationLayer(
        activation=_map_activation(node.get("activationFn"))), None


def _build_dropout(node):
    from deeplearning4j_tpu.nn.conf import layers as L

    p = 0.5
    drop = node.get("iDropout")
    if isinstance(drop, dict) and "p" in drop:
        # Java Dropout stores RETAIN probability p; ours is drop prob
        p = 1.0 - float(drop["p"])
    return L.DropoutLayer(dropout=p), None


_LAYER_BUILDERS = {
    "dense": _dense_like("DenseLayer"),
    "output": _dense_like("OutputLayer"),
    "rnnoutput": _dense_like("RnnOutputLayer"),
    "loss": _dense_like("LossLayer"),
    "convolution": _build_conv,
    "subsampling": _build_subsampling,
    "batchNormalization": _build_batchnorm,
    "LSTM": _build_lstm,
    "embedding": _build_embedding,
    "activation": _build_activation,
    "dropout": _build_dropout,
}

_PREPROCESSOR_BUILDERS = {
    "cnnToFeedForward": lambda n: _pp("CnnToFeedForwardPreProcessor")(
        height=int(n.get("inputHeight", 0)),
        width=int(n.get("inputWidth", 0)),
        channels=int(n.get("numChannels", 0))),
    "feedForwardToCnn": lambda n: _pp("FeedForwardToCnnPreProcessor")(
        height=int(n.get("inputHeight", 0)),
        width=int(n.get("inputWidth", 0)),
        channels=int(n.get("numChannels", 0))),
    "rnnToFeedForward": lambda n: _pp("RnnToFeedForwardPreProcessor")(),
    "feedForwardToRnn": lambda n: _pp("FeedForwardToRnnPreProcessor")(),
    "cnnToRnn": lambda n: _pp("CnnToRnnPreProcessor")(),
}


def _pp(name):
    from deeplearning4j_tpu.nn.conf import preprocessors as P

    return getattr(P, name)


# ----------------------------------------------------------------------
# load
# ----------------------------------------------------------------------

def load_java_configuration(conf_json: str):
    """Java ``MultiLayerConfiguration.toJson()`` → (our
    MultiLayerConfiguration, param slicers, java layer nodes)."""
    from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.conf.layers.base import GlobalConf

    root = json.loads(conf_json)
    confs = root.get("confs")
    if confs is None:
        raise ValueError(
            "Not a Java MultiLayerConfiguration JSON (no 'confs' key); "
            "ComputationGraph-format zips are not supported yet")
    layers, slicers, nodes = [], [], []
    seed = 0
    for entry in confs:
        seed = int(entry.get("seed", seed))
        layer_node = entry["layer"]
        if "@class" in layer_node:  # beta4+-era Id.CLASS layer tags
            jclass = layer_node["@class"].rsplit(".", 1)[-1]
            by_class = {"DenseLayer": "dense", "OutputLayer": "output",
                        "ConvolutionLayer": "convolution",
                        "SubsamplingLayer": "subsampling",
                        "BatchNormalization": "batchNormalization",
                        "LSTM": "LSTM", "EmbeddingLayer": "embedding",
                        "RnnOutputLayer": "rnnoutput",
                        "ActivationLayer": "activation",
                        "DropoutLayer": "dropout", "LossLayer": "loss"}
            if jclass not in by_class:
                raise ValueError(f"Unsupported Java layer class {jclass!r}")
            name, node = by_class[jclass], layer_node
        else:  # WRAPPER_OBJECT form: {"dense": {...}}
            (name, node), = layer_node.items()
        if name not in _LAYER_BUILDERS:
            raise ValueError(
                f"Unsupported Java layer type {name!r}; supported: "
                f"{sorted(_LAYER_BUILDERS)}")
        layer, slicer = _LAYER_BUILDERS[name](node)
        layers.append(layer)
        slicers.append(slicer)
        nodes.append(node)

    preprocessors = {}
    for k, v in (root.get("inputPreProcessors") or {}).items():
        (pname, pnode), = v.items()
        if pname in _PREPROCESSOR_BUILDERS:
            preprocessors[int(k)] = _PREPROCESSOR_BUILDERS[pname](pnode)

    conf = MultiLayerConfiguration(
        global_conf=GlobalConf(seed=seed),
        layers=layers,
        preprocessors=preprocessors or None,
        backprop_type=("tbptt" if root.get("backpropType") == "TruncatedBPTT"
                       else "standard"),
        tbptt_fwd_length=int(root.get("tbpttFwdLength", 20)),
        tbptt_back_length=int(root.get("tbpttBackLength", 20)),
    )
    return conf, slicers, nodes


def _cnn_flatten_perm(h: int, w: int, c: int) -> np.ndarray:
    """Row permutation between the two CNN-flatten conventions at a
    cnnToFeedForward boundary: Java's preprocessor flattens NCHW
    (channel-major); this package flattens NHWC. ``perm[j_ours] =
    j_java`` so ``W_ours = W_java[perm]`` makes the loaded dense layer
    consume our flatten order while computing the Java result."""
    idx = np.arange(h * w * c)
    h_i = idx // (w * c)
    w_i = (idx % (w * c)) // c
    c_i = idx % c
    return c_i * (h * w) + h_i * w + w_i


def _infer_input_type(conf, nodes):
    from deeplearning4j_tpu.nn.conf import InputType

    first = conf.layers[0]
    pp0 = conf.preprocessors.get(0)
    if pp0 is not None and type(pp0).__name__ == "FeedForwardToCnnPreProcessor":
        return InputType.feed_forward(pp0.height * pp0.width * pp0.channels)
    kind = type(first).__name__
    n_in = getattr(first, "n_in", None)
    if kind in ("ConvolutionLayer", "SubsamplingLayer"):
        return None  # image H/W not recorded in the Java JSON
    if kind in ("LSTM", "GravesLSTM", "SimpleRnn", "RnnOutputLayer"):
        return InputType.recurrent(n_in) if n_in else None
    if n_in:
        return InputType.feed_forward(n_in)
    return None


def restore_java_multi_layer_network(path: str, input_type=None):
    """Load a model zip produced by the *Java* stack's
    ``ModelSerializer.writeModel`` into a MultiLayerNetwork.

    ``input_type``: required for CNNs whose input H/W the Java JSON does
    not record (it resolves them into nIn at build time); inferred for
    feed-forward / recurrent stacks.
    """
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path, "r") as z:
        names = set(z.namelist())
        if "configuration.json" not in names:
            raise ValueError(f"{path}: no configuration.json entry")
        conf_json = z.read("configuration.json").decode("utf-8")
        conf, slicers, nodes = load_java_configuration(conf_json)
        if input_type is None:
            input_type = _infer_input_type(conf, nodes)
        if input_type is None:
            raise ValueError(
                "Pass input_type=InputType.convolutional(h, w, c): the "
                "Java JSON does not record image dimensions")
        conf.input_type = input_type
        # the builder's build() normally runs this chain; the loader
        # constructed MultiLayerConfiguration directly
        for layer in conf.layers:
            layer.inherit_defaults(conf.global_conf)
        ct = input_type
        for i, layer in enumerate(conf.layers):
            if i in conf.preprocessors:
                ct = conf.preprocessors[i].get_output_type(ct)
            layer.initialize(ct)
            ct = layer.get_output_type(ct)
        net = MultiLayerNetwork(conf).init()

        if "coefficients.bin" in names and "noParams.marker" not in names:
            with z.open("coefficients.bin") as f:
                flat = nd4j_bin.read_array(io.BytesIO(f.read()))
            flat = np.asarray(flat, np.float32).reshape(-1)
            pos = 0
            for i, slicer in enumerate(slicers):
                if slicer is None:
                    continue
                params: Dict[str, np.ndarray] = {}
                state: Dict[str, np.ndarray] = {}
                pos = slicer(flat, pos, params, state)
                pp = conf.preprocessors.get(i)
                if (pp is not None and "W" in params
                        and type(pp).__name__ ==
                        "CnnToFeedForwardPreProcessor"
                        and params["W"].ndim == 2
                        and params["W"].shape[0]
                        == pp.height * pp.width * pp.channels):
                    perm = _cnn_flatten_perm(pp.height, pp.width,
                                             pp.channels)
                    params["W"] = params["W"][perm]
                import jax.numpy as jnp

                for k, v in params.items():
                    net.params_[i][k] = jnp.asarray(v, jnp.float32)
                for k, v in state.items():
                    net.state_[i][k] = jnp.asarray(v, jnp.float32)
            if pos != flat.size:
                raise ValueError(
                    f"coefficients.bin has {flat.size} values; layer "
                    f"layout consumed {pos} — layer/format mismatch")
    return net


# ----------------------------------------------------------------------
# export (the reverse direction: write a zip the Java stack can read)
# ----------------------------------------------------------------------

def _export_layer(layer, params, state
                  ) -> List[Tuple[str, dict, List[np.ndarray]]]:
    """our Layer → [(java type name, java JSON node, flat param chunks in
    the Java view order), ...]. Usually one entry; BatchNormalization
    with a fused activation expands to TWO Java layers (BN + activation)
    because the Java BN runtime ignores its activationFn
    (nn/layers/normalization/BatchNormalization.java:225-226)."""
    from deeplearning4j_tpu.nn.conf import layers as L

    def act(name):
        if name not in _ACTIVATION_EXPORT:
            raise ValueError(f"No Java activation for {name!r}")
        return {"@class": _ACT_PKG + _ACTIVATION_EXPORT[name]}

    def base(node):
        if layer.name:
            node["layerName"] = layer.name
        if getattr(layer, "updater", None) is not None:
            try:
                node["iUpdater"] = _export_updater(layer.updater)
            except ValueError:
                pass
        reg = getattr(layer, "regularization", None)
        if reg is not None:
            node["l1"], node["l2"] = reg.l1, reg.l2
            node["l1Bias"], node["l2Bias"] = reg.l1_bias, reg.l2_bias
        return node

    t = type(layer).__name__
    if t in ("DenseLayer", "OutputLayer", "RnnOutputLayer"):
        node = base({
            "nIn": layer.n_in, "nOut": layer.n_out,
            "activationFn": act(layer.activation),
            "weightInit": str(layer.weight_init).upper()
            if isinstance(layer.weight_init, str) else "XAVIER",
        })
        if t != "DenseLayer":
            loss = getattr(layer, "loss", "mcxent")
            if loss not in _LOSS_EXPORT:
                raise ValueError(f"No Java loss for {loss!r}")
            node["lossFn"] = {"@class": _LOSS_PKG + _LOSS_EXPORT[loss]}
        w = np.asarray(params["W"], np.float32)
        b = np.asarray(params["b"], np.float32)
        chunks = [w.reshape(-1, order="F"), b.reshape(-1)]
        name = {"DenseLayer": "dense", "OutputLayer": "output",
                "RnnOutputLayer": "rnnoutput"}[t]
        return [(name, node, chunks)]
    if t == "ConvolutionLayer":
        node = base({
            "nIn": layer.n_in, "nOut": layer.n_out,
            "activationFn": act(layer.activation),
            "weightInit": str(layer.weight_init).upper()
            if isinstance(layer.weight_init, str) else "XAVIER",
            "kernelSize": list(layer.kernel_size),
            "stride": list(layer.stride),
            "padding": list(layer.padding),
            "dilation": list(layer.dilation),
            "convolutionMode": layer.convolution_mode.capitalize(),
            "hasBias": layer.has_bias,
        })
        w = np.asarray(params["W"], np.float32)  # HWIO
        w_oihw = np.transpose(w, (3, 2, 0, 1))
        chunks = []
        if layer.has_bias:
            chunks.append(np.asarray(params["b"], np.float32).reshape(-1))
        chunks.append(w_oihw.reshape(-1, order="C"))
        return [("convolution", node, chunks)]
    if t == "SubsamplingLayer":
        node = base({
            "poolingType": layer.pooling_type.upper(),
            "kernelSize": list(layer.kernel_size),
            "stride": list(layer.stride),
            "padding": list(layer.padding),
            "convolutionMode": layer.convolution_mode.capitalize(),
            "pnorm": layer.pnorm,
        })
        return [("subsampling", node, [])]
    if t == "BatchNormalization":
        node = base({
            "nIn": layer.n_feat, "nOut": layer.n_feat,
            "decay": layer.decay, "eps": layer.eps,
            "gamma": layer.gamma, "beta": layer.beta,
            "lockGammaBeta": layer.lock_gamma_beta,
        })
        chunks = []
        if not layer.lock_gamma_beta:
            chunks.append(np.asarray(params["gamma"], np.float32))
            chunks.append(np.asarray(params["beta"], np.float32))
        chunks.append(np.asarray(state["mean"], np.float32))
        chunks.append(np.asarray(state["var"], np.float32))
        out = [("batchNormalization", node, chunks)]
        if layer.activation not in (None, "identity"):
            # Java BN ignores activationFn at runtime — emit an explicit
            # activation layer so the exported model computes the same fn
            out.append(("activation",
                        {"activationFn": act(layer.activation)}, []))
        return out
    if t == "LSTM":
        node = base({
            "nIn": layer.n_in, "nOut": layer.n_out,
            "activationFn": act(layer.activation),
            "gateActivationFn": act(layer.gate_activation),
            "forgetGateBiasInit": layer.forget_gate_bias_init,
            "weightInit": str(layer.weight_init).upper()
            if isinstance(layer.weight_init, str) else "XAVIER",
        })
        chunks = [
            np.asarray(params["Wx"], np.float32).reshape(-1, order="F"),
            np.asarray(params["Wh"], np.float32).reshape(-1, order="F"),
            np.asarray(params["b"], np.float32).reshape(-1),
        ]
        return [("LSTM", node, chunks)]
    if t == "ActivationLayer":
        return [("activation",
                 base({"activationFn": act(layer.activation)}), [])]
    raise ValueError(f"No Java export mapping for layer {t}")


def write_java_model(net, path: str) -> None:
    """Export a MultiLayerNetwork as a Java-stack-format model zip
    (``configuration.json`` Jackson schema + ``coefficients.bin``
    ``Nd4j.write`` stream) — the reverse interop direction."""
    confs = []
    chunks: List[np.ndarray] = []
    # exported index of each original layer — BN-with-activation expands
    # to two Java layers, shifting every later index (and the
    # inputPreProcessors keys, which are layer positions)
    exported_index: Dict[int, int] = {}
    for i, layer in enumerate(net.layers):
        params = net.params_[i]
        pp = (net.conf.preprocessors or {}).get(i)
        if (pp is not None and "W" in params
                and type(pp).__name__ == "CnnToFeedForwardPreProcessor"):
            w = np.asarray(params["W"], np.float32)
            if w.ndim == 2 and \
                    w.shape[0] == pp.height * pp.width * pp.channels:
                perm = _cnn_flatten_perm(pp.height, pp.width, pp.channels)
                w_java = np.empty_like(w)
                w_java[perm] = w  # inverse of the import permutation
                params = dict(params)
                params["W"] = w_java
        exported_index[i] = len(confs)
        for name, node, layer_chunks in _export_layer(
                layer, params, net.state_[i]):
            confs.append({
                "layer": {name: node},
                "seed": net.conf.global_conf.seed,
                "miniBatch": True,
                "optimizationAlgo": "STOCHASTIC_GRADIENT_DESCENT",
                "minimize": True,
            })
            chunks.extend(layer_chunks)
    pps = {}
    for idx, pp in (net.conf.preprocessors or {}).items():
        t = type(pp).__name__
        jidx = str(exported_index[int(idx)])
        if t == "CnnToFeedForwardPreProcessor":
            pps[jidx] = {"cnnToFeedForward": {
                "inputHeight": pp.height, "inputWidth": pp.width,
                "numChannels": pp.channels}}
        elif t == "FeedForwardToCnnPreProcessor":
            pps[jidx] = {"feedForwardToCnn": {
                "inputHeight": pp.height, "inputWidth": pp.width,
                "numChannels": pp.channels}}
        elif t == "RnnToFeedForwardPreProcessor":
            pps[jidx] = {"rnnToFeedForward": {}}
        elif t == "FeedForwardToRnnPreProcessor":
            pps[jidx] = {"feedForwardToRnn": {}}
        elif t == "CnnToRnnPreProcessor":
            pps[jidx] = {"cnnToRnn": {}}
        else:
            raise ValueError(
                f"No Java export mapping for preprocessor {t} at layer "
                f"{idx} — refusing to silently drop it")
    root = {
        "backprop": True,
        "backpropType": ("TruncatedBPTT"
                         if net.conf.backprop_type == "tbptt"
                         else "Standard"),
        "tbpttFwdLength": net.conf.tbptt_fwd_length,
        "tbpttBackLength": net.conf.tbptt_back_length,
        "pretrain": False,
        "confs": confs,
    }
    if pps:
        root["inputPreProcessors"] = pps
    flat = (np.concatenate([c.reshape(-1) for c in chunks])
            if chunks else np.zeros((0,), np.float32))
    buf = io.BytesIO()
    # Java flattenedParams is a (1, N) row vector (MultiLayerNetwork.java:609)
    nd4j_bin.write_array(buf, flat.reshape(1, -1).astype(np.float32))
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("configuration.json", json.dumps(root, indent=2))
        z.writestr("coefficients.bin", buf.getvalue())
