"""HDF5 archive reader (reference ``keras/Hdf5Archive.java:48-63``, which
uses JavaCPP-HDF5; here h5py — SURVEY.md §2.9.3's prescribed replacement).

Handles both layouts:
- Keras 2.x: ``model_weights/<layer>/<layer>/<weight>:0`` datasets
- Keras 3.x legacy h5: ``model_weights/<layer>/<model>/<layer>/<weight>``
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

try:
    import h5py
except ImportError:  # pragma: no cover - h5py is in the baked image
    h5py = None


def _decode(v):
    return v.decode() if isinstance(v, bytes) else v


class Hdf5Archive:
    def __init__(self, path: str):
        if h5py is None:
            raise ImportError("h5py is required for Keras model import")
        self.path = path
        self._f = h5py.File(path, "r")

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # ------------------------------------------------------------- config
    def model_config(self) -> dict:
        raw = self._f.attrs.get("model_config")
        if raw is None:
            raise ValueError(
                f"{self.path} has no 'model_config' attribute — not a Keras "
                "full-model HDF5 (weights-only files are not importable "
                "without the architecture; same restriction as the reference)"
            )
        return json.loads(_decode(raw))

    def training_config(self) -> Optional[dict]:
        raw = self._f.attrs.get("training_config")
        return None if raw is None else json.loads(_decode(raw))

    def keras_version(self) -> str:
        for holder in (self._f.attrs, self._weights_group().attrs):
            v = holder.get("keras_version")
            if v is not None:
                return _decode(v)
        return "unknown"

    # ------------------------------------------------------------ weights
    def _weights_group(self):
        if "model_weights" in self._f:
            return self._f["model_weights"]
        return self._f  # weights-only files store layers at the root

    def layer_names(self) -> List[str]:
        g = self._weights_group()
        names = g.attrs.get("layer_names")
        if names is not None:
            return [_decode(n) for n in names]
        return list(g.keys())

    def layer_weights(self, layer_name: str) -> Dict[str, np.ndarray]:
        """All datasets under the layer's group, keyed by their full path
        relative to the group (slashes preserved, ':0' suffixes stripped).
        Callers match on trailing path components (``kernel``, ``bias``,
        ``forward_lstm/.../kernel`` …)."""
        g = self._weights_group()
        if layer_name not in g:
            return {}
        out: Dict[str, np.ndarray] = {}

        def walk(group, prefix: str):
            for k in group:
                item = group[k]
                key = f"{prefix}{k}"
                if isinstance(item, h5py.Dataset):
                    out[key.split(":")[0]] = np.asarray(item)
                else:
                    walk(item, key + "/")

        walk(g[layer_name], "")
        return out


def pick(weights: Dict[str, np.ndarray], *suffixes: str,
         contains: Optional[str] = None) -> Optional[np.ndarray]:
    """Find the unique weight whose path ends with one of ``suffixes``
    (optionally also containing ``contains``). None if absent."""
    for suffix in suffixes:
        hits = [
            k for k in weights
            if (k == suffix or k.endswith("/" + suffix))
            and (contains is None or contains in k)
        ]
        if len(hits) == 1:
            return weights[hits[0]]
        if len(hits) > 1:
            raise ValueError(f"Ambiguous weight '{suffix}' (contains={contains}): {hits}")
    return None
