"""Keras layer-config → framework-layer mappers + weight translators.

Reference: the ~35 ``KerasLayer`` subclasses under
``keras/layers/{core,convolutional,pooling,recurrent,embeddings,
normalization,noise,advanced/activations,wrappers}`` (SURVEY.md §2.6).
Here each Keras class name maps to one function returning a ``Mapped``
record: the equivalent layer/vertex of this framework plus a pure weight
translator (numpy in → params/state dicts out).

Weight-layout translation table (reference ``KerasModelUtils.importWeights``
``:170``; silent-accuracy-bug territory, SURVEY §7 hard-part 4):
- Dense kernel (in,out) → W (in,out): identity (both are right-multiply).
- Conv2D kernel HWIO → W HWIO: identity (NHWC native on TPU; the
  reference's NCHW permutation is *deleted*, not ported).
- DepthwiseConv2D kernel (kh,kw,in,mult) → W (kh,kw,1,in*mult): reshape
  (in-major interleave matches XLA's feature_group_count convention).
- Conv2DTranspose kernel (kh,kw,out,in) → W (kh,kw,out,in): identity.
- LSTM kernels (in,4u) gate order [i,f,g,o] → Wx gate order [i,f,o,g].
- BatchNorm moving_mean/moving_variance → layer *state*, not params.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.modelimport.keras.archive import pick
from deeplearning4j_tpu.nn.conf.graph_vertices import (
    ElementWiseVertex,
    GraphVertex,
    MergeVertex,
    PreprocessorVertex,
    ReshapeVertex,
)
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNormalization,
    Bidirectional,
    Convolution1DLayer,
    ConvolutionLayer,
    Cropping2D,
    Deconvolution2D,
    DenseLayer,
    DepthwiseConvolution2D,
    DropoutLayer,
    EmbeddingSequenceLayer,
    GlobalPoolingLayer,
    LastTimeStep,
    Layer,
    LocalResponseNormalization,
    LSTM,
    SeparableConvolution2D,
    SimpleRnn,
    SpaceToDepthLayer,
    Subsampling1DLayer,
    SubsamplingLayer,
    Upsampling1D,
    Upsampling2D,
    ZeroPadding1DLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.conf.preprocessors import CnnToFeedForwardPreProcessor

WeightTranslator = Callable[[Dict[str, np.ndarray]], Tuple[dict, dict]]

_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "relu6": "relu6", "elu": "elu",
    "selu": "selu", "gelu": "gelu", "tanh": "tanh", "sigmoid": "sigmoid",
    "hard_sigmoid": "hardsigmoid", "softmax": "softmax", "softplus": "softplus",
    "softsign": "softsign", "swish": "swish", "silu": "swish", "mish": "mish",
    "leaky_relu": "leakyrelu", "exponential": None, "log_softmax": "logsoftmax",
}


class UnsupportedKerasLayer(ValueError):
    pass


def map_activation(name) -> str:
    if name is None:
        return "identity"
    if isinstance(name, dict):  # serialized Activation object
        name = name.get("class_name", "").lower()
    mapped = _ACTIVATIONS.get(name)
    if mapped is None and name not in _ACTIVATIONS:
        raise UnsupportedKerasLayer(f"Unsupported Keras activation '{name}'")
    if mapped is None:
        raise UnsupportedKerasLayer(f"Keras activation '{name}' has no equivalent")
    return mapped


class Mapped:
    """One Keras layer's translation: ``layer`` XOR ``vertex`` XOR skip."""

    def __init__(
        self,
        layer: Optional[Layer] = None,
        vertex: Optional[GraphVertex] = None,
        skip: bool = False,
        translator: Optional[WeightTranslator] = None,
        is_flatten: bool = False,
    ):
        self.layer = layer
        self.vertex = vertex
        self.skip = skip
        self.translator = translator
        self.is_flatten = is_flatten


def _pair(v):
    if isinstance(v, (list, tuple)):
        return [int(v[0]), int(v[1] if len(v) > 1 else v[0])]
    return [int(v), int(v)]


def _check_channels_last(cfg: dict, name: str):
    df = cfg.get("data_format", "channels_last")
    if df != "channels_last":
        raise UnsupportedKerasLayer(
            f"Layer '{name}': data_format={df} not supported — this import "
            "targets channels_last (NHWC is the TPU-native layout; convert "
            "the model with Keras before exporting)"
        )


def _conv_mode(cfg: dict) -> str:
    pad = cfg.get("padding", "valid")
    if pad == "same":
        return "same"
    if pad in ("valid", "causal"):
        if pad == "causal":
            raise UnsupportedKerasLayer("causal conv padding not supported")
        return "truncate"
    raise UnsupportedKerasLayer(f"Unknown Keras padding {pad!r}")


def _dense_tr(n_out: int) -> WeightTranslator:
    def tr(w):
        kernel = pick(w, "kernel")
        bias = pick(w, "bias")
        return {
            "W": np.asarray(kernel, np.float32),
            "b": np.zeros((n_out,), np.float32) if bias is None
            else np.asarray(bias, np.float32),
        }, {}

    return tr


# ------------------------------------------------------------------ core
def _map_dense(cfg: dict) -> Mapped:
    units = int(cfg["units"])
    # use_bias=False imports as a zero bias (DenseLayer always carries b)
    layer = DenseLayer(
        n_out=units,
        activation=map_activation(cfg.get("activation", "linear")),
    )
    return Mapped(layer=layer, translator=_dense_tr(units))


def _map_activation_layer(cfg: dict) -> Mapped:
    return Mapped(layer=ActivationLayer(activation=map_activation(cfg.get("activation"))))


def _map_relu_layer(cfg: dict) -> Mapped:
    # keras.layers.ReLU with optional max_value (ReLU6) / negative_slope
    ns = float(cfg.get("negative_slope", 0.0) or 0.0)
    th = float(cfg.get("threshold", 0.0) or 0.0)
    mv = cfg.get("max_value")
    if th != 0.0:
        raise UnsupportedKerasLayer(f"ReLU threshold={th} unsupported")
    if mv is not None and ns != 0.0:
        raise UnsupportedKerasLayer("ReLU with both max_value and negative_slope")
    if mv is not None:
        if abs(float(mv) - 6.0) > 1e-6:
            raise UnsupportedKerasLayer(f"ReLU max_value={mv} unsupported (only 6)")
        return Mapped(layer=ActivationLayer(activation="relu6"))
    if ns != 0.0:
        return Mapped(layer=ActivationLayer(activation=f"leakyrelu({ns})"))
    return Mapped(layer=ActivationLayer(activation="relu"))


def _map_leaky_relu(cfg: dict) -> Mapped:
    # Keras 2: alpha (default 0.3); Keras 3: negative_slope
    alpha = cfg.get("negative_slope", cfg.get("alpha", 0.3))
    return Mapped(layer=ActivationLayer(activation=f"leakyrelu({float(alpha)})"))


def _map_dropout(cfg: dict) -> Mapped:
    return Mapped(layer=DropoutLayer(dropout=float(cfg.get("rate", 0.5))))


def _map_flatten(cfg: dict) -> Mapped:
    # NHWC C-order flatten == CnnToFeedForwardPreProcessor's reshape; in a
    # sequential net the builder infers the preprocessor, in a graph a
    # PreprocessorVertex carries it.
    return Mapped(
        vertex=PreprocessorVertex(CnnToFeedForwardPreProcessor()),
        skip=True, is_flatten=True,
    )


def _map_reshape(cfg: dict) -> Mapped:
    shape = [int(s) for s in cfg["target_shape"]]
    return Mapped(vertex=ReshapeVertex([-1] + shape))


# ------------------------------------------------------------- conv family
def _map_conv2d(cfg: dict) -> Mapped:
    _check_channels_last(cfg, cfg.get("name", "conv2d"))
    filters = int(cfg["filters"])
    layer = ConvolutionLayer(
        n_out=filters,
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        convolution_mode=_conv_mode(cfg),
        activation=map_activation(cfg.get("activation", "linear")),
        has_bias=cfg.get("use_bias", True),
    )

    def tr(w):
        p = {"W": np.asarray(pick(w, "kernel"), np.float32)}
        if layer.has_bias:
            b = pick(w, "bias")
            p["b"] = (np.zeros((filters,), np.float32) if b is None
                      else np.asarray(b, np.float32))
        return p, {}

    return Mapped(layer=layer, translator=tr)


def _map_conv1d(cfg: dict) -> Mapped:
    filters = int(cfg["filters"])
    layer = Convolution1DLayer(
        n_out=filters,
        kernel_size=int(_pair(cfg["kernel_size"])[0]),
        stride=int(_pair(cfg.get("strides", 1))[0]),
        dilation=int(_pair(cfg.get("dilation_rate", 1))[0]),
        convolution_mode=_conv_mode(cfg),
        activation=map_activation(cfg.get("activation", "linear")),
        has_bias=cfg.get("use_bias", True),
    )

    def tr(w):
        # Keras Conv1D kernel (k, in, out) == Convolution1DLayer W layout
        # (WIO, conv.py init_params) — identity translation
        p = {"W": np.asarray(pick(w, "kernel"), np.float32)}
        if layer.has_bias:
            b = pick(w, "bias")
            p["b"] = (np.zeros((filters,), np.float32) if b is None
                      else np.asarray(b, np.float32))
        return p, {}

    return Mapped(layer=layer, translator=tr)


def _map_depthwise_conv2d(cfg: dict) -> Mapped:
    _check_channels_last(cfg, cfg.get("name", "dw"))
    mult = int(cfg.get("depth_multiplier", 1))
    layer = DepthwiseConvolution2D(
        depth_multiplier=mult,
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg),
        activation=map_activation(cfg.get("activation", "linear")),
        has_bias=cfg.get("use_bias", True),
    )

    def tr(w):
        k = np.asarray(
            pick(w, "depthwise_kernel", "kernel"), np.float32
        )  # (kh,kw,in,mult)
        kh, kw, cin, m = k.shape
        p = {"W": k.reshape(kh, kw, 1, cin * m)}
        if layer.has_bias:
            b = pick(w, "bias")
            p["b"] = (np.zeros((cin * m,), np.float32) if b is None
                      else np.asarray(b, np.float32))
        return p, {}

    return Mapped(layer=layer, translator=tr)


def _map_separable_conv2d(cfg: dict) -> Mapped:
    _check_channels_last(cfg, cfg.get("name", "sep"))
    filters = int(cfg["filters"])
    layer = SeparableConvolution2D(
        n_out=filters,
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg),
        activation=map_activation(cfg.get("activation", "linear")),
        has_bias=cfg.get("use_bias", True),
    )

    def tr(w):
        dk = np.asarray(pick(w, "depthwise_kernel"), np.float32)
        pk = np.asarray(pick(w, "pointwise_kernel"), np.float32)
        kh, kw, cin, m = dk.shape
        p = {"dW": dk.reshape(kh, kw, 1, cin * m), "pW": pk}
        if layer.has_bias:
            b = pick(w, "bias")
            p["b"] = (np.zeros((filters,), np.float32) if b is None
                      else np.asarray(b, np.float32))
        return p, {}

    return Mapped(layer=layer, translator=tr)


def _map_conv2d_transpose(cfg: dict) -> Mapped:
    _check_channels_last(cfg, cfg.get("name", "deconv"))
    filters = int(cfg["filters"])
    layer = Deconvolution2D(
        n_out=filters,
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg),
        activation=map_activation(cfg.get("activation", "linear")),
        has_bias=cfg.get("use_bias", True),
    )

    def tr(w):
        p = {"W": np.asarray(pick(w, "kernel"), np.float32)}  # (kh,kw,out,in)
        if layer.has_bias:
            b = pick(w, "bias")
            p["b"] = (np.zeros((filters,), np.float32) if b is None
                      else np.asarray(b, np.float32))
        return p, {}

    return Mapped(layer=layer, translator=tr)


# ------------------------------------------------------------ pool family
def _map_pool2d(cfg: dict, pooling_type: str) -> Mapped:
    _check_channels_last(cfg, cfg.get("name", "pool"))
    return Mapped(layer=SubsamplingLayer(
        pooling_type=pooling_type,
        kernel_size=_pair(cfg.get("pool_size", 2)),
        stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
        convolution_mode=_conv_mode(cfg),
    ))


def _map_pool1d(cfg: dict, pooling_type: str) -> Mapped:
    size = cfg.get("pool_size", 2)
    size = int(size[0] if isinstance(size, (list, tuple)) else size)
    strides = cfg.get("strides") or size
    strides = int(strides[0] if isinstance(strides, (list, tuple)) else strides)
    return Mapped(layer=Subsampling1DLayer(
        pooling_type=pooling_type, kernel_size=size, stride=strides,
        convolution_mode=_conv_mode(cfg),
    ))


def _map_global_pool(cfg: dict, pooling_type: str) -> Mapped:
    if cfg.get("keepdims"):
        raise UnsupportedKerasLayer("GlobalPooling keepdims=True unsupported")
    return Mapped(layer=GlobalPoolingLayer(pooling_type=pooling_type))


# ----------------------------------------------------------------- norm
def _map_batchnorm(cfg: dict) -> Mapped:
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        axis = axis[0]
    # framework BN always normalizes the trailing (channel) axis; -1 is the
    # Keras 3 encoding, 3 the common Keras 2 channels_last rank-4 encoding
    if axis not in (-1, 3):
        raise UnsupportedKerasLayer(
            f"BatchNormalization axis={axis} unsupported (channels-last only)"
        )
    layer = BatchNormalization(
        eps=float(cfg.get("epsilon", 1e-3)),
        decay=float(cfg.get("momentum", 0.99)),
    )
    scale = cfg.get("scale", True)
    center = cfg.get("center", True)

    def tr(w):
        mean = pick(w, "moving_mean")
        var = pick(w, "moving_variance")
        n = mean.shape[0]
        gamma = pick(w, "gamma") if scale else None
        beta = pick(w, "beta") if center else None
        params = {
            "gamma": np.ones((n,), np.float32) if gamma is None
            else np.asarray(gamma, np.float32),
            "beta": np.zeros((n,), np.float32) if beta is None
            else np.asarray(beta, np.float32),
        }
        state = {"mean": np.asarray(mean, np.float32),
                 "var": np.asarray(var, np.float32)}
        return params, state

    return Mapped(layer=layer, translator=tr)


def _map_lrn(cfg: dict) -> Mapped:
    """Local response normalization (reference ``KerasLRN.java`` — the
    keras-contrib/Keras-1 ``LRN``/``LRN2D`` layer): alpha/beta/k/n map
     1:1 onto LocalResponseNormalization; the across-channel window form
    ``x / (k + alpha·Σx²)^beta`` matches tf.nn.local_response_normalization
    with ``depth_radius = n//2`` (n odd)."""
    return Mapped(layer=LocalResponseNormalization(
        k=float(cfg.get("k", 2.0)),
        n=float(cfg.get("n", 5.0)),
        alpha=float(cfg.get("alpha", 1e-4)),
        beta=float(cfg.get("beta", 0.75)),
    ))


def _map_space_to_depth(cfg: dict) -> Mapped:
    """Space-to-depth / YOLO2 "reorg" (reference
    ``KerasSpaceToDepth.java``, which hardcodes blocks=2 for the YOLO2
    import path; the block size is honoured here when present)."""
    _check_channels_last(cfg, cfg.get("name", "space_to_depth"))
    block = int(cfg.get("block_size", cfg.get("blocks", 2)))
    return Mapped(layer=SpaceToDepthLayer(block_size=block))


def _keras1_conv_cfg(cfg: dict, rank: int) -> dict:
    """Normalize Keras-1 conv config keys (``nb_filter``/``nb_row``/
    ``nb_col``/``subsample``/``atrous_rate``/``border_mode``) to the
    Keras-2 names the conv mappers read. Keras-2-style configs pass
    through untouched (legacy class name, modern serialization)."""
    if "filters" in cfg:
        return cfg
    out = dict(cfg)
    out["filters"] = cfg["nb_filter"]
    if rank == 1:
        out["kernel_size"] = [int(cfg["filter_length"])]
        out["strides"] = [int(cfg.get("subsample_length", 1))]
        rate = cfg.get("atrous_rate", 1)
        out["dilation_rate"] = [int(rate)]
    else:
        out["kernel_size"] = [int(cfg["nb_row"]), int(cfg["nb_col"])]
        out["strides"] = _pair(cfg.get("subsample", 1))
        out["dilation_rate"] = _pair(cfg.get("atrous_rate", 1))
    if "border_mode" in cfg:
        out["padding"] = cfg["border_mode"]
    return out


def _map_atrous_conv1d(cfg: dict) -> Mapped:
    """Dilated conv, Keras-1 ``AtrousConvolution1D`` (reference
    ``KerasAtrousConvolution1D.java``); Convolution1DLayer carries the
    dilation directly."""
    return _map_conv1d(_keras1_conv_cfg(cfg, 1))


def _map_atrous_conv2d(cfg: dict) -> Mapped:
    """Dilated conv, Keras-1 ``AtrousConvolution2D`` (reference
    ``KerasAtrousConvolution2D.java``)."""
    return _map_conv2d(_keras1_conv_cfg(cfg, 2))


# ------------------------------------------------------------- pad / crop
def _map_zeropad2d(cfg: dict) -> Mapped:
    _check_channels_last(cfg, cfg.get("name", "pad"))
    p = cfg.get("padding", 1)
    if isinstance(p, int):
        pad = [p, p, p, p]
    else:
        (t, b), (l, r) = [_pair(q) for q in p]
        pad = [t, b, l, r]
    return Mapped(layer=ZeroPaddingLayer(pad=pad))


def _map_zeropad1d(cfg: dict) -> Mapped:
    p = cfg.get("padding", 1)
    pad = _pair(p)
    return Mapped(layer=ZeroPadding1DLayer(pad=pad))


def _map_cropping2d(cfg: dict) -> Mapped:
    _check_channels_last(cfg, cfg.get("name", "crop"))
    c = cfg.get("cropping", 0)
    if isinstance(c, int):
        crop = [c, c, c, c]
    else:
        (t, b), (l, r) = [_pair(q) for q in c]
        crop = [t, b, l, r]
    return Mapped(layer=Cropping2D(crop=crop))


def _map_upsampling2d(cfg: dict) -> Mapped:
    _check_channels_last(cfg, cfg.get("name", "up"))
    if cfg.get("interpolation", "nearest") != "nearest":
        raise UnsupportedKerasLayer("UpSampling2D interpolation != nearest")
    return Mapped(layer=Upsampling2D(size=_pair(cfg.get("size", 2))))


def _map_upsampling1d(cfg: dict) -> Mapped:
    size = cfg.get("size", 2)
    return Mapped(layer=Upsampling1D(size=int(size)))


# ------------------------------------------------------------- recurrent
def _lstm_reorder(k: np.ndarray) -> np.ndarray:
    """Keras gate order [i,f,g,o] → framework order [i,f,o,g] (last axis)."""
    u = k.shape[-1] // 4
    i, f, g, o = (k[..., j * u:(j + 1) * u] for j in range(4))
    return np.concatenate([i, f, o, g], axis=-1)


def _lstm_tr(prefix: Optional[str] = None) -> WeightTranslator:
    def tr(w):
        kernel = pick(w, "kernel", contains=prefix)
        rec = pick(w, "recurrent_kernel", contains=prefix)
        bias = pick(w, "bias", contains=prefix)
        p = {
            "Wx": _lstm_reorder(np.asarray(kernel, np.float32)),
            "Wh": _lstm_reorder(np.asarray(rec, np.float32)),
        }
        p["b"] = (
            np.zeros((kernel.shape[-1],), np.float32) if bias is None
            else _lstm_reorder(np.asarray(bias, np.float32))
        )
        return p, {}

    return tr


def _build_lstm(cfg: dict) -> LSTM:
    return LSTM(
        n_out=int(cfg["units"]),
        activation=map_activation(cfg.get("activation", "tanh")),
        gate_activation=map_activation(cfg.get("recurrent_activation", "sigmoid")),
    )


def _map_lstm(cfg: dict) -> Mapped:
    if cfg.get("go_backwards"):
        raise UnsupportedKerasLayer("LSTM go_backwards=True unsupported")
    inner = _build_lstm(cfg)
    layer: Layer = inner
    if not cfg.get("return_sequences", False):
        layer = LastTimeStep(inner)
    return Mapped(layer=layer, translator=_lstm_tr())


def _map_simple_rnn(cfg: dict) -> Mapped:
    if cfg.get("go_backwards"):
        raise UnsupportedKerasLayer("SimpleRNN go_backwards=True unsupported")
    inner = SimpleRnn(
        n_out=int(cfg["units"]),
        activation=map_activation(cfg.get("activation", "tanh")),
    )
    layer: Layer = inner
    if not cfg.get("return_sequences", False):
        layer = LastTimeStep(inner)

    def tr(w):
        return {
            "Wx": np.asarray(pick(w, "kernel"), np.float32),
            "Wh": np.asarray(pick(w, "recurrent_kernel"), np.float32),
            "b": np.asarray(pick(w, "bias"), np.float32)
            if pick(w, "bias") is not None
            else np.zeros((int(cfg["units"]),), np.float32),
        }, {}

    return Mapped(layer=layer, translator=tr)


def _map_bidirectional(cfg: dict) -> Mapped:
    inner_cfg = cfg["layer"]
    inner_class = inner_cfg["class_name"]
    ic = inner_cfg["config"]
    if inner_class != "LSTM":
        raise UnsupportedKerasLayer(f"Bidirectional({inner_class}) unsupported")
    if not ic.get("return_sequences", False):
        raise UnsupportedKerasLayer(
            "Bidirectional(return_sequences=False) unsupported"
        )
    merge = {"concat": "concat", "sum": "add", "mul": "mul", "ave": "ave"}.get(
        cfg.get("merge_mode", "concat")
    )
    if merge is None:
        raise UnsupportedKerasLayer(f"merge_mode={cfg.get('merge_mode')} unsupported")
    layer = Bidirectional(_build_lstm(ic), mode=merge)
    fwd_tr, bwd_tr = _lstm_tr("forward"), _lstm_tr("backward")

    def tr(w):
        fp, _ = fwd_tr(w)
        bp, _ = bwd_tr(w)
        return {"fwd": fp, "bwd": bp}, {}

    return Mapped(layer=layer, translator=tr)


def _map_embedding(cfg: dict) -> Mapped:
    vocab, dim = int(cfg["input_dim"]), int(cfg["output_dim"])
    layer = EmbeddingSequenceLayer(
        n_in=vocab, n_out=dim, has_bias=False, activation="identity"
    )

    def tr(w):
        emb = pick(w, "embeddings", "kernel")
        return {"W": np.asarray(emb, np.float32)}, {}

    return Mapped(layer=layer, translator=tr)


# ----------------------------------------------------------------- merges
def _map_merge_concat(cfg: dict) -> Mapped:
    axis = cfg.get("axis", -1)
    # axis=3 on NHWC 4D tensors IS the channel (last) axis — InceptionV3
    # and friends spell it explicitly. MergeVertex asserts rank 4 at
    # apply time for this case so a rank-5 axis=3 concat fails loudly
    # instead of silently merging the wrong axis.
    if axis not in (-1, None, 3):
        raise UnsupportedKerasLayer(f"Concatenate axis={axis} unsupported (only -1)")
    return Mapped(vertex=MergeVertex(require_rank=4 if axis == 3 else None))


def _map_merge(op: str) -> Callable[[dict], Mapped]:
    def f(cfg: dict) -> Mapped:
        return Mapped(vertex=ElementWiseVertex(op))

    return f


MAPPERS: Dict[str, Callable[[dict], Mapped]] = {
    "Dense": _map_dense,
    "Activation": _map_activation_layer,
    "ReLU": _map_relu_layer,
    "LeakyReLU": _map_leaky_relu,
    "ELU": lambda cfg: Mapped(layer=ActivationLayer(activation="elu")),
    "Softmax": lambda cfg: Mapped(layer=ActivationLayer(activation="softmax")),
    "ThresholdedReLU": lambda cfg: Mapped(
        layer=ActivationLayer(activation="thresholdedrelu")),
    "Dropout": _map_dropout,
    "SpatialDropout1D": _map_dropout,
    "SpatialDropout2D": _map_dropout,
    "Flatten": _map_flatten,
    "Reshape": _map_reshape,
    "Conv1D": _map_conv1d,
    "Convolution1D": _map_conv1d,
    "Conv2D": _map_conv2d,
    "Convolution2D": _map_conv2d,
    "DepthwiseConv2D": _map_depthwise_conv2d,
    "SeparableConv2D": _map_separable_conv2d,
    "SeparableConvolution2D": _map_separable_conv2d,
    "Conv2DTranspose": _map_conv2d_transpose,
    "Deconvolution2D": _map_conv2d_transpose,
    "MaxPooling2D": lambda cfg: _map_pool2d(cfg, "max"),
    "AveragePooling2D": lambda cfg: _map_pool2d(cfg, "avg"),
    "MaxPooling1D": lambda cfg: _map_pool1d(cfg, "max"),
    "AveragePooling1D": lambda cfg: _map_pool1d(cfg, "avg"),
    "GlobalMaxPooling2D": lambda cfg: _map_global_pool(cfg, "max"),
    "GlobalAveragePooling2D": lambda cfg: _map_global_pool(cfg, "avg"),
    "GlobalMaxPooling1D": lambda cfg: _map_global_pool(cfg, "max"),
    "GlobalAveragePooling1D": lambda cfg: _map_global_pool(cfg, "avg"),
    "BatchNormalization": _map_batchnorm,
    "LRN": _map_lrn,
    "LRN2D": _map_lrn,
    "LocalResponseNormalization": _map_lrn,
    "SpaceToDepth": _map_space_to_depth,
    "AtrousConvolution1D": _map_atrous_conv1d,
    "AtrousConvolution2D": _map_atrous_conv2d,
    "ZeroPadding2D": _map_zeropad2d,
    "ZeroPadding1D": _map_zeropad1d,
    "Cropping2D": _map_cropping2d,
    "UpSampling2D": _map_upsampling2d,
    "UpSampling1D": _map_upsampling1d,
    "LSTM": _map_lstm,
    "SimpleRNN": _map_simple_rnn,
    "Bidirectional": _map_bidirectional,
    "Embedding": _map_embedding,
    "Add": _map_merge("add"),
    "Subtract": _map_merge("subtract"),
    "Multiply": _map_merge("product"),
    "Average": _map_merge("average"),
    "Maximum": _map_merge("max"),
    "Concatenate": _map_merge_concat,
    "Merge": _map_merge_concat,
}


def map_keras_layer(class_name: str, cfg: dict) -> Mapped:
    # custom/contrib layers serialize as "package>ClassName" (Keras 3
    # registered_keras_serializable) — dispatch on the bare class name
    class_name = class_name.split(">")[-1]
    fn = MAPPERS.get(class_name)
    if fn is None:
        raise UnsupportedKerasLayer(
            f"No mapper for Keras layer class '{class_name}' "
            f"(supported: {sorted(MAPPERS)})"
        )
    return fn(cfg)
