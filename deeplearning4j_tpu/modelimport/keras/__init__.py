"""Keras HDF5 model import (reference ``keras/KerasModelImport.java:50-121``).

TPU-native design: instead of the reference's JavaCPP-HDF5 archive +
per-layer ``KerasLayer`` class hierarchy, this is an h5py reader + a flat
mapper registry (keras class name → builder of this framework's layer /
vertex + a weight translator). The imported model is an ordinary
MultiLayerNetwork / ComputationGraph whose whole forward is one jitted XLA
program — imported models get the same MXU/fusion treatment as native ones.
"""

from deeplearning4j_tpu.modelimport.keras.importer import KerasModelImport

__all__ = ["KerasModelImport"]
