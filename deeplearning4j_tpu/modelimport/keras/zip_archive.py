"""Keras 3 ``.keras`` zip archive reader.

Format (Keras 3 native): a zip holding ``config.json`` (architecture),
``metadata.json`` (keras_version) and ``model.weights.h5`` whose datasets
are POSITIONAL — ``layers/<name>/vars/<i>`` (and ``.../cell/vars/<i>``
for RNNs, ``forward_layer``/``backward_layer`` under Bidirectional).

This reader presents the same interface as ``Hdf5Archive`` and renames
positional vars back to canonical weight names (``kernel``,
``recurrent_kernel``, ``moving_variance`` …) so the existing name-based
weight translators (mappers.py) work unchanged. Naming tables follow each
layer's build order in Keras 3, adjusted by config flags (``use_bias``,
``center``/``scale``) since absent weights shift the positions.
"""

from __future__ import annotations

import io
import json
import re
import zipfile
from typing import Dict, List, Optional

import numpy as np


def _to_snake_case(name: str) -> str:
    """Keras 3's naming.to_snake_case (weights h5 groups are named from
    the layer CLASS, not the config name)."""
    name = re.sub(r"\W+", "", name)
    name = re.sub("(.)([A-Z][a-z]+)", r"\1_\2", name)
    return re.sub("([a-z])([A-Z])", r"\1_\2", name).lower()

try:
    import h5py
except ImportError:  # pragma: no cover
    h5py = None


def _var_names(class_name: str, cfg: dict) -> Optional[List[str]]:
    """Build-order weight names for one (sub)layer, config-adjusted."""
    use_bias = cfg.get("use_bias", True)

    def with_bias(names):
        return names + ["bias"] if use_bias else names

    if class_name in ("Dense", "Conv1D", "Conv2D", "Conv3D",
                      "Conv1DTranspose", "Conv2DTranspose", "EinsumDense"):
        return with_bias(["kernel"])
    if class_name == "DepthwiseConv2D":
        return with_bias(["depthwise_kernel"])
    if class_name == "SeparableConv2D":
        return with_bias(["depthwise_kernel", "pointwise_kernel"])
    if class_name == "Embedding":
        return ["embeddings"]
    if class_name == "BatchNormalization":
        names = []
        if cfg.get("scale", True):
            names.append("gamma")
        if cfg.get("center", True):
            names.append("beta")
        return names + ["moving_mean", "moving_variance"]
    if class_name == "LayerNormalization":
        names = []
        if cfg.get("scale", True):
            names.append("gamma")
        if cfg.get("center", True):
            names.append("beta")
        return names
    if class_name in ("LSTM", "GRU", "SimpleRNN", "LSTMCell", "GRUCell",
                      "SimpleRNNCell"):
        return with_bias(["kernel", "recurrent_kernel"])
    if class_name == "PReLU":
        return ["alpha"]
    return None  # parameter-free or unknown: keep positional names


class KerasZipArchive:
    """Same surface as Hdf5Archive, over the ``.keras`` zip format."""

    def __init__(self, path: str):
        if h5py is None:
            raise ImportError("h5py is required for Keras model import")
        self.path = path
        self._zf = zipfile.ZipFile(path, "r")
        self._config = json.loads(self._zf.read("config.json"))
        try:
            self._meta = json.loads(self._zf.read("metadata.json"))
        except KeyError:
            self._meta = {}
        self._h5 = h5py.File(io.BytesIO(self._zf.read("model.weights.h5")), "r")
        self._finish_init()

    def _finish_init(self):
        # layer name → (class_name, config) for var naming
        self._layer_info: Dict[str, tuple] = {}
        self._index_layers(self._config)
        # config layer name → h5 group name: the weights file names groups
        # by object path (snake_case(class), uniquified per model in layer
        # order), NOT by the config layer name
        self._h5_name: Dict[str, str] = {}
        layers_cfg = (self._config.get("config", {}) or {}).get("layers", [])
        counts: Dict[str, int] = {}
        for lc in layers_cfg:
            cls = lc.get("class_name", "")
            cname = (lc.get("config", {}) or {}).get("name")
            if cls == "InputLayer" or cname is None:
                continue
            base = _to_snake_case(cls)
            n = counts.get(base, 0)
            counts[base] = n + 1
            self._h5_name[cname] = base if n == 0 else f"{base}_{n}"

    def _index_layers(self, cfg: dict):
        if not isinstance(cfg, dict):
            return
        cls = cfg.get("class_name")
        conf = cfg.get("config", {})
        name = conf.get("name") if isinstance(conf, dict) else None
        if cls and name:
            self._layer_info[name] = (cls, conf)
        if isinstance(conf, dict):
            for key in ("layers",):
                for sub in conf.get(key, []) or []:
                    self._index_layers(sub)
            for key in ("layer", "forward_layer", "backward_layer", "cell"):
                if conf.get(key):
                    self._index_layers(conf[key])

    def close(self):
        self._h5.close()
        self._zf.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # ------------------------------------------------------------- config
    def model_config(self) -> dict:
        return self._config

    def training_config(self) -> Optional[dict]:
        # .keras stores compile config inside config.json
        cc = self._config.get("compile_config")
        if cc:
            return cc
        cfg = self._config.get("config", {})
        return cfg.get("compile_config") if isinstance(cfg, dict) else None

    def keras_version(self) -> str:
        return self._meta.get("keras_version", "3")

    # ------------------------------------------------------------ weights
    def layer_names(self) -> List[str]:
        g = self._h5.get("layers")
        return list(g.keys()) if g is not None else []

    def _rename(self, path_parts: List[str], idx: int) -> str:
        """Replace the trailing vars/<idx> with the canonical weight name
        for the owning (sub)layer."""
        owner = None
        # owning sublayer = last path component that names a known layer,
        # or a cell/ level (RNN cells hold the recurrent weights)
        for part in reversed(path_parts):
            if part == "cell":
                owner = ("LSTMCell", {})  # cell table: kernel/rec/bias
                # bias presence: inherit the parent RNN layer's use_bias
                for p2 in reversed(path_parts):
                    if p2 in self._layer_info:
                        owner = ("LSTMCell", self._layer_info[p2][1])
                        break
                break
            if part in self._layer_info:
                owner = self._layer_info[part]
                break
        names = _var_names(owner[0], owner[1]) if owner else None
        if names is not None and idx < len(names):
            return names[idx]
        return f"var_{idx}"

    def layer_weights(self, layer_name: str) -> Dict[str, np.ndarray]:
        g = self._h5.get("layers")
        if g is None:
            return {}
        # the class-order mapping is authoritative: a config name like
        # "dense_1" can COLLIDE with another layer's positional h5 group
        # name, so a direct hit is only trusted when no mapping exists
        h5_name = self._h5_name.get(layer_name)
        if h5_name is None and layer_name in g:
            h5_name = layer_name
        if h5_name is None or h5_name not in g:
            return {}
        orig = layer_name
        layer_name = h5_name
        out: Dict[str, np.ndarray] = {}

        def walk(group, parts: List[str]):
            for k in group:
                item = group[k]
                if isinstance(item, h5py.Dataset):
                    # path ...>/vars/<k>
                    if parts and parts[-1] == "vars":
                        # owner lookup uses the CONFIG name (layer_info key)
                        name = self._rename([orig] + parts, int(k))
                        prefix = "/".join(p for p in parts[:-1])
                        key = f"{prefix}/{name}" if prefix else name
                    else:
                        key = "/".join(parts + [k])
                    out[key] = np.asarray(item)
                else:
                    walk(item, parts + [k])

        walk(g[layer_name], [])
        return out


class JsonWeightsArchive(KerasZipArchive):
    """Architecture-JSON + weights-only ``.weights.h5`` pair (reference
    ``KerasModelImport.importKerasModelAndWeights(modelJson,
    weightsHdf5)``). Keras 3 ``save_weights`` uses the same positional
    ``layers/<name>/vars/<i>`` layout as the ``.keras`` zip, so all the
    renaming machinery is inherited."""

    def __init__(self, json_path: str, weights_path: str):
        if h5py is None:
            raise ImportError("h5py is required for Keras model import")
        self.path = f"{json_path}+{weights_path}"
        self._zf = None
        with open(json_path, "r", encoding="utf-8") as f:
            self._config = json.load(f)
        self._meta = {}
        self._h5 = h5py.File(weights_path, "r")
        if "layers" not in self._h5:
            # Keras 1/2 save_weights used a NAME-keyed root layout; only
            # the Keras 3 positional layout is supported here — failing
            # loudly beats importing a randomly-initialized net
            self._h5.close()
            raise ValueError(
                f"{weights_path}: no 'layers' group — not a Keras 3 "
                ".weights.h5 (Keras 1/2 weights-only files are not "
                "supported; re-save with Keras 3 or use a full-model file)"
            )
        self._finish_init()

    def close(self):
        self._h5.close()
