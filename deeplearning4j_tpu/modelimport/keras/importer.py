"""Keras model assembly + weight copy.

Reference entry points: ``keras/KerasModelImport.java:50-121``
(``importKerasSequentialModelAndWeights`` → MultiLayerNetwork,
``importKerasModelAndWeights`` → ComputationGraph);
assembly ``keras/KerasModel.java`` / ``KerasSequentialModel.java``;
weight copy ``utils/KerasModelUtils.importWeights:170``.

Handles Keras 2.x and Keras 3.x (legacy ``.h5``) full-model files.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.modelimport.keras.archive import Hdf5Archive
from deeplearning4j_tpu.modelimport.keras.zip_archive import KerasZipArchive
from deeplearning4j_tpu.modelimport.keras.mappers import (
    Mapped,
    UnsupportedKerasLayer,
    map_keras_layer,
)
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    LossLayer,
    OutputLayer,
    RnnOutputLayer,
)

_LOSS_BY_ACT = {"softmax": "mcxent", "sigmoid": "xent"}

_KERAS_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "l1", "mae": "l1",
}


def _input_type_for_shape(shape: Sequence[Optional[int]],
                          channels_first: bool = False) -> InputType:
    dims = [d for d in shape[1:]]
    if len(dims) == 3:
        if channels_first:  # (c, h, w) → NHWC type; user feeds NHWC
            return InputType.convolutional(dims[1], dims[2], dims[0])
        return InputType.convolutional(dims[0], dims[1], dims[2])
    if len(dims) == 2:
        if channels_first:  # temporal NCW: (c, steps) → (steps, c) runtime
            return InputType.recurrent(dims[0], dims[1])
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    raise UnsupportedKerasLayer(f"Unsupported Keras input shape {shape}")


def _detect_channels_first(layer_cfgs) -> bool:
    return any(
        (lc.get("config", {}) or {}).get("data_format") == "channels_first"
        for lc in layer_cfgs
    )


def _to_channels_last_cfg(lc: dict) -> dict:
    """Rewrite a layer config to channels_last for mapping: kernel/stride
    semantics are layout-independent in the NHWC runtime. BN's
    ``axis=1`` (the NCHW channel axis) becomes the last axis."""
    conf = dict(lc.get("config", {}))
    if conf.get("data_format") == "channels_first":
        conf["data_format"] = "channels_last"
    if lc.get("class_name") == "BatchNormalization":
        ax = conf.get("axis")
        if ax == 1 or ax == [1]:
            conf["axis"] = -1
    out = dict(lc)
    out["config"] = conf
    return out


def _chw_to_hwc_perm(h: int, w: int, c: int) -> "np.ndarray":
    """Row permutation taking a flatten-of-(c,h,w) ordered kernel to
    flatten-of-(h,w,c) ordering (the NHWC runtime's Flatten)."""
    idx = np.arange(c * h * w).reshape(c, h, w)     # keras NCHW flatten order
    return idx.transpose(1, 2, 0).reshape(-1)       # our NHWC flatten order


def _layer_input_shape(layer_cfg: dict) -> Optional[List[Optional[int]]]:
    cfg = layer_cfg.get("config", {})
    for key in ("batch_shape", "batch_input_shape"):
        if cfg.get(key) is not None:
            return list(cfg[key])
    return None


def _loss_from_training_config(tc: Optional[dict]) -> Optional[str]:
    if not tc:
        return None
    loss = tc.get("loss")
    if isinstance(loss, dict):  # per-output dict or serialized loss object
        loss = loss.get("class_name", None) or next(iter(loss.values()), None)
        if isinstance(loss, dict):
            loss = loss.get("class_name")
    if isinstance(loss, str):
        key = loss.lower()
        # Keras 3 serializes class names (CategoricalCrossentropy)
        key = {
            "categoricalcrossentropy": "categorical_crossentropy",
            "sparsecategoricalcrossentropy": "sparse_categorical_crossentropy",
            "binarycrossentropy": "binary_crossentropy",
            "meansquarederror": "mean_squared_error",
            "meanabsoluteerror": "mean_absolute_error",
        }.get(key, key)
        return _KERAS_LOSSES.get(key)
    return None


def _resolve_loss(loss_hint: Optional[str], activation: Optional[str],
                  default_loss: Optional[str], what: str) -> str:
    """Loss for an output head: explicit training_config first, then the
    canonical activation pairing, then the caller's default_loss —
    otherwise FAIL LOUDLY (a silent mse default on an uncompiled model is
    a training-correctness trap)."""
    loss = loss_hint or _LOSS_BY_ACT.get(activation) or default_loss
    if loss is None:
        raise ValueError(
            f"Cannot infer a loss for {what}: the file's training_config "
            "yielded no usable loss (saved uncompiled, or compiled with a "
            "loss this importer does not map) and the output activation "
            f"{activation!r} has no canonical loss pairing. Pass "
            "default_loss=... (e.g. 'mse', 'mcxent') to choose one "
            "explicitly."
        )
    return loss


def _output_head(layer, loss_hint: Optional[str],
                 default_loss: Optional[str] = None):
    """Convert a terminal mapped layer into this framework's output-layer
    form (reference appends ``KerasLoss``): Dense → OutputLayer (fused
    logits path), anything else → the layer + a parameter-free LossLayer."""
    if isinstance(layer, DenseLayer) and not isinstance(layer, OutputLayer):
        loss = _resolve_loss(loss_hint, layer.activation, default_loss,
                             f"output layer '{layer.name}'")
        return OutputLayer(n_out=layer.n_out, activation=layer.activation, loss=loss), None
    if getattr(layer, "is_output_layer", False):
        return layer, None
    loss = _resolve_loss(loss_hint, getattr(layer, "activation", None),
                         default_loss, f"terminal layer '{layer.name}'")
    return layer, LossLayer(loss=loss, activation="identity")


def open_archive(path: str, weights_path: Optional[str] = None):
    """Format dispatch: architecture-JSON + weights pair, Keras 3
    ``.keras`` zip, or HDF5 full-model file."""
    import zipfile

    if weights_path is not None:
        from deeplearning4j_tpu.modelimport.keras.zip_archive import (
            JsonWeightsArchive,
        )

        return JsonWeightsArchive(path, weights_path)
    if zipfile.is_zipfile(path):
        return KerasZipArchive(path)
    return Hdf5Archive(path)


def _inbound_names(layer_cfg: dict) -> List[str]:
    """Source vertex names from inbound_nodes — Keras 2 nested-list format
    or Keras 3 keras_history format."""
    nodes = layer_cfg.get("inbound_nodes") or []
    if not nodes:
        return []
    node = nodes[0]
    names: List[str] = []
    if isinstance(node, dict):  # Keras 3: {"args": [...], "kwargs": {...}}
        def scan(obj):
            if isinstance(obj, dict):
                if obj.get("class_name") == "__keras_tensor__":
                    names.append(obj["config"]["keras_history"][0])
                else:
                    for v in obj.values():
                        scan(v)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    scan(v)

        scan(node.get("args", []))
    else:  # Keras 2: [["src", node_idx, tensor_idx, {...}], ...]
        for entry in node:
            names.append(entry[0])
    return names


class KerasModelImport:
    """Static entry points mirroring ``KerasModelImport.java:50-121``."""

    # ------------------------------------------------------------ sequential
    @staticmethod
    def import_keras_sequential_model_and_weights(
        path: str, compute_dtype: Optional[str] = None,
        default_loss: Optional[str] = None,
        weights_path: Optional[str] = None,
    ):
        """→ MultiLayerNetwork with copied weights. ``compute_dtype``
        ("bfloat16") enables mixed-precision inference/fine-tuning on the
        imported net; weights stay fp32 master copies. ``default_loss``
        is used only when the file carries no training_config AND the
        output activation has no canonical loss (otherwise errors)."""
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with open_archive(path, weights_path) as ar:
            cfg = ar.model_config()
            if cfg["class_name"] != "Sequential":
                raise ValueError(
                    f"{path} holds a {cfg['class_name']} model; use "
                    "import_keras_model_and_weights for functional models"
                )
            layer_cfgs = cfg["config"]["layers"]
            tc_loss = _loss_from_training_config(ar.training_config())
            channels_first = _detect_channels_first(layer_cfgs)

            input_shape = None
            mapped: List[Tuple[str, Mapped]] = []
            # the first WEIGHTED layer after a Flatten needs its kernel
            # rows permuted when the source model flattened NCHW order;
            # parameterless layers (Dropout/Activation) in between don't
            # consume the pending flag
            flatten_feeds: Dict[str, bool] = {}
            flatten_pending = False
            for lc in layer_cfgs:
                if channels_first:
                    lc = _to_channels_last_cfg(lc)
                cls, conf = lc["class_name"], lc.get("config", {})
                shape = _layer_input_shape(lc)
                if shape is not None and input_shape is None:
                    input_shape = shape
                if cls == "InputLayer":
                    continue
                m = map_keras_layer(cls, conf)
                name = conf.get("name", cls)
                if m.is_flatten:
                    flatten_pending = True
                elif flatten_pending and m.translator is not None:
                    if isinstance(m.layer, DenseLayer):
                        flatten_feeds[name] = True
                        flatten_pending = False
                    elif channels_first:
                        # a weighted non-Dense layer (e.g. BN) between
                        # Flatten and Dense would ALSO need per-feature
                        # reordering in Keras-1 NCHW files; defer loudly
                        # (see needs_perm keras-1 gate) rather than
                        # import silently wrong
                        flatten_feeds[name] = "non_dense"
                mapped.append((name, m))
            if input_shape is None:
                bis = cfg["config"].get("build_input_shape")
                if bis is None:
                    raise ValueError(f"{path}: no input shape recorded")
                input_shape = list(bis)

            # terminal → output head
            names_layers = [(n, m) for n, m in mapped if m.layer is not None]
            if not names_layers:
                raise ValueError(f"{path}: no parameterizable layers found")
            last_name, last_m = names_layers[-1]
            head, extra_loss = _output_head(last_m.layer, tc_loss, default_loss)
            last_m.layer = head

            nb = NeuralNetConfiguration.builder().seed(0)
            if compute_dtype is not None:
                nb = nb.compute_dtype(compute_dtype)
            lb = nb.list()
            index_of: Dict[str, int] = {}
            idx = 0
            for n, m in mapped:
                if m.layer is None:
                    if not m.is_flatten:
                        raise UnsupportedKerasLayer(
                            f"Layer '{n}' is graph-only; import this model "
                            "via import_keras_model_and_weights"
                        )
                    continue  # Flatten: the builder infers the reshape
                lb.layer(m.layer)
                index_of[n] = idx
                idx += 1
            if extra_loss is not None:
                lb.layer(extra_loss)
            conf_built = (
                lb.set_input_type(
                    _input_type_for_shape(input_shape, channels_first)
                ).build()
            )
            net = MultiLayerNetwork(conf_built).init()
            types = conf_built.layer_types()

            # ---- weight copy
            new_params = list(net.params_)
            new_state = list(net.state_)
            for n, m in mapped:
                if m.translator is None or n not in index_of:
                    continue
                w = ar.layer_weights(n)
                if not w:
                    continue
                p, s = m.translator(w)
                i = index_of[n]
                # Keras 2/3 Flatten(data_format=channels_first) transposes
                # to channels_last BEFORE flattening, so rows already come
                # in (h, w, c) order; only Keras 1 / Theano-era files
                # flattened raw row-major NCHW and need the permutation
                # (verified empirically against keras 3 goldens).
                keras1 = ar.keras_version().startswith("1")
                if (channels_first and keras1
                        and flatten_feeds.get(n) == "non_dense"):
                    raise UnsupportedKerasLayer(
                        f"Keras-1 channels_first model has weighted layer "
                        f"'{n}' between Flatten and Dense; its per-feature "
                        "parameters would need NCHW reordering — unsupported"
                    )
                needs_perm = (channels_first and flatten_feeds.get(n) is True
                              and "W" in p and keras1)
                if needs_perm:
                    prev_t = (conf_built.layers[i - 1].get_output_type(types[i - 1])
                              if i > 0 else conf_built.input_type)
                    if prev_t.kind == "convolutional":
                        perm = _chw_to_hwc_perm(prev_t.height, prev_t.width,
                                                prev_t.channels)
                        p = dict(p)
                        p["W"] = np.asarray(p["W"])[perm, :]
                new_params[i] = {
                    k: _shaped(v, net.params_[i], k, n) for k, v in p.items()
                }
                if s:
                    new_state[i] = {
                        k: _shaped(v, net.state_[i], k, n) for k, v in s.items()
                    }
            net.params_ = new_params
            net.state_ = new_state
            net.channels_first_source = channels_first  # user feeds NHWC
            return net

    # ------------------------------------------------------------ functional
    @staticmethod
    def import_keras_model_and_weights(
        path: str, compute_dtype: Optional[str] = None,
        default_loss: Optional[str] = None,
        weights_path: Optional[str] = None,
    ):
        """→ ComputationGraph (functional) or MultiLayerNetwork (sequential),
        matching the reference's type dispatch."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        with open_archive(path, weights_path) as ar:
            cfg = ar.model_config()
            if cfg["class_name"] == "Sequential":
                return KerasModelImport.import_keras_sequential_model_and_weights(
                    path, compute_dtype=compute_dtype,
                    default_loss=default_loss, weights_path=weights_path
                )
            tc_loss = _loss_from_training_config(ar.training_config())
            gconf = cfg["config"]
            layer_cfgs = gconf["layers"]

            inputs: List[str] = []
            input_types: List[InputType] = []
            channels_first = _detect_channels_first(layer_cfgs)
            mapped: Dict[str, Mapped] = {}
            inbound: Dict[str, List[str]] = {}
            order: List[str] = []
            for lc in layer_cfgs:
                if channels_first:
                    # config rewrite only: Keras 2/3 Flatten already emits
                    # channels_last row order, so graph imports need no
                    # kernel permutation (Keras-1 functional NCHW models
                    # would; none are generatable for fixtures — the
                    # sequential path carries that logic)
                    lc = _to_channels_last_cfg(lc)
                cls, conf = lc["class_name"], lc.get("config", {})
                name = conf.get("name") or lc.get("name")
                if cls == "InputLayer":
                    inputs.append(name)
                    shape = _layer_input_shape(lc)
                    if shape is None:
                        raise ValueError(f"InputLayer {name} without shape")
                    input_types.append(
                        _input_type_for_shape(shape, channels_first)
                    )
                    continue
                mapped[name] = map_keras_layer(cls, conf)
                inbound[name] = _inbound_names(lc)
                order.append(name)

            def norm_outputs(spec):
                # [name,0,0] | [[name,0,0], ...]
                if spec and isinstance(spec[0], (list, tuple)):
                    return [s[0] for s in spec]
                return [spec[0]]

            out_names = norm_outputs(gconf["output_layers"])

            nb = NeuralNetConfiguration.builder().seed(0)
            if compute_dtype is not None:
                nb = nb.compute_dtype(compute_dtype)
            gb = (
                nb.graph_builder()
                .add_inputs(*inputs)
                .set_input_types(*input_types)
            )
            for name in order:
                m = mapped[name]
                srcs = inbound[name]
                if m.layer is not None:
                    gb.add_layer(name, m.layer, *srcs)
                elif m.vertex is not None:
                    gb.add_vertex(name, m.vertex, *srcs)
                else:
                    raise UnsupportedKerasLayer(f"Layer {name} maps to nothing")

            # ensure every network output is an output layer
            final_outputs = []
            for on in out_names:
                m = mapped.get(on)
                if m is not None and m.layer is not None and getattr(
                    m.layer, "is_output_layer", False
                ):
                    final_outputs.append(on)
                    continue
                act = getattr(m.layer, "activation", "identity") if (
                    m and m.layer is not None) else "identity"
                loss = _resolve_loss(tc_loss, act, default_loss,
                                     f"network output '{on}'")
                loss_name = f"{on}_loss"
                gb.add_layer(loss_name, LossLayer(loss=loss, activation="identity"), on)
                final_outputs.append(loss_name)
            gb.set_outputs(*final_outputs)
            net = ComputationGraph(gb.build()).init()

            # ---- weight copy
            new_params = dict(net.params_)
            new_state = dict(net.state_)
            for name in order:
                m = mapped[name]
                if m.translator is None:
                    continue
                w = ar.layer_weights(name)
                if not w:
                    continue
                p, s = m.translator(w)
                new_params[name] = {
                    k: _shaped(v, net.params_[name], k, name) for k, v in p.items()
                }
                if s:
                    new_state[name] = {
                        k: _shaped(v, net.state_[name], k, name) for k, v in s.items()
                    }
            net.params_ = new_params
            net.state_ = new_state
            return net

    # aliases matching the reference's overload names
    importKerasModelAndWeights = import_keras_model_and_weights
    importKerasSequentialModelAndWeights = import_keras_sequential_model_and_weights


def _shaped(v, tgt: dict, key: str, layer_name: str):
    import jax.numpy as jnp

    if key not in tgt:
        raise ValueError(
            f"Imported weight '{key}' for layer '{layer_name}' has no "
            f"destination (model has {sorted(tgt)})"
        )
    if isinstance(v, dict):  # nested params (Bidirectional fwd/bwd)
        return {k: _shaped(sub, tgt[key], k, f"{layer_name}.{key}")
                for k, sub in v.items()}
    if tuple(v.shape) != tuple(tgt[key].shape):
        raise ValueError(
            f"Shape mismatch for {layer_name}.{key}: keras {v.shape} vs "
            f"model {tuple(tgt[key].shape)}"
        )
    return jnp.asarray(v, tgt[key].dtype)
