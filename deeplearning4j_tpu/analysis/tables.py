"""Doc-table regeneration from the declared schema.

ARCHITECTURE.md embeds the flight-event and chaos-fire-point tables
between marker comments; these renderers produce the exact block, and
a test asserts the docs match — the table can only change by changing
``obs/events.py``, which the ``event-schema`` lint rule ties to the
actual call sites. ``cli lint --events-table`` prints the block for
pasting.
"""

from __future__ import annotations

EVENT_TABLE_BEGIN = "<!-- BEGIN generated flight-event table " \
    "(obs/events.py; cli lint --events-table) -->"
EVENT_TABLE_END = "<!-- END generated flight-event table -->"


def render_event_table() -> str:
    from deeplearning4j_tpu.obs import events

    lines = [EVENT_TABLE_BEGIN, "",
             "| event kind | producer | meaning |", "|---|---|---|"]
    for kind, (producer, desc) in events.FLIGHT_EVENTS.items():
        lines.append(f"| `{kind}` | `{producer}` | {desc} |")
    lines += ["", "| chaos fire point | producer | meaning |",
              "|---|---|---|"]
    for point, (producer, desc) in events.HOOK_POINTS.items():
        lines.append(f"| `{point}` | `{producer}` | {desc} |")
    lines += ["", EVENT_TABLE_END]
    return "\n".join(lines)


def render_drill_table() -> str:
    """The chaos drill matrix as markdown (from the live DRILLS
    registry — heavier import; not used by the lint fast path)."""
    from deeplearning4j_tpu.chaos.drills import DRILLS

    lines = ["| drill | workload | seam(s) | paired | tier |",
             "|---|---|---|---|---|"]
    for d in DRILLS.values():
        lines.append(
            f"| {d.name} | {d.workload} | {', '.join(d.seams)} | "
            f"{'yes' if d.paired else 'no'} | "
            f"{'fast' if d.fast else 'slow'} |")
    return "\n".join(lines)
