"""Doc-table regeneration from the declared schema.

ARCHITECTURE.md embeds the flight-event and chaos-fire-point tables
between marker comments; these renderers produce the exact block, and
a test asserts the docs match — the table can only change by changing
``obs/events.py``, which the ``event-schema`` lint rule ties to the
actual call sites. ``cli lint --events-table`` prints the block for
pasting.
"""

from __future__ import annotations

EVENT_TABLE_BEGIN = "<!-- BEGIN generated flight-event table " \
    "(obs/events.py; cli lint --events-table) -->"
EVENT_TABLE_END = "<!-- END generated flight-event table -->"

ALERT_TABLE_BEGIN = "<!-- BEGIN generated alert-rule table " \
    "(obs/slo.py; cli lint --alerts-table) -->"
ALERT_TABLE_END = "<!-- END generated alert-rule table -->"


def render_event_table() -> str:
    from deeplearning4j_tpu.obs import events

    lines = [EVENT_TABLE_BEGIN, "",
             "| event kind | producer | meaning |", "|---|---|---|"]
    for kind, (producer, desc) in events.FLIGHT_EVENTS.items():
        lines.append(f"| `{kind}` | `{producer}` | {desc} |")
    lines += ["", "| chaos fire point | producer | meaning |",
              "|---|---|---|"]
    for point, (producer, desc) in events.HOOK_POINTS.items():
        lines.append(f"| `{point}` | `{producer}` | {desc} |")
    lines += ["", EVENT_TABLE_END]
    return "\n".join(lines)


def render_alert_table() -> str:
    """The SLO alert-rule table, regenerated from the live rule pack
    (obs/slo.py default pack + the canary-gate rules at their default
    knobs) — same byte-identical-embed contract as the flight-event
    table, so ARCHITECTURE's alert catalog can only change by changing
    the pack, which the ``alert-schema`` lint rule ties to the
    declared names."""
    from deeplearning4j_tpu.obs import slo

    class _Stats:
        requests = errors = gen_requests = 0
        score = None
        latency_sum = gen_latency_sum = 0.0

        def mean_latency(self):
            return None

        def mean_gen_latency(self):
            return None

    class _MM:  # inert stand-in: the table needs signatures, not state
        active = canary = None

    rules = slo.default_rules() + slo.canary_gate_rules(
        _MM(), higher_is_better=False, latency_trip_mult=5.0,
        latency_trip_min_samples=8, score_trip_tolerance=0.0)
    lines = [ALERT_TABLE_BEGIN, "",
             "| alert | kind | severity | signal | condition | "
             "meaning |", "|---|---|---|---|---|---|"]
    for r in rules:
        d = " ".join(r.description.split())
        sig = r.signal_text().replace("|", "\\|")
        lines.append(f"| `{r.name}` | {r.kind} | {r.severity} | "
                     f"`{sig}` | {r.condition_text()} | {d} |")
    lines += ["", ALERT_TABLE_END]
    return "\n".join(lines)


def render_drill_table() -> str:
    """The chaos drill matrix as markdown (from the live DRILLS
    registry — heavier import; not used by the lint fast path)."""
    from deeplearning4j_tpu.chaos.drills import DRILLS

    lines = ["| drill | workload | seam(s) | paired | tier |",
             "|---|---|---|---|---|"]
    for d in DRILLS.values():
        lines.append(
            f"| {d.name} | {d.workload} | {', '.join(d.seams)} | "
            f"{'yes' if d.paired else 'no'} | "
            f"{'fast' if d.fast else 'slow'} |")
    return "\n".join(lines)
