"""Controller-verdict rule: every adaptive-capacity action site must
record a flight event carrying the triggering verdict.

The observe→act loop's auditability contract: when a controller turns a
knob (deadline retune, bucket switch, slot scale, tenant demote/
restore, model prewarm/evict), the flight ring must show *why* — the
``controller_*`` event with a ``verdict=`` field next to the action.
Without it, a postmortem sees the system reconfigure itself with no
recorded cause, which is exactly the "self-driving with no black box"
failure mode this repo's forensics discipline exists to prevent.

Two checks:

- A ``controller_*`` flight record without a ``verdict=`` kwarg is a
  finding (the event exists but carries no cause).
- A call to a controller *action method* (``set_max_wait_ms``,
  ``retune_buckets``, ``scale_generation_slots``, ``demote_tenant``,
  ``restore_tenant``, ``prewarm_model``, ``evict_model``) inside a
  function that records NO verdict-carrying ``controller_*`` event is
  a finding — unless the enclosing function *is* one of the action
  methods (the definitions and their internal delegation are the
  mechanism, not a policy decision) or is itself a ``controller_*``
  helper by name.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from deeplearning4j_tpu.analysis.core import (
    FileContext,
    Finding,
    register_rule,
)
from deeplearning4j_tpu.analysis.rules_events import (
    _literal_first_arg,
    _recv_matches,
    _RECORDER_NAMES,
)

#: the controller actuation surface: calling any of these IS a capacity
#: action, so the caller must attach its verdict
ACTION_METHODS = frozenset({
    "set_max_wait_ms", "retune_buckets", "scale_generation_slots",
    "demote_tenant", "restore_tenant", "prewarm_model", "evict_model",
})


def _is_controller_record(call: ast.Call) -> bool:
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "record"
            and _recv_matches(fn, _RECORDER_NAMES, "recorder")):
        return False
    kind = _literal_first_arg(call)
    return kind is not None and kind.startswith("controller_")


def _has_verdict_kwarg(call: ast.Call) -> bool:
    return any(kw.arg == "verdict" for kw in call.keywords
               if kw.arg is not None)


def _called_action(call: ast.Call):
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in ACTION_METHODS:
        return fn.attr
    if isinstance(fn, ast.Name) and fn.id in ACTION_METHODS:
        return fn.id
    return None


def _own_nodes(fn_node: ast.AST):
    """Walk a function's OWN body: nested defs analyze as their own
    scope (the outer loop visits them), and lambdas DEFER their call —
    a ``lambda n: router.scale_generation_slots(model, n)`` is an
    actuator being built, not an action being taken."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@register_rule(
    "controller-verdict-attached",
    "adaptive-capacity action sites must record a controller_* flight "
    "event with the triggering verdict attached (verdict= kwarg)")
def check_controller_verdict(ctx: FileContext) -> Iterable[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # the action methods themselves (and controller_* helpers) are
        # the mechanism — policy attribution is their CALLERS' job
        if node.name in ACTION_METHODS \
                or node.name.startswith("controller_"):
            continue
        records_verdict = False
        bare_records: List[ast.Call] = []
        action_calls: List[tuple] = []
        for sub in _own_nodes(node):
            if not isinstance(sub, ast.Call):
                continue
            if _is_controller_record(sub):
                if _has_verdict_kwarg(sub):
                    records_verdict = True
                else:
                    bare_records.append(sub)
            else:
                method = _called_action(sub)
                if method is not None:
                    action_calls.append((sub, method))
        for call in bare_records:
            kind = _literal_first_arg(call)
            findings.append(ctx.finding(
                "controller-verdict-attached", call,
                f"controller flight event {kind!r} recorded without a "
                "verdict= field — attach the triggering "
                "HealthVerdict status so the forensics show WHY the "
                "system reconfigured itself"))
        if not records_verdict:
            for call, method in action_calls:
                findings.append(ctx.finding(
                    "controller-verdict-attached", call,
                    f"capacity action {method}() called in "
                    f"{node.name}() with no verdict-carrying "
                    "controller_* flight record in the same function "
                    "— record the action with its triggering verdict "
                    "(verdict=...) or route it through a controller"))
    return findings
