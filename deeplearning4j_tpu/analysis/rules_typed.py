"""Typed-error rules: the chaos invariant taxonomy, enforced statically.

The dynamic half (``chaos/invariants.check_typed_errors``) asserts that
no caller-visible error is a bare KeyError/AttributeError/… — an
implementation detail leaking where a typed verdict belongs. These
rules stop the leak at the ``raise`` site and catch its dual: a broad
``except`` that swallows everything without re-raising or at least an
explicit, reviewed acknowledgement.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from deeplearning4j_tpu.analysis.core import (
    FileContext,
    Finding,
    register_rule,
)

#: builtins that may never be raised bare in production code. ValueError
#: / TypeError / RuntimeError / OSError / NotImplementedError stay legal:
#: they are the documented caller-contract verdicts (see
#: chaos/invariants.typed_error_bases) — the banned set is the
#: implementation-detail leaks.
_BANNED_RAISES = {
    "Exception", "BaseException", "KeyError", "IndexError",
    "AttributeError", "StopIteration", "StopAsyncIteration",
    "ZeroDivisionError", "UnboundLocalError",
}

#: dunder protocols where the bare builtin IS the contract
_PROTOCOL_FUNCS = {
    "__getattr__": {"AttributeError"},
    "__getattribute__": {"AttributeError"},
    "__delattr__": {"AttributeError"},
    "__getitem__": {"KeyError", "IndexError"},
    "__setitem__": {"KeyError", "IndexError"},
    "__delitem__": {"KeyError", "IndexError"},
    "__missing__": {"KeyError"},
    "__next__": {"StopIteration"},
    "__anext__": {"StopAsyncIteration"},
    # the DL4J iterator API: `def next(self)` backs `__next__`, so
    # StopIteration there IS the protocol, not a leak
    "next": {"StopIteration"},
}

_BROAD = {"Exception", "BaseException"}


def _raised_name(node: ast.Raise) -> str:
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    if isinstance(exc, ast.Name):
        return exc.id
    return ""


def _is_property_def(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        if isinstance(dec, ast.Name) and dec.id == "property":
            return True
        if isinstance(dec, ast.Attribute) and dec.attr in (
                "setter", "getter", "deleter"):
            return True
    return False


@register_rule(
    "typed-errors-bare-raise",
    "production code raises typed errors from the project taxonomy, "
    "never bare builtin exceptions (KeyError/AttributeError/...)")
def check_bare_raise(ctx: FileContext) -> Iterable[Finding]:
    findings: List[Finding] = []

    def visit(node: ast.AST, func_stack: tuple) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func_stack = func_stack + (node,)
        elif isinstance(node, ast.Raise):
            name = _raised_name(node)
            if name in _BANNED_RAISES:
                fn = func_stack[-1] if func_stack else None
                allowed = set()
                if fn is not None:
                    allowed = _PROTOCOL_FUNCS.get(fn.name, set())
                    if _is_property_def(fn):
                        # AttributeError from a property getter is the
                        # hasattr() protocol
                        allowed = allowed | {"AttributeError"}
                if name not in allowed:
                    findings.append(ctx.finding(
                        "typed-errors-bare-raise", node,
                        f"bare {name} leaks an implementation detail; "
                        "raise a typed error (subclass "
                        f"{name} if dict-/attr-compat matters, like "
                        "UnknownModelError does)"))
        for child in ast.iter_child_nodes(node):
            visit(child, func_stack)

    visit(ctx.tree, ())
    return findings


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare `except:`
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in _BROAD for n in names)


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


@register_rule(
    "typed-errors-broad-except",
    "bare/broad except without re-raise must carry an explicit "
    "trailing acknowledgement comment (e.g. '# noqa: BLE001 — why')")
def check_broad_except(ctx: FileContext) -> Iterable[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if _reraises(node):
            continue
        if node.type is None:
            # bare `except:` also swallows SystemExit/KeyboardInterrupt
            # — never acceptable, comment or not
            findings.append(ctx.finding(
                "typed-errors-broad-except", node,
                "bare `except:` swallows SystemExit/KeyboardInterrupt "
                "too; catch Exception at most, re-raise, or narrow"))
            continue
        if "#" in ctx.line_text(node.lineno):
            continue  # explicit, reviewed acknowledgement on the line
        findings.append(ctx.finding(
            "typed-errors-broad-except", node,
            "broad except swallows without re-raise or "
            "acknowledgement; narrow it, re-raise typed, or annotate "
            "the except line with a trailing comment saying why "
            "swallowing is safe (the repo idiom: "
            "'# noqa: BLE001 — <reason>')"))
    return findings
