"""Linter core: findings, the rule registry, tree walking, reports.

A rule is a callable ``(ctx: FileContext) -> Iterable[Finding]``
registered under a dotted rule id. ``lint_paths`` parses each ``.py``
file once and hands the same AST to every rule; ``run_lint`` layers the
baseline (suppression) semantics on top and produces the
:class:`LintReport` the CLI, the drive script and the tier-1 gate test
all consume.

Fingerprints are deliberately line-number-independent: the SHA-1 of
``rule : relpath : stripped-source-line : occurrence-index``. A finding
keeps its identity when unrelated edits move it, so baselines don't rot
with every refactor — but when the offending LINE changes or goes away,
the baseline entry goes stale and the lint fails until the entry is
removed (expiry is explicit, never silent).
"""

from __future__ import annotations

import ast
import hashlib
import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence

#: directories never walked (caches, VCS internals)
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


class Finding:
    """One rule violation, anchored to ``path:line``."""

    __slots__ = ("rule", "path", "line", "col", "message", "text",
                 "fingerprint")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, text: str = ""):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.text = text
        self.fingerprint = ""  # assigned by lint_paths (needs occurrence)

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "text": self.text, "fingerprint": self.fingerprint}

    def __repr__(self):
        return f"{self.location()}: {self.rule}: {self.message}"


class FileContext:
    """Everything a rule needs about one source file, parsed once."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.AST):
        self.path = path
        #: repo-root-relative, '/'-separated (stable across platforms,
        #: what fingerprints and baselines store)
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        #: path segments, for scope checks ("serving" in ctx.parts)
        self.parts = tuple(self.relpath.split("/"))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule, self.relpath, line, col, message,
                       text=self.line_text(line).strip())


#: rule id -> (description, fn)
RULES: "Dict[str, tuple]" = {}


def register_rule(rule_id: str, description: str):
    """Decorator registering a rule engine under ``rule_id``."""

    def wrap(fn: Callable[[FileContext], Iterable[Finding]]):
        RULES[rule_id] = (description, fn)
        return fn

    return wrap


def _load_rules() -> None:
    # importing the rule modules populates RULES (idempotent)
    from deeplearning4j_tpu.analysis import (  # noqa: F401
        rules_controller,
        rules_durability,
        rules_events,
        rules_trace,
        rules_typed,
    )


def iter_python_files(root: str,
                      paths: Optional[Sequence[str]] = None):
    """Yield (abspath, relpath) for every ``.py`` under ``root`` (or
    under the explicit ``paths``, which may be files or directories,
    absolute or root-relative)."""
    root = os.path.abspath(root)
    if paths:
        tops = [p if os.path.isabs(p) else os.path.join(root, p)
                for p in paths]
    else:
        tops = [root]
    for top in tops:
        if os.path.isfile(top):
            yield top, os.path.relpath(top, root)
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    full = os.path.join(dirpath, name)
                    yield full, os.path.relpath(full, root)


def _assign_fingerprints(findings: List[Finding]) -> None:
    seen: Dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        key = (f.rule, f.path, f.text)
        occ = seen.get(key, 0)
        seen[key] = occ + 1
        raw = f"{f.rule}:{f.path}:{f.text}:{occ}"
        f.fingerprint = hashlib.sha1(raw.encode()).hexdigest()[:12]


def lint_paths(root: str, paths: Optional[Sequence[str]] = None,
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the (selected) rules over every python file under ``root``;
    returns fingerprinted findings sorted by location. A file that does
    not parse is itself a finding (rule ``parse-error``) — an analyzer
    that silently skips unparseable code would gate nothing."""
    _load_rules()
    chosen = RULES if rules is None else {
        r: RULES[r] for r in rules}  # KeyError on an unknown rule id is
    # a caller bug surfaced loudly, matching run_matrix's typed refusal
    findings: List[Finding] = []
    for full, rel in iter_python_files(root, paths):
        try:
            with open(full, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            findings.append(Finding("parse-error", rel, 1, 0,
                                    f"unreadable: {e}"))
            continue
        try:
            tree = ast.parse(source, filename=full)
        except SyntaxError as e:
            findings.append(Finding("parse-error", rel,
                                    e.lineno or 1, e.offset or 0,
                                    f"syntax error: {e.msg}"))
            continue
        ctx = FileContext(full, rel, source, tree)
        for rule_id, (_desc, fn) in chosen.items():
            findings.extend(fn(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    _assign_fingerprints(findings)
    return findings


class LintReport:
    """The gate's verdict: active findings fail; baseline-suppressed
    ones pass; stale baseline entries (matched nothing) ALSO fail —
    a fixed finding must be removed from the baseline, so the file
    only ever shrinks through explicit review."""

    def __init__(self, active: List[Finding], suppressed: List[Finding],
                 stale: List[dict], root: str, baseline_path: str = ""):
        self.active = active
        self.suppressed = suppressed
        self.stale = stale
        self.root = root
        self.baseline_path = baseline_path

    @property
    def ok(self) -> bool:
        return not self.active and not self.stale

    @property
    def exit_code(self) -> int:
        return 0 if self.ok else 1

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "root": self.root,
            "baseline": self.baseline_path,
            "active": [f.to_dict() for f in self.active],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "stale_baseline_entries": list(self.stale),
            "counts": {"active": len(self.active),
                       "suppressed": len(self.suppressed),
                       "stale": len(self.stale)},
        }

    def format(self, verbose: bool = False) -> str:
        lines: List[str] = []
        for f in self.active:
            lines.append(f"{f.location()}: {f.rule}: {f.message}")
        for entry in self.stale:
            lines.append(
                f"{entry.get('path', '?')}: stale-baseline: entry "
                f"{entry.get('fingerprint')} ({entry.get('rule')}) "
                "matched nothing — the finding is gone; remove the "
                "entry from the baseline")
        if verbose:
            for f in self.suppressed:
                lines.append(f"{f.location()}: suppressed({f.rule}): "
                             f"{f.message}")
        lines.append(
            f"lint: {len(self.active)} finding(s), "
            f"{len(self.suppressed)} baseline-suppressed, "
            f"{len(self.stale)} stale baseline entr"
            f"{'y' if len(self.stale) == 1 else 'ies'} -> "
            f"{'OK' if self.ok else 'FAIL'}")
        return "\n".join(lines)


def run_lint(root: str, paths: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None,
             rules: Optional[Sequence[str]] = None) -> LintReport:
    """Lint + baseline: the one call behind ``cli lint``, the drive
    script and the tier-1 gate test."""
    from deeplearning4j_tpu.analysis import baseline as bl

    findings = lint_paths(root, paths, rules=rules)
    if baseline_path and os.path.exists(baseline_path):
        entries = bl.load_baseline(baseline_path)
    else:
        entries = []
    active, suppressed, stale = bl.apply_baseline(findings, entries)
    return LintReport(active, suppressed, stale, os.path.abspath(root),
                      baseline_path or "")
