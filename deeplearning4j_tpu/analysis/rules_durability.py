"""Durability rules: atomic publishes must be of fsynced bytes, and
durable surfaces must route through the injectable fs layer.

The defect class (caught by hand in PR 13 review, now codified):
``os.replace`` of a file whose bytes were never fsynced can publish an
EMPTY artifact after power loss — the rename is durable before the
data is. And any write on a durable surface (checkpoints, registry
journals/snapshots, tune stores — everything under serving/, train/,
tune/) that bypasses ``chaos/fslayer.py`` silently opts out of typed
StorageError handling, the chaos seams, and the torn-tail repair
discipline.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from deeplearning4j_tpu.analysis.core import (
    FileContext,
    Finding,
    register_rule,
)

#: the fs layer itself and its tests legitimately touch os.replace
_FSLAYER_SUFFIX = "chaos/fslayer.py"

#: call names that count as a durability barrier for the staged bytes
_FSYNC_NAMES = {"fsync", "fsync_file", "fsync_path", "write_atomic"}

#: packages whose writes are durable surfaces (the artifacts a crash
#: drill replays): serving registry/snapshots, train checkpoints, tune
#: stores
_DURABLE_DIRS = {"serving", "train", "tune"}

#: modes that create/overwrite an artifact. 'r+'/'rb+' in-place
#: patching is deliberately NOT flagged: that is the torn-tail-repair /
#: fault-injection idiom, and fslayer.repair_torn_tail itself owns the
#: durable cases
_WRITE_MODES = set("wax")


def _is_os_replace(call: ast.Call) -> bool:
    fn = call.func
    return (isinstance(fn, ast.Attribute)
            and fn.attr in ("replace", "rename")
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "os")


def _calls_fsync_before(scope: ast.AST, lineno: int) -> bool:
    for node in ast.walk(scope):
        if not isinstance(node, ast.Call):
            continue
        if getattr(node, "lineno", 10**9) >= lineno:
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if name.lstrip("_") in _FSYNC_NAMES:
            return True
    return False


def _enclosing_scopes(tree: ast.AST):
    """Yield (function-or-module scope, node) pairs for every node,
    innermost scope first at lookup time (computed as a parent map)."""
    parents = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent

    def innermost(node):
        cur = parents.get(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            cur = parents.get(cur)
        return cur

    return innermost


@register_rule(
    "durability-unsynced-replace",
    "os.replace/os.rename must be preceded by an fsync of the staged "
    "bytes in the same function (or routed through chaos/fslayer)")
def check_unsynced_replace(ctx: FileContext) -> Iterable[Finding]:
    if ctx.relpath.endswith(_FSLAYER_SUFFIX):
        return []
    findings: List[Finding] = []
    innermost = _enclosing_scopes(ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_os_replace(node):
            scope = innermost(node) or ctx.tree
            if not _calls_fsync_before(scope, node.lineno):
                findings.append(ctx.finding(
                    "durability-unsynced-replace", node,
                    "os.replace of bytes never fsynced in this "
                    "function — a power loss after the rename can "
                    "publish an empty file; fsync the staged fd "
                    "(or use chaos/fslayer.replace after "
                    "fsync_file/fsync_path)"))
    return findings


def _open_write_mode(call: ast.Call) -> bool:
    fn = call.func
    if not (isinstance(fn, ast.Name) and fn.id == "open"):
        return False
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return bool(_WRITE_MODES & set(mode.value))
    return False


#: os.open flag names that make the fd a write surface. O_CREAT counts
#: even alone — creating a durable artifact IS a write
_OS_OPEN_WRITE_FLAGS = {"O_WRONLY", "O_RDWR", "O_APPEND", "O_CREAT"}


def _os_open_write_flags(call: ast.Call) -> bool:
    """``os.open(path, os.O_WRONLY | ...)`` — the low-level bypass the
    mode-string check above cannot see (how the cluster journal's
    lease/heartbeat appends WOULD dodge fslayer if hand-rolled). The
    flags expression is walked structurally, so ``|``-composed flags,
    parenthesised groups and ``os.O_*`` vs bare ``O_*`` imports all
    match."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "open"
            and isinstance(fn.value, ast.Name) and fn.value.id == "os"):
        return False
    flags = None
    if len(call.args) >= 2:
        flags = call.args[1]
    for kw in call.keywords:
        if kw.arg == "flags":
            flags = kw.value
    if flags is None:
        return False
    for node in ast.walk(flags):
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        if name in _OS_OPEN_WRITE_FLAGS:
            return True
    return False


@register_rule(
    "durability-bypass-fslayer",
    "write-mode open() / write-flag os.open() on a durable surface "
    "(serving/train/tune) must route through chaos/fslayer "
    "(open_for_write / append_line / write_atomic)")
def check_bypass_fslayer(ctx: FileContext) -> Iterable[Finding]:
    if not (_DURABLE_DIRS & set(ctx.parts)):
        return []
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _open_write_mode(node):
            findings.append(ctx.finding(
                "durability-bypass-fslayer", node,
                "direct write-mode open() on a durable surface "
                "bypasses the typed-StorageError/chaos-seam fs layer; "
                "use chaos/fslayer.open_for_write, append_line or "
                "write_atomic"))
        elif _os_open_write_flags(node):
            findings.append(ctx.finding(
                "durability-bypass-fslayer", node,
                "os.open with write flags (O_WRONLY/O_RDWR/O_APPEND/"
                "O_CREAT) on a durable surface bypasses the typed-"
                "StorageError/chaos-seam fs layer; use "
                "chaos/fslayer.open_for_write, append_line or "
                "write_atomic"))
    return findings
