"""Event-schema rule: every flight event kind and chaos fire point is
declared in ``obs/events.py``.

The chaos invariant checker asserts event ORDER against documented
state machines; that only works if the names are right. A typo'd
``flight.record`` kind silently breaks a forensic subsequence check
months later, and an undeclared kind is an event nobody documented.
The declared schema is also what the ARCHITECTURE flight-event table
regenerates from, so passing this rule means the docs cover the code.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from deeplearning4j_tpu.analysis.core import (
    FileContext,
    Finding,
    register_rule,
)

#: receiver spellings that mean "the flight recorder" at a
#: ``X.record("kind", ...)`` call site across the repo
_RECORDER_NAMES = {"flight", "_flight", "rec", "recorder"}
#: and "the chaos hooks module" at ``X.fire("point", ...)``
_HOOKS_NAMES = {"hooks", "chaos_hooks", "_chaos", "_hooks"}


def _literal_first_arg(call: ast.Call):
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _recv_matches(fn: ast.Attribute, names: set, attr_alias: str) -> bool:
    v = fn.value
    if isinstance(v, ast.Name) and v.id in names:
        return True
    # self.recorder.record(...) / ctx.hooks.fire(...) style
    if isinstance(v, ast.Attribute) and v.attr == attr_alias:
        return True
    return False


@register_rule(
    "event-schema",
    "flight.record kinds and chaos_hooks.fire points must be declared "
    "in obs/events.py (the table ARCHITECTURE regenerates from)")
def check_event_schema(ctx: FileContext) -> Iterable[Finding]:
    from deeplearning4j_tpu.obs import events as schema

    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        fn = node.func
        if fn.attr == "record" and _recv_matches(fn, _RECORDER_NAMES,
                                                 "recorder"):
            kind = _literal_first_arg(node)
            if kind is not None and not schema.is_declared_event(kind):
                findings.append(ctx.finding(
                    "event-schema", node,
                    f"flight event kind {kind!r} is not declared in "
                    "obs/events.py FLIGHT_EVENTS — declare it (one "
                    "entry: producer + description) so the forensic "
                    "subsequence checks and the ARCHITECTURE table "
                    "cover it"))
        elif fn.attr == "fire" and _recv_matches(fn, _HOOKS_NAMES,
                                                 "hooks"):
            point = _literal_first_arg(node)
            if point is not None \
                    and not schema.is_declared_hook_point(point):
                findings.append(ctx.finding(
                    "event-schema", node,
                    f"chaos hook point {point!r} is not declared in "
                    "obs/events.py HOOK_POINTS — declare it (and "
                    "register_hook_seam it in chaos/seams.py) so "
                    "plans can address it"))
    return findings


def _is_alert_rule_ctor(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id == "AlertRule":
        return True
    # obs.alerts.AlertRule(...) / alerts.AlertRule(...) style
    return isinstance(fn, ast.Attribute) and fn.attr == "AlertRule"


@register_rule(
    "alert-schema",
    "AlertRule names must be declared in obs/events.py ALERTS (the "
    "set the chaos drills' expected_alerts and the ARCHITECTURE "
    "alert-rule table are checked against)")
def check_alert_schema(ctx: FileContext) -> Iterable[Finding]:
    """A typo'd alert name would silently break a drill's
    ``expected_alerts`` detection check (the drill would wait for an
    alert that can never fire under that name), and an undeclared one
    is an alert nobody documented — the exact failure mode the
    flight-event half of this rule already guards."""
    from deeplearning4j_tpu.obs import events as schema

    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) \
                or not _is_alert_rule_ctor(node):
            continue
        name = _literal_first_arg(node)
        if name is not None and not schema.is_declared_alert(name):
            findings.append(ctx.finding(
                "alert-schema", node,
                f"alert rule name {name!r} is not declared in "
                "obs/events.py ALERTS — declare it (producer + "
                "description) so expected_alerts checks and the "
                "ARCHITECTURE alert-rule table cover it"))
    return findings
