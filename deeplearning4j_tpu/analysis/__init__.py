"""Static invariant analysis: the review-found defect classes as code.

Every review round of PRs 6-13 hand-caught the same four defect
classes; this package turns those hard-won rules into an AST-based
linter (stdlib ``ast``, zero new dependencies) that gates tier-1:

- ``durability-unsynced-replace`` / ``durability-bypass-fslayer`` —
  an ``os.replace`` of un-fsynced bytes can publish an empty file
  after power loss, and durable-surface writes in serving/train/tune
  must route through ``chaos/fslayer.py`` (typed StorageError + chaos
  seams).
- ``typed-errors-bare-raise`` / ``typed-errors-broad-except`` —
  production paths never raise bare builtin exceptions or swallow
  broadly without re-raise/acknowledgement (the chaos invariant
  taxonomy, enforced statically).
- ``trace-host-sync`` / ``trace-probe-jnp`` — host-sync calls inside
  jitted step bodies and ``jnp`` input construction inside kernel
  probes (the PR 12 tracer bug class).
- ``event-schema`` — every ``flight.record``/``chaos_hooks.fire``
  name must be declared in ``obs/events.py``, from which the
  ARCHITECTURE tables regenerate.

Entry points: ``cli lint`` (human + ``--json``), ``run_lint`` (the
library call the tier-1 gate test uses), ``LINT_BASELINE.json`` at the
repo root (explicitly triaged pre-existing findings; stale entries
expire loudly).
"""

from deeplearning4j_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintReport,
    lint_paths,
    run_lint,
)
from deeplearning4j_tpu.analysis.baseline import (  # noqa: F401
    load_baseline,
    write_baseline,
)
