"""Baseline suppression: pre-existing findings, triaged explicitly.

The baseline file (``LINT_BASELINE.json`` at the repo root) is the
reviewed list of findings the tree consciously carries — each entry
records the fingerprint, where it was when triaged, the offending line
text, and WHY it is acceptable. Semantics:

- **add**: a finding whose fingerprint appears in the baseline is
  suppressed (reported separately, never failing the gate). New
  entries land only through review — ``cli lint --write-baseline``
  regenerates the file from the current findings so the diff shows
  exactly what is being accepted.
- **expire**: an entry that matched nothing is STALE and fails the
  gate. Either the finding was fixed (delete the entry) or the code
  changed enough that the fingerprint moved (re-triage). Silent rot —
  a baseline suppressing ghosts — is exactly what review-found rule
  lists die of.

Fingerprints are line-number-independent (see ``core``), so unrelated
edits never churn the file.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Sequence, Tuple

BASELINE_NAME = "LINT_BASELINE.json"
_VERSION = 1


def load_baseline(path: str) -> List[dict]:
    with open(path, "r", encoding="utf-8") as fh:
        body = json.load(fh)
    if not isinstance(body, dict) or "entries" not in body:
        raise ValueError(
            f"baseline {path!r} is not a {{version, entries}} object")
    version = body.get("version")
    if version != _VERSION:
        raise ValueError(f"baseline {path!r} has version {version!r}; "
                         f"this analyzer reads version {_VERSION}")
    entries = body["entries"]
    if not isinstance(entries, list):
        raise ValueError(f"baseline {path!r} entries is not a list")
    for e in entries:
        if not isinstance(e, dict) or "fingerprint" not in e:
            raise ValueError(
                f"baseline {path!r} entry without a fingerprint: {e!r}")
    return entries


def apply_baseline(findings: Sequence, entries: Sequence[dict]
                   ) -> Tuple[list, list, List[dict]]:
    """Split findings into (active, suppressed) and report stale
    entries. One entry suppresses exactly one finding occurrence —
    fingerprints already carry an occurrence index, so N identical
    lines need N reviewed entries."""
    by_fp: Dict[str, dict] = {}
    for e in entries:
        by_fp[str(e["fingerprint"])] = e
    matched = set()
    active, suppressed = [], []
    for f in findings:
        if f.fingerprint in by_fp:
            matched.add(f.fingerprint)
            suppressed.append(f)
        else:
            active.append(f)
    stale = [e for e in entries if str(e["fingerprint"]) not in matched]
    return active, suppressed, stale


def write_baseline(path: str, findings: Sequence,
                   reasons: Dict[str, str] = None) -> dict:
    """Regenerate the baseline from the given findings (the triage
    helper behind ``cli lint --write-baseline``). ``reasons`` maps
    fingerprints to triage notes; unmapped entries get a placeholder
    the reviewer is expected to replace."""
    reasons = reasons or {}
    body = {
        "version": _VERSION,
        "generated": time.strftime("%Y-%m-%d"),
        "comment": ("Explicitly triaged pre-existing lint findings. "
                    "Entries suppress exactly one finding each; an "
                    "entry whose finding is gone goes STALE and fails "
                    "the gate until removed (see ARCHITECTURE "
                    "# Static analysis)."),
        "entries": [
            {"fingerprint": f.fingerprint, "rule": f.rule,
             "path": f.path, "line": f.line, "text": f.text,
             "reason": reasons.get(f.fingerprint,
                                   "TODO: reviewed-and-accepted because "
                                   "<why>")}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(body, fh, indent=1)
        fh.write("\n")
    return body
