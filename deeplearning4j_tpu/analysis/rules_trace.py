"""Trace-safety rules: the PR 12 tracer bug class, codified.

Two defects this catches:

- **Host syncs inside jitted step bodies** (``float()``, ``.item()``,
  ``np.asarray``, ``jax.device_get``, ``.block_until_ready``): under
  ``jit`` these force a device round-trip per dispatch — exactly the
  per-step syncs the pipelined training loop removed — or fail outright
  under an ambient trace.
- **``jnp`` input construction inside kernel probes**: a probe's inputs
  built with ``jnp`` become TRACERS when the probe runs under an
  ambient trace, and the AOT-compiled probe executables reject them
  (the latent flash-attention probe bug PR 12 found and fixed). Probe
  inputs must be numpy.

Jitted bodies are found statically: defs decorated with ``jit`` /
``jax.jit`` / ``partial(jax.jit, ...)``, plus local defs passed to a
``jax.jit(...)`` / ``jit(...)`` call anywhere in the module (including
through ``jax.value_and_grad`` / ``partial`` wrappers) — the repo's
dominant idiom is ``def step(...): ...; return jax.jit(step)``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from deeplearning4j_tpu.analysis.core import (
    FileContext,
    Finding,
    register_rule,
)

_NP_NAMES = {"np", "numpy", "onp"}
_NP_SYNC_FNS = {"asarray", "array"}
_JNP_CTORS = {"array", "asarray", "ones", "zeros", "full", "arange",
              "linspace", "eye", "empty", "ones_like", "zeros_like",
              "full_like"}


def _is_jit_callable(fn: ast.AST) -> bool:
    """`jit` / `jax.jit` / `pjit` / `jax.pjit` as an expression."""
    if isinstance(fn, ast.Name):
        return fn.id in ("jit", "pjit")
    if isinstance(fn, ast.Attribute):
        return fn.attr in ("jit", "pjit")
    return False


def _collect_jitted_names(tree: ast.AST) -> Set[str]:
    """Names of functions that end up under jit in this module."""
    names: Set[str] = set()

    def first_name_arg(call: ast.Call):
        # unwrap jax.jit(X), jax.jit(partial(X,...)),
        # jax.jit(jax.value_and_grad(X)), nested combinations
        if not call.args:
            return None
        arg = call.args[0]
        while isinstance(arg, ast.Call):
            if not arg.args:
                return None
            arg = arg.args[0]
        return arg.id if isinstance(arg, ast.Name) else None

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_callable(node.func):
            name = first_name_arg(node)
            if name:
                names.add(name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec
                if isinstance(dec, ast.Call):
                    # @partial(jax.jit, ...) or @jax.jit(...)
                    if (isinstance(dec.func, ast.Name)
                            and dec.func.id == "partial" and dec.args):
                        target = dec.args[0]
                    else:
                        target = dec.func
                if _is_jit_callable(target):
                    names.add(node.name)
    return names


def _static_shape_math(call: ast.Call) -> bool:
    """float(x.shape[0]) / float(len(xs)) style trace-time constants."""
    for sub in ast.walk(call):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape",
                                                           "ndim",
                                                           "size",
                                                           "dtype"):
            return True
        if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                and sub.func.id == "len"):
            return True
        if sub is not call and isinstance(sub, ast.Constant):
            return True
    return False


def _host_sync_kind(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "float":
        if node.args and not _static_shape_math(node):
            return "float() host read"
        return ""
    if isinstance(fn, ast.Attribute):
        if fn.attr == "item":
            return ".item() host read"
        if fn.attr == "block_until_ready":
            return ".block_until_ready() host sync"
        if fn.attr == "device_get":
            return "jax.device_get host transfer"
        if (fn.attr in _NP_SYNC_FNS and isinstance(fn.value, ast.Name)
                and fn.value.id in _NP_NAMES):
            return f"{fn.value.id}.{fn.attr} device->host copy"
    return ""


@register_rule(
    "trace-host-sync",
    "no host-sync calls (float()/.item()/np.asarray/device_get) inside "
    "jitted step bodies")
def check_host_sync(ctx: FileContext) -> Iterable[Finding]:
    jitted = _collect_jitted_names(ctx.tree)
    if not jitted:
        return []
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in jitted):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    kind = _host_sync_kind(sub)
                    if kind:
                        findings.append(ctx.finding(
                            "trace-host-sync", sub,
                            f"{kind} inside jitted body "
                            f"{node.name!r} forces a device "
                            "round-trip per dispatch (or breaks "
                            "under an ambient trace); compute it "
                            "in-graph or outside the step"))
    return findings


def _is_probe_def(node: ast.AST) -> bool:
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    return (node.name.startswith("_probe") or node.name == "probe"
            or node.name.startswith("probe_"))


@register_rule(
    "trace-probe-jnp",
    "kernel probes (nn/ops) build inputs with numpy, never jnp — jnp "
    "values become tracers under an ambient trace and AOT probe "
    "executables reject them")
def check_probe_jnp(ctx: FileContext) -> Iterable[Finding]:
    if "ops" not in ctx.parts:
        return []
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not _is_probe_def(node):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "jnp"
                    and fn.attr in _JNP_CTORS):
                findings.append(ctx.finding(
                    "trace-probe-jnp", sub,
                    f"probe input built with jnp.{fn.attr} becomes a "
                    "TRACER under an ambient trace and the AOT probe "
                    "executable rejects it (the PR 12 flash-probe "
                    "bug); build probe inputs with numpy"))
    return findings
