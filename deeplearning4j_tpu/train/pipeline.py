"""Pipelined training loop: in-graph multi-step bundling.

Every fit path used to pay one Python→XLA dispatch per batch plus a
synchronous host→device transfer on the main thread. TensorFlow's system
design argues for keeping the step loop IN the dataflow graph so dispatch
cost amortizes over many steps (arXiv 1605.08695 §4.2), and the
Julia-to-TPU work shows fixed-shape whole-loop compilation is exactly the
program shape the TPU wants (arXiv 1810.09868). This module provides the
bundling layer:

- :func:`make_bundled_step` wraps a model's raw (unjitted) train step in a
  ``lax.scan`` over K stacked batches: ONE dispatch executes K optimizer
  steps. The host iteration counter is advanced *in-graph* as scan carry
  (epoch is constant within a bundle — bundles never cross epoch
  boundaries), and the fault-state pytree (train/faults.py) threads
  through the scan so the non-finite guard / loss scaling behave
  bit-identically to the unbundled loop.
- The divergence tripwire (``max_consecutive_bad_steps``) is checked once
  per bundle on the FINAL ``consec`` — K-1 fewer host syncs; a bad streak
  that starts in one bundle and continues into the next still trips,
  while a streak that both starts and fully recovers strictly inside one
  bundle is not observed mid-bundle (documented trade; set
  ``steps_per_call=1`` for per-step tripwire granularity).
- Per-step losses come back as a stacked device array.
  :func:`dispatch_bundle_listeners` hands it to listeners: bundle-aware
  listeners (``bundle_done`` hook — ScoreIterationListener,
  CollectScoresIterationListener) get a :class:`BundleScores` whose host
  values are fetched AT MOST ONCE per bundle; legacy listeners still get
  per-step ``iteration_done`` calls with ``model.score_`` rebound to the
  matching device scalar slice (no sync unless the listener reads it).
- Listeners that need per-step host callbacks — ``on_backward_pass`` and
  the introspection hooks (``on_forward_pass`` /
  ``on_gradient_calculation``) — force ``steps_per_call=1`` via
  :func:`resolve_steps_per_call` (bundled steps cannot stop between
  optimizer steps to call back into Python).

Bundling is legal when: backprop is standard (tBPTT chunk loops advance
one host iteration per *batch* across several chunk dispatches and thread
carries outside the graph — :func:`resolve_steps_per_call` rejects it),
the K batches share shapes/dtypes/mask layout (the batch stacker in
data/iterators.py guarantees this; ragged tails fall back to the
single-step path), and no attached listener needs per-step host
callbacks.
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)

# test hook: total host fetches of bundled score arrays (the sync-free
# listener regression test asserts one fetch per bundle, not per step)
_host_fetches = 0


class BundleScores:
    """Per-step losses of one bundle. Stays a device array; the host copy
    is materialized lazily and AT MOST ONCE, however many listeners (or
    frequency hits) read it."""

    def __init__(self, scores):
        self.dev = scores
        self._host: Optional[np.ndarray] = None
        self.fetch_count = 0

    def __len__(self) -> int:
        return int(self.dev.shape[0])

    def host(self) -> np.ndarray:
        if self._host is None:
            global _host_fetches
            self._host = np.asarray(self.dev)
            self.fetch_count += 1
            _host_fetches += 1
        return self._host


# --------------------------------------------------------------------------
# legality / resolution
# --------------------------------------------------------------------------
_PER_STEP_HOOKS = ("on_forward_pass", "on_gradient_calculation",
                   "on_backward_pass")


def bundling_blockers(listeners: Sequence[Any]) -> List[str]:
    """Listener needs that require per-step host callbacks (and therefore
    force ``steps_per_call=1``), as ``Type.reason`` strings: the
    introspection/backward hooks, plus listeners declaring
    ``requires_per_step_state`` — their ``iteration_done`` side effects
    snapshot the MODEL (checkpoint zips, profiler trace windows), and a
    post-bundle replay would hand every step end-of-bundle state."""
    from deeplearning4j_tpu.train.listeners import _has_hook

    out = set()
    for lst in listeners:
        own = getattr(lst, "bundling_blockers", None)
        if callable(own):
            # composites self-report their children's needs (their own
            # delegating hook overrides would read as always-blocking)
            out.update(own())
            continue
        for h in _PER_STEP_HOOKS:
            if _has_hook(lst, h):
                out.add(f"{type(lst).__name__}.{h}")
        if getattr(lst, "requires_per_step_state", False):
            out.add(f"{type(lst).__name__}.requires_per_step_state")
    return sorted(out)


def capture_data_state(model, it) -> None:
    """Record the iterator's stream position on the model, for
    checkpoint ``meta.json`` provenance (model_serializer extends the
    RNG chain with it). Duck-typed: iterators without ``data_state``
    (the legacy async path) are a no-op — only position-aware sources
    like ``data.loader.ShardedLoader`` participate. Called by the fit
    loop after every dispatched step and at each epoch boundary, so any
    checkpoint the listeners write carries the position the NEXT step
    would read from."""
    fn = getattr(it, "data_state", None)
    if callable(fn):
        model._data_state = fn()


def resolve_steps_per_call(model, requested: Optional[int] = None) -> int:
    """Effective bundle size for a fit loop: the requested K (default:
    ``GlobalConf.steps_per_call``), clamped to 1 when a listener needs
    per-step host callbacks. tBPTT configurations reject bundling with a
    ValueError rather than silently degrading — the chunk loop's
    iteration clock (one host iteration per batch, shared by all chunk
    dispatches) is incompatible with the scan's per-step carry."""
    if requested is None:
        requested = getattr(model.conf.global_conf, "steps_per_call", 1)
    k = int(requested or 1)
    if k <= 1:
        return 1
    if getattr(model.conf, "backprop_type", "standard") == "tbptt":
        raise ValueError(
            "steps_per_call > 1 cannot bundle tBPTT fits: chunk steps share "
            "one host iteration and carries cross chunk boundaries outside "
            "the graph; use steps_per_call=1 for tBPTT configurations"
        )
    blockers = bundling_blockers(getattr(model, "listeners", []))
    if blockers:
        log.info(
            "steps_per_call=%d forced to 1: listener hooks need per-step "
            "host callbacks (%s)", k, ", ".join(blockers))
        return 1
    return k


# --------------------------------------------------------------------------
# the bundled step
# --------------------------------------------------------------------------
def bundled_scan(raw_step, guarded: bool, telemetry: bool = False):
    """Wrap a raw train step ``(params, opt, state, [fstate,] f, l, fm,
    lm, rng, iteration, epoch) -> (params, opt, state, [fstate,] score
    [, telem])`` in a ``lax.scan`` over the leading K axis of the batch
    arrays and the stacked per-step rngs. The iteration counter rides the
    carry (+1 per step, in-graph); per-step scores are stacked into the
    (K,) output — and with ``telemetry`` the per-step telemetry dict
    (obs/telemetry.py) stacks the same way, riding the scan outputs
    alongside the scores so ONE host fetch surfaces a whole bundle's
    monitoring signals. ``None`` masks pass through (pytree nodes with no
    leaves scan transparently). Works for MultiLayerNetwork (array
    batches) and ComputationGraph (per-input tuples) alike."""
    if guarded:
        def bundle(params, opt_state, state, fstate, features, labels,
                   fmask, lmask, rngs, iteration, epoch):
            def body(carry, xs):
                p, o, s, fs, it = carry
                f, l, fm, lm, rng = xs
                out = raw_step(p, o, s, fs, f, l, fm, lm, rng, it, epoch)
                if telemetry:
                    p, o, s, fs, score, telem = out
                    return (p, o, s, fs, it + 1), (score, telem)
                p, o, s, fs, score = out
                return (p, o, s, fs, it + 1), score

            (p, o, s, fs, _), ys = jax.lax.scan(
                body, (params, opt_state, state, fstate, iteration),
                (features, labels, fmask, lmask, rngs))
            if telemetry:
                scores, telems = ys
                return p, o, s, fs, scores, telems
            return p, o, s, fs, ys

        return bundle

    def bundle(params, opt_state, state, features, labels, fmask, lmask,
               rngs, iteration, epoch):
        def body(carry, xs):
            p, o, s, it = carry
            f, l, fm, lm, rng = xs
            out = raw_step(p, o, s, f, l, fm, lm, rng, it, epoch)
            if telemetry:
                p, o, s, score, telem = out
                return (p, o, s, it + 1), (score, telem)
            p, o, s, score = out
            return (p, o, s, it + 1), score

        (p, o, s, _), ys = jax.lax.scan(
            body, (params, opt_state, state, iteration),
            (features, labels, fmask, lmask, rngs))
        if telemetry:
            scores, telems = ys
            return p, o, s, scores, telems
        return p, o, s, ys

    return bundle


def make_bundled_step(model, jit: bool = True, telemetry=None):
    """K-step bundled train step for ``model`` (MultiLayerNetwork or
    ComputationGraph): its raw train step under a ``lax.scan``. The
    compiled program is K-invariant in code size (the scan body traces
    once) but specialized to the stacked batch shapes, like every other
    jitted step. ``telemetry`` (a TelemetryConf) adds the stacked
    per-step telemetry output."""
    from deeplearning4j_tpu.obs import trace as _trace
    from deeplearning4j_tpu.train import faults as _faults

    guarded = model._active_fault_policy() is not None
    bundle = bundled_scan(model.train_step_fn(telemetry=telemetry), guarded,
                          telemetry=telemetry is not None)
    if not jit:
        return bundle
    bundle = _trace.count_retraces(
        f"{type(model).__name__}.bundled_step", bundle)
    donate = _faults.guard_donation(0, 1, 2) if guarded else (0, 1, 2)
    return jax.jit(bundle, donate_argnums=donate)


# --------------------------------------------------------------------------
# listener dispatch
# --------------------------------------------------------------------------
def dispatch_bundle_listeners(model, it0: int, epoch: int, scores,
                              telem=None) -> None:
    """Deliver one bundle's worth of iteration events.

    ``telem`` (the bundled step's stacked telemetry pytree, when the
    model trains with a TelemetryConf) is delivered FIRST via
    ``telemetry_done`` so listeners can fold the per-step in-graph
    signals into the records they emit from the score hooks. Then
    bundle-aware listeners (a ``bundle_done(model, it0, epoch,
    BundleScores)`` hook) get the whole bundle at once — their host
    fetch, if any, happens once per bundle. Every other listener keeps
    its exact legacy contract: ``iteration_done`` per step, in step
    order, with ``model.score_`` rebound to that step's device scalar
    (slicing a device array does not sync; only a listener that actually
    reads ``model.score()`` pays the transfer)."""
    if telem is not None:
        from deeplearning4j_tpu.obs import telemetry as _telemetry

        _telemetry.dispatch_telemetry(
            model.listeners, model, it0, epoch,
            _telemetry.BundleTelemetry(telem, int(scores.shape[0])))
    dispatch_bundle_to(model.listeners, model, it0, epoch,
                       BundleScores(scores))


def dispatch_bundle_to(listeners: Sequence[Any], model, it0: int,
                       epoch: int, bs: "BundleScores") -> None:
    """Bundle delivery over an explicit listener list — the core of
    :func:`dispatch_bundle_listeners`, also called by composite
    listeners (ComposableIterationListener.bundle_done) so composed
    Score/CollectScores children keep the once-per-bundle fetch."""
    k = len(bs)
    legacy = []
    for lst in listeners:
        if hasattr(lst, "bundle_done"):
            lst.bundle_done(model, it0, epoch, bs)
        else:
            legacy.append(lst)
    if legacy:
        for j in range(k):
            model.score_ = bs.dev[j]
            for lst in legacy:
                lst.iteration_done(model, it0 + j + 1, epoch)
    model.score_ = bs.dev[k - 1]
