"""Step-level training fault tolerance.

Three pillars (TensorFlow system paper, arXiv 1605.08695 §4.3, treats the
triad as table stakes; the reference's production posture — updater state
in the checkpoint, ``InvalidScoreIterationTerminationCondition`` — assumes
it exists):

1. **In-graph non-finite guard** — a global all-finite verdict over the
   synchronized gradient folded into the jitted train step. The update is
   applied through ``jnp.where`` on the scalar verdict, so a bad batch
   skips the weight/updater-state update while params, opt state and layer
   state pass through bit-identical — with NO per-step host sync (the
   verdict never leaves the device unless ``max_consecutive_bad_steps``
   is armed). Under the ZeRO-1 sharded update the verdict is computed on
   the GLOBAL (pre-reduce-scatter) gradient so every replica agrees.

2. **Dynamic loss scaling** — for ``compute_dtype`` mixed precision the
   loss is multiplied by a scale carried in the fault state, gradients
   are unscaled inside the step, the scale halves on overflow (the
   overflowed step is skipped) and grows ×``scale_growth`` after
   ``scale_growth_interval`` consecutive good steps. All in-graph.

3. **Crash-safe checkpointing** — ``ModelSerializer.write_model`` stages
   through a same-directory temp file and ``os.replace``s it into place
   (a SIGKILL mid-write never corrupts the visible checkpoint), plus a
   keep-last-k retention policy and ``load_latest_valid`` that detects
   truncated/corrupt zips (CRC + required-entry check) and falls back to
   the previous good checkpoint.

The step counter subtlety: a skipped step must not advance the updater's
bias-correction time ``t`` or the schedule iteration, otherwise "fit with
a NaN batch skipped" diverges from "fit with that batch removed" (Adam's
``1-beta^t`` terms would shift). The guarded steps therefore drive the
updater from the in-graph ``good_count`` carried in the fault state, not
from the host iteration counter (which keeps counting every batch seen,
skipped or not, for reporting parity with the reference).

Fault injection (tests/chaos drills): ``fault_injection(nan_grad_steps=…)``
bakes a deterministic "gradients become NaN at host iteration k" fault
into steps traced while it is active; ``truncate_file`` chops a checkpoint
mid-zip. Both are no-ops in production paths.
"""

from __future__ import annotations

import contextlib
import functools
import os
import uuid
import weakref
import zipfile
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


class TrainingDivergedError(RuntimeError):
    """Raised when ``max_consecutive_bad_steps`` non-finite gradient steps
    occur back to back — the run is diverging, not hitting stray bad
    batches, and silently skipping forever would mask it."""


# --------------------------------------------------------------------------
# policy
# --------------------------------------------------------------------------
class FaultPolicy:
    """Training fault-tolerance configuration, carried on
    ``GlobalConf.fault_policy`` (JSON round-trips with the network conf).

    - ``skip_nonfinite``: skip the weight update on a non-finite global
      gradient instead of poisoning the parameters.
    - ``max_consecutive_bad_steps``: raise :class:`TrainingDivergedError`
      after this many back-to-back skipped steps (None = never; checking
      costs one host sync per step, so it is opt-in).
    - ``loss_scaling``: dynamic loss scaling. None (default) auto-enables
      exactly when the model trains with a reduced ``compute_dtype``;
      True/False force it.
    - ``init_loss_scale`` / ``scale_growth_interval`` / ``scale_backoff``
      / ``scale_growth`` / ``min_loss_scale`` / ``max_loss_scale``: the
      loss-scale schedule (halve on overflow, grow after N good steps).
    - ``keep_last``: checkpoint retention for :func:`save_checkpoint`
      (None = keep everything).
    """

    def __init__(
        self,
        skip_nonfinite: bool = True,
        max_consecutive_bad_steps: Optional[int] = None,
        loss_scaling: Optional[bool] = None,
        init_loss_scale: float = 2.0 ** 15,
        scale_growth_interval: int = 200,
        scale_backoff: float = 0.5,
        scale_growth: float = 2.0,
        min_loss_scale: float = 1.0,
        max_loss_scale: float = 2.0 ** 24,
        keep_last: Optional[int] = None,
    ):
        self.skip_nonfinite = bool(skip_nonfinite)
        self.max_consecutive_bad_steps = (
            None if max_consecutive_bad_steps is None
            else int(max_consecutive_bad_steps))
        self.loss_scaling = loss_scaling if loss_scaling is None else bool(
            loss_scaling)
        self.init_loss_scale = float(init_loss_scale)
        self.scale_growth_interval = int(scale_growth_interval)
        self.scale_backoff = float(scale_backoff)
        self.scale_growth = float(scale_growth)
        self.min_loss_scale = float(min_loss_scale)
        self.max_loss_scale = float(max_loss_scale)
        self.keep_last = None if keep_last is None else int(keep_last)

    # -- activation ---------------------------------------------------------
    def scaling_active(self, compute_dtype) -> bool:
        """Loss scaling applies iff forced on, or (by default) the model
        computes in a reduced dtype (bf16/fp16 backward can overflow)."""
        if self.loss_scaling is not None:
            return self.loss_scaling
        return compute_dtype is not None

    def guard_active(self, compute_dtype) -> bool:
        return (self.skip_nonfinite
                or self.max_consecutive_bad_steps is not None
                or self.scaling_active(compute_dtype))

    # -- serde (mirrors nn/conf/serde generic contract) ----------------------
    def to_dict(self) -> dict:
        return {
            "@class": "FaultPolicy",
            "skip_nonfinite": self.skip_nonfinite,
            "max_consecutive_bad_steps": self.max_consecutive_bad_steps,
            "loss_scaling": self.loss_scaling,
            "init_loss_scale": self.init_loss_scale,
            "scale_growth_interval": self.scale_growth_interval,
            "scale_backoff": self.scale_backoff,
            "scale_growth": self.scale_growth,
            "min_loss_scale": self.min_loss_scale,
            "max_loss_scale": self.max_loss_scale,
            "keep_last": self.keep_last,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPolicy":
        return cls(**{k: v for k, v in d.items() if not k.startswith("@")})

    def __eq__(self, other):
        return isinstance(other, FaultPolicy) and self.to_dict() == other.to_dict()

    def __repr__(self):
        fields = {k: v for k, v in self.to_dict().items()
                  if not k.startswith("@")}
        return f"FaultPolicy({fields})"


def _register_serde():
    from deeplearning4j_tpu.nn.conf import serde

    serde.register(FaultPolicy)


_register_serde()


def active_policy(policy: Optional[FaultPolicy], compute_dtype
                  ) -> Optional[FaultPolicy]:
    """The policy iff its guard has anything to do for this model."""
    if policy is None or not policy.guard_active(compute_dtype):
        return None
    return policy


# --------------------------------------------------------------------------
# in-graph fault state
# --------------------------------------------------------------------------
def init_fault_state(policy: FaultPolicy, scaling: bool,
                     start_step: int = 0) -> Dict[str, Array]:
    """Device-resident scalar carry for the guarded steps. ``good_count``
    seeds from the model's iteration counter so a checkpoint-resumed run
    keeps its Adam bias-correction clock."""
    st = {
        "bad_count": jnp.zeros((), jnp.int32),
        "consec": jnp.zeros((), jnp.int32),
        "good_count": jnp.asarray(int(start_step), jnp.int32),
    }
    if scaling:
        st["loss_scale"] = jnp.asarray(policy.init_loss_scale, jnp.float32)
        st["scale_good"] = jnp.zeros((), jnp.int32)
    return st


def all_finite(tree) -> Array:
    """Scalar bool: every element of every floating leaf is finite.
    Traced over the logical (globally synchronized) values, so under
    GSPMD the verdict is replicated and all shards agree by
    construction."""
    oks = [jnp.all(jnp.isfinite(leaf))
           for leaf in jax.tree_util.tree_leaves(tree)
           if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)]
    if not oks:
        return jnp.asarray(True)
    return functools.reduce(jnp.logical_and, oks)


def guard_donation(*argnums) -> tuple:
    """Buffer donation for GUARDED train steps — every guarded step reads
    its old params/opt-state/layer-state into a ``jnp.where(verdict, new,
    old)`` select, so donated inputs are both read late and aliased to
    outputs. On real accelerators XLA sequences that correctly and
    donation stays on (the standard training-loop memory optimization).
    XLA:CPU miscompiles this aliasing pattern under heap pressure
    (observed as bad_alloc/segfaults once enough programs are live —
    the same backend bug class parallel/mesh.zero1_donation documents
    for the ZeRO-1 repl→shard→repl path), so donation is disabled
    there."""
    if jax.default_backend() == "cpu":
        return ()
    return tuple(argnums)


def where_tree(pred, new, old):
    """Elementwise select between two identically-structured pytrees —
    the skip mechanism (no branch, no host sync, sharding-preserving)."""
    return jax.tree_util.tree_map(lambda n, o: jnp.where(pred, n, o),
                                  new, old)


def advance_fault_state(policy: FaultPolicy, fstate: Dict[str, Array],
                        finite: Array) -> Dict[str, Array]:
    """Next fault-state carry given this step's verdict."""
    fin_i = finite.astype(jnp.int32)
    new = {
        "bad_count": fstate["bad_count"] + (1 - fin_i),
        "consec": jnp.where(finite, jnp.int32(0), fstate["consec"] + 1),
        "good_count": fstate["good_count"] + fin_i,
    }
    if "loss_scale" in fstate:
        scale, good = fstate["loss_scale"], fstate["scale_good"]
        grown = (good + 1) >= policy.scale_growth_interval
        up = jnp.minimum(scale * policy.scale_growth, policy.max_loss_scale)
        down = jnp.maximum(scale * policy.scale_backoff,
                           policy.min_loss_scale)
        new["loss_scale"] = jnp.where(finite,
                                      jnp.where(grown, up, scale), down)
        new["scale_good"] = jnp.where(jnp.logical_and(finite, ~grown),
                                      good + 1, jnp.int32(0))
    return new


#: model → bad_count seen at its previous check. Under bundling the
#: tripwire only observes the END-of-bundle consec, so a mid-bundle NaN
#: that recovers before the boundary leaves consec==0 — the delta
#: against this map is what still makes it into the black box. Weak
#: keys: a dropped model must not pin its entry (tuner pools churn
#: models).
_last_reported_bad: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def check_fault_state(policy: Optional[FaultPolicy],
                      fstate: Optional[Dict[str, Array]],
                      owner=None) -> None:
    """Host-side divergence tripwire. Costs one device sync, so it only
    runs when ``max_consecutive_bad_steps`` is armed.

    Flight-recorder feed: an armed tripwire already pays the host read,
    so the black box gets the NaN-skip streak for free — and when the
    trip fires, the divergence event is recorded AND the ring is dumped
    BEFORE the raise, so even a caller that swallows (or never catches)
    :class:`TrainingDivergedError` leaves the postmortem on disk.
    ``owner`` (the model) keys the transient-skip detection: a NaN step
    that recovers before the bundle boundary ends the check with
    consec==0, and only the cumulative ``bad_count`` advancing since
    this owner's previous check reveals it happened at all."""
    if (policy is None or fstate is None
            or policy.max_consecutive_bad_steps is None):
        return
    consec, bad_count = (
        int(v) for v in
        jax.device_get((fstate["consec"], fstate["bad_count"])))
    new_bad = 0
    if owner is not None:
        prev = _last_reported_bad.get(owner)
        _last_reported_bad[owner] = bad_count
        # a reset fault state (bad_count rewound below prev) starts a
        # fresh baseline instead of masking its first skips
        new_bad = bad_count - prev if (prev is not None
                                       and bad_count >= prev) else bad_count
    if consec == 0 and new_bad <= 0:
        return
    from deeplearning4j_tpu.obs import flight as _flight

    rec = _flight.default_flight_recorder()
    rec.record("nan_skip", consec=consec, bad_count=bad_count)
    if consec >= policy.max_consecutive_bad_steps:
        rec.record("divergence_trip", consec=consec,
                   limit=int(policy.max_consecutive_bad_steps),
                   bad_count=bad_count)
        if rec.dump_dir is not None:
            rec.dump(reason="divergence")
        raise TrainingDivergedError(
            f"{consec} consecutive non-finite gradient steps (limit "
            f"max_consecutive_bad_steps={policy.max_consecutive_bad_steps}) "
            "— training is diverging; lower the learning rate, check the "
            "data pipeline, or restore the last checkpoint"
        )


# --------------------------------------------------------------------------
# deterministic fault injection (test/chaos hook)
# --------------------------------------------------------------------------
_INJECT_NAN_STEPS: frozenset = frozenset()


def set_fault_injection(nan_grad_steps: Sequence[int] = ()) -> frozenset:
    """Arm the gradient-NaN injector for steps traced from now on; returns
    the previous setting. Steps are HOST iteration numbers (the
    ``iteration`` argument of the train step)."""
    global _INJECT_NAN_STEPS
    prev = _INJECT_NAN_STEPS
    _INJECT_NAN_STEPS = frozenset(int(s) for s in nan_grad_steps)
    return prev


@contextlib.contextmanager
def fault_injection(nan_grad_steps: Sequence[int] = ()):
    prev = set_fault_injection(nan_grad_steps)
    try:
        yield
    finally:
        global _INJECT_NAN_STEPS
        _INJECT_NAN_STEPS = prev


def inject_gradient_faults(grads, iteration):
    """Replace every gradient with NaN at the armed host iterations.
    Reads the injection registry at TRACE time — steps built outside a
    ``fault_injection`` context compile to an identity, and a step
    COMPILED inside one keeps its poison after the context exits (train
    steps are cached on the model/facade). Chaos drills must therefore
    use a fresh model (or cleared jit caches) per armed context; never
    arm injection around a model that will keep training."""
    if not _INJECT_NAN_STEPS:
        return grads
    it = jnp.asarray(iteration, jnp.int32)
    bad = functools.reduce(
        jnp.logical_or, [it == s for s in sorted(_INJECT_NAN_STEPS)])

    def poison(g):
        if not jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            return g
        return jnp.where(bad, jnp.asarray(jnp.nan, jnp.asarray(g).dtype), g)

    return jax.tree_util.tree_map(poison, grads)


def truncate_file(path: str, frac: float = 0.5) -> int:
    """Chop a file to ``frac`` of its size (fault injection: the on-disk
    state a crash mid-write would have left WITHOUT atomic replace).
    Returns the new size."""
    size = os.path.getsize(path)
    keep = max(int(size * frac), 1)
    with open(path, "rb+") as f:
        f.truncate(keep)
    return keep


# --------------------------------------------------------------------------
# crash-safe checkpoint directory management
# --------------------------------------------------------------------------
_TMP_MARKER = ".tmp-"


def atomic_tmp_path(path: str) -> str:
    """Same-directory staging name for an atomic ``os.replace`` publish
    (rename is only atomic within a filesystem)."""
    return f"{path}{_TMP_MARKER}{os.getpid()}-{uuid.uuid4().hex[:8]}"


def validate_checkpoint(path: str) -> Tuple[bool, str]:
    """(ok, reason). Detects truncation (zip central directory gone),
    CRC corruption, and zips that are not model checkpoints (required
    entries missing)."""
    from deeplearning4j_tpu.train.model_serializer import (
        COEFFICIENTS_ENTRY,
        CONFIG_ENTRY,
    )

    if not os.path.isfile(path):
        return False, "not a file"
    try:
        with zipfile.ZipFile(path, "r") as z:
            names = set(z.namelist())
            missing = {CONFIG_ENTRY, COEFFICIENTS_ENTRY} - names
            if missing:
                return False, (f"missing checkpoint entries {sorted(missing)}"
                               f" (found {sorted(names)})")
            bad = z.testzip()  # CRC over every member
            if bad is not None:
                return False, f"CRC mismatch in entry {bad!r}"
    except (zipfile.BadZipFile, OSError, EOFError) as e:
        return False, f"unreadable zip ({type(e).__name__}: {e})"
    return True, "ok"


def is_valid_checkpoint(path: str) -> bool:
    return validate_checkpoint(path)[0]


def checkpoint_files(directory: str) -> List[str]:
    """Checkpoint candidates in ``directory``, oldest → newest
    (mtime, then name). Staging temp files from in-flight or crashed
    atomic writes are never candidates."""
    out = []
    for name in os.listdir(directory):
        if _TMP_MARKER in name:
            continue
        if not name.endswith((".zip", ".bin")):
            continue
        p = os.path.join(directory, name)
        try:
            # stat now, not in the sort key: a concurrent prune may
            # delete entries between listdir and the sort
            mtime = os.path.getmtime(p)
        except OSError:
            continue
        if os.path.isfile(p):
            out.append((mtime, p))
    return [p for _, p in sorted(out)]


_TMP_SWEEP_AGE_S = 900.0  # staging files older than this are crash debris


def sweep_stale_tmp(directory: str,
                    max_age_s: float = _TMP_SWEEP_AGE_S,
                    surface: Optional[str] = None,
                    recursive: bool = False) -> List[str]:
    """Remove orphaned ``.tmp-`` staging files left by a PRIOR crashed
    atomic write; returns the swept paths. Called when an artifact
    directory is (re)opened — CheckpointListener, ModelRegistry,
    TrialStore — and from retention pruning. Only files older than
    ``max_age_s`` are debris: a younger one may belong to a concurrent
    writer about to ``os.replace`` it. Sweeps are counted in a
    ``tmp_sweep`` flight event so crash debris is visible in the black
    box rather than silently accumulating (or silently vanishing)."""
    import time

    removed: List[str] = []
    if not os.path.isdir(directory):
        return removed
    now = time.time()
    if recursive:
        walk = ((root, files) for root, _d, files in os.walk(directory))
    else:
        walk = [(directory, os.listdir(directory))]
    for root, names in walk:
        for name in names:
            if _TMP_MARKER not in name:
                continue
            p = os.path.join(root, name)
            try:
                if (os.path.isfile(p)
                        and now - os.path.getmtime(p) > max_age_s):
                    os.remove(p)
                    removed.append(p)
            except OSError:
                pass
    if removed:
        from deeplearning4j_tpu.obs import flight as _flight

        _flight.record("tmp_sweep", directory=str(directory),
                       count=len(removed),
                       surface=surface or "checkpoint")
    return removed


def prune_checkpoints(directory: str, keep_last: Optional[int]
                      ) -> List[str]:
    """Delete all but the newest ``keep_last`` checkpoints; returns the
    removed paths. Staging temp files are swept only once they are
    clearly crash debris (older than ``_TMP_SWEEP_AGE_S``) — a younger
    one may belong to a concurrent writer about to os.replace it."""
    removed: List[str] = list(sweep_stale_tmp(directory))
    if keep_last is None:
        return removed
    files = checkpoint_files(directory)
    for p in files[: max(len(files) - int(keep_last), 0)]:
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            pass
    return removed


def save_checkpoint(model, directory: str, keep_last: Optional[int] = None,
                    stem: Optional[str] = None) -> str:
    """Atomic write of ``model`` into ``directory`` with keep-last-k
    retention; returns the checkpoint path."""
    from deeplearning4j_tpu.train.model_serializer import ModelSerializer

    os.makedirs(directory, exist_ok=True)
    name = (stem or f"checkpoint_iter_{int(model.iteration):08d}") + ".zip"
    path = os.path.join(directory, name)
    ModelSerializer.write_model(model, path, save_updater=True)
    prune_checkpoints(directory, keep_last)
    from deeplearning4j_tpu.obs import flight as _flight

    _flight.record("checkpoint_write", path=path,
                   iteration=int(model.iteration))
    return path


def checkpoint_fingerprint(path: str) -> Tuple[int, int]:
    """Cheap identity of a checkpoint's on-disk content:
    ``(mtime_ns, size)``. The serving engine's hot-reload uses it to
    make a periodic ``/reload`` poll free — a checkpoint whose
    fingerprint has not changed is not re-restored. Atomic publishes
    (``os.replace``) always change both fields together, so a torn
    read of a half-written file can never fingerprint as current."""
    st = os.stat(path)
    return (st.st_mtime_ns, st.st_size)


def checkpoint_error_class(reason: str) -> str:
    """Coarse class of a :func:`validate_checkpoint` failure reason —
    the field the ``checkpoint_fallback`` flight event carries so a
    postmortem can split CRC corruption from truncation from stray
    files without string-matching free-form reasons."""
    r = reason.lower()
    if "crc" in r:
        return "crc_mismatch"
    if "unreadable" in r:
        return "unreadable_zip"
    if "missing" in r:
        return "missing_entries"
    if "not a file" in r:
        return "not_a_file"
    return "invalid"


def latest_valid_checkpoint(directory: str, missing_ok: bool = False
                            ) -> Optional[str]:
    """Newest checkpoint in ``directory`` that passes validation,
    warning about (and skipping over) corrupt/truncated newer ones.
    Every skipped checkpoint is ALSO recorded as a
    ``checkpoint_fallback`` flight event naming the skipped path and
    its error class — the serving engine's corrupt-newest fallback and
    the registry publish path both resolve through here, and a
    truncated snapshot routed around silently would be invisible in the
    black box. Raises FileNotFoundError when no valid checkpoint
    exists — ``missing_ok=True`` returns None instead (restart-wrapper
    and tuner-resume callers treat "nothing yet" as "start fresh", not
    an error)."""
    import warnings

    candidates = (checkpoint_files(directory)
                  if os.path.isdir(directory) else [])
    if not candidates:
        if missing_ok:
            return None
        raise FileNotFoundError(f"no checkpoints in {directory!r}")
    skipped: List[Tuple[str, str]] = []
    chosen: Optional[str] = None
    for path in reversed(candidates):
        ok, reason = validate_checkpoint(path)
        if ok:
            chosen = path
            break
        skipped.append((path, reason))
        warnings.warn(
            f"skipping corrupt checkpoint {path!r}: {reason}; "
            "falling back to the previous one", stacklevel=2)
    if skipped:
        from deeplearning4j_tpu.obs import flight as _flight

        for path, reason in skipped:
            _flight.record("checkpoint_fallback", skipped=str(path),
                           error_class=checkpoint_error_class(reason),
                           reason=reason, served=chosen,
                           directory=str(directory))
    if chosen is not None:
        return chosen
    if missing_ok:
        return None
    raise FileNotFoundError(
        f"no VALID checkpoint in {directory!r} "
        f"({len(candidates)} candidates, all corrupt)")


def load_latest_valid(directory: str):
    """Restore the newest valid checkpoint in ``directory`` (model type
    sniffed from the zip); returns ``(model, path)``."""
    from deeplearning4j_tpu.obs import flight as _flight
    from deeplearning4j_tpu.train.model_serializer import ModelGuesser

    path = latest_valid_checkpoint(directory)
    model = ModelGuesser.load_model_guess(path)
    _flight.record("checkpoint_load", path=path,
                   iteration=int(getattr(model, "iteration", 0) or 0))
    return model, path


# --------------------------------------------------------------------------
# elastic recovery: survive device/host loss mid-fit
# --------------------------------------------------------------------------
class MeshFailureError(RuntimeError):
    """A device or host dropped out of the training mesh mid-fit.
    ``survivors`` (when known) is the device list still healthy; None
    means "probe for them" (:func:`probe_devices`)."""

    def __init__(self, message: str, survivors: Optional[Sequence] = None):
        super().__init__(message)
        self.survivors = None if survivors is None else list(survivors)


class InjectedHostDropout(MeshFailureError):
    """Deterministic mesh failure from :func:`host_dropout_injection`
    — the chaos hook the elastic drill uses (a SIGKILLed host cannot be
    staged portably on a single-host CPU mesh; dropping k virtual
    devices at a chosen iteration exercises the identical recovery
    path)."""


class ElasticRecoveryExhaustedError(RuntimeError):
    """Elastic recovery gave up: the retry budget ran out or the
    surviving mesh fell below ``min_devices``. The newest valid
    checkpoint is intact on disk — this error means "page a human",
    not "state was lost"."""


#: substrings (lowercased) that mark a runtime error as a mesh/collective
#: failure rather than a programming error. Conservative on purpose: a
#: NaN or shape bug must never be "recovered" by silently shrinking the
#: mesh and replaying from the checkpoint.
_MESH_FAILURE_MARKERS = (
    "device unavailable",
    "device is unavailable",
    "failed to connect",
    "connection reset",
    "socket closed",
    "heartbeat",
    "coordination service",
    "peer task",
    "slice health",
    "data transfer",
    "network error",
)


def is_mesh_failure(exc: BaseException) -> bool:
    """Does this exception look like the mesh lost a participant?
    :class:`MeshFailureError` always qualifies; XLA/distributed runtime
    errors qualify when their message carries a known transport/health
    marker."""
    if isinstance(exc, MeshFailureError):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in _MESH_FAILURE_MARKERS)


def probe_devices(devices: Sequence) -> List:
    """The subset of ``devices`` that still completes a trivial
    computation — the survivor roster when a failure did not name its
    casualties. A transient failure probes all-healthy; the driver then
    retries on the full mesh (and the attempt still counts against the
    retry budget)."""
    ok = []
    for d in devices:
        try:
            x = jax.device_put(jnp.zeros((), jnp.float32), d)
            (x + 1).block_until_ready()
            ok.append(d)
        except Exception:  # noqa: BLE001 — any failure marks it dead
            continue
    return ok


# -- deterministic host-dropout injection (chaos hook) ----------------------
_DROPOUT_INJECTION: Optional[Dict] = None


def set_host_dropout_injection(at_iteration: Optional[int] = None,
                               survivors: Optional[int] = None):
    """Arm (or with None disarm) the one-shot host-dropout injector:
    the elastic schedule raises :class:`InjectedHostDropout` with the
    first ``survivors`` devices as the healthy roster just before host
    iteration ``at_iteration`` dispatches. Returns the previous
    setting."""
    global _DROPOUT_INJECTION
    prev = _DROPOUT_INJECTION
    _DROPOUT_INJECTION = (
        None if at_iteration is None
        else {"at_iteration": int(at_iteration),
              "survivors": int(survivors) if survivors is not None else None,
              "fired": False})
    return prev


@contextlib.contextmanager
def host_dropout_injection(at_iteration: int, survivors: int):
    prev = set_host_dropout_injection(at_iteration, survivors)
    try:
        yield
    finally:
        global _DROPOUT_INJECTION
        _DROPOUT_INJECTION = prev


def check_host_dropout(iteration: int) -> None:
    """Fire the armed injector (once) when ``iteration`` reaches it."""
    inj = _DROPOUT_INJECTION
    if inj is None or inj["fired"] or iteration < inj["at_iteration"]:
        return
    inj["fired"] = True
    n = inj["survivors"]
    survivors = jax.devices()[:n] if n is not None else None
    raise InjectedHostDropout(
        f"injected host dropout before iteration {iteration} "
        f"({'survivors=' + str(n) if n is not None else 'survivors unknown'})",
        survivors=survivors)


_EPOCH_CLOCK_CLS = None


def _epoch_clock(it0: int, e0: int, n_batches: int):
    """Listener keeping ``model.epoch`` equal to the flattened
    schedule's logical epoch during an elastic fit. The driver runs the
    whole schedule as ONE ParallelWrapper epoch per recovery segment,
    so without this every mid-run checkpoint would carry the segment's
    entry epoch — a crash + ``--resume`` would then restore (and print,
    and key ``save_every_n_epochs`` listeners on) the wrong epoch.
    Attached BEFORE the driver's CheckpointListener so each checkpoint
    serializes the epoch a plain epochs-loop fit would have recorded at
    that iteration. Class built lazily to keep faults.py's
    lazy-listener-import discipline."""
    global _EPOCH_CLOCK_CLS
    if _EPOCH_CLOCK_CLS is None:
        from deeplearning4j_tpu.train.listeners import TrainingListener

        class _EpochClockListener(TrainingListener):
            # epoch must track every step, or a bundled segment would
            # checkpoint end-of-bundle epochs mid-bundle
            requires_per_step_state = True

            def __init__(self, it0, e0, n_batches):
                self.it0 = int(it0)
                self.e0 = int(e0)
                self.n = max(int(n_batches), 1)

            def iteration_done(self, model, iteration, epoch):
                # epoch bumps AFTER an epoch's last iteration_done
                # (multilayer/wrapper fit paths), so the last step of
                # logical epoch e still records e: (done-1)//n, not
                # done//n
                done = max(int(iteration) - self.it0, 1)
                model.epoch = self.e0 + (done - 1) // self.n

        _EPOCH_CLOCK_CLS = _EpochClockListener
    return _EPOCH_CLOCK_CLS(it0, e0, n_batches)


class _ElasticSchedule:
    """DataSetIterator facade over the driver's flattened batch
    schedule: yields batches from ``start``, checking the dropout
    injector against the GLOBAL iteration number before each dispatch.
    Deliberately not async (``async_supported() → False``): the
    injection must raise on the fit thread, inside the fit loop, like a
    real collective failure would."""

    def __init__(self, schedule: Sequence, start: int, it0: int):
        self.schedule = schedule
        self.start = int(start)
        self.it0 = int(it0)

    def __iter__(self):
        for i in range(self.start, len(self.schedule)):
            check_host_dropout(self.it0 + i)
            yield self.schedule[i]

    def reset(self) -> None:
        pass

    def batch(self) -> int:
        f = getattr(self.schedule[0], "features", None)
        return int(f.shape[0]) if hasattr(f, "shape") else 0

    def async_supported(self) -> bool:
        return False


class ElasticFitDriver:
    """Fit that survives losing part of its mesh.

    Wraps a data-parallel fit (ParallelWrapper over a TrainingMesh of
    ``devices``) with the elastic recovery loop ROADMAP item 1 names:

    1. checkpoint every ``checkpoint_every_n_iterations`` optimizer
       steps (atomic, keep-last-k — the PR-2 discipline);
    2. when the fit dies of a mesh failure (:func:`is_mesh_failure`;
       injected drills raise :class:`InjectedHostDropout`), record
       ``mesh_shrink``, re-form a smaller mesh from the survivors
       (``error.survivors`` when the failure names them, else
       :func:`probe_devices`);
    3. reload ``latest_valid_checkpoint`` and reshard it onto the
       survivor mesh (parallel/reshard.py — ``reshard_start/done``
       flight events carry N→M, wall time and the byte ledger);
    4. resume the batch schedule in place from the checkpoint's
       iteration (``elastic_resume``) — the restored RNG chain and
       fault state make the resumed fit bit-identical to an
       uninterrupted fit over the same mesh sequence;
    5. give up with :class:`ElasticRecoveryExhaustedError` (and an
       ``elastic_giveup`` event + black-box dump) after ``max_retries``
       recoveries or when fewer than ``min_devices`` devices survive.
       ``backoff_s`` sleeps ``backoff_s * 2**attempt`` before each
       recovery (a real fleet re-admits hosts; give them a moment).

    The driver owns ``self.model`` — recovery replaces the dead model
    object with the restored one (listeners carried over), and ``fit``
    returns it.
    """

    def __init__(self, model, checkpoint_dir: str, *,
                 devices: Optional[Sequence] = None,
                 min_devices: int = 1,
                 max_retries: int = 2,
                 backoff_s: float = 0.0,
                 checkpoint_every_n_iterations: int = 1,
                 keep_last: Optional[int] = 3,
                 sharded_update: Optional[bool] = None,
                 steps_per_call: Optional[int] = None):
        if not checkpoint_dir:
            raise ValueError("ElasticFitDriver needs a checkpoint_dir — "
                             "recovery resumes from its newest valid "
                             "checkpoint")
        self.model = model
        self.checkpoint_dir = str(checkpoint_dir)
        self.devices = None if devices is None else list(devices)
        self.min_devices = max(int(min_devices), 1)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.checkpoint_every = max(int(checkpoint_every_n_iterations), 1)
        self.keep_last = keep_last
        self.sharded_update = sharded_update
        self.steps_per_call = steps_per_call
        self.recoveries = 0
        from deeplearning4j_tpu.train.listeners import CheckpointListener

        self._ckpt_listener = CheckpointListener(
            self.checkpoint_dir,
            save_every_n_iterations=self.checkpoint_every,
            keep_mode="last",
            keep_last=int(keep_last) if keep_last else 1)

    # -- internals -----------------------------------------------------------
    def _attach(self, model, clock=None) -> None:
        if clock is not None and clock not in model.listeners:
            # the clock must run BEFORE the checkpointer so each
            # checkpoint serializes the already-synced logical epoch
            at = (model.listeners.index(self._ckpt_listener)
                  if self._ckpt_listener in model.listeners
                  else len(model.listeners))
            model.listeners.insert(at, clock)
        if self._ckpt_listener not in model.listeners:
            model.add_listeners(self._ckpt_listener)

    def _detach(self, model, clock=None) -> None:
        if clock is not None and clock in model.listeners:
            model.listeners.remove(clock)
        if self._ckpt_listener in model.listeners:
            model.listeners.remove(self._ckpt_listener)

    def _giveup(self, cause: BaseException, survivors: int,
                detail: str) -> None:
        from deeplearning4j_tpu.obs import flight as _flight

        _flight.record("elastic_giveup", attempts=self.recoveries,
                       survivors=survivors,
                       min_devices=self.min_devices,
                       max_retries=self.max_retries)
        rec = _flight.default_flight_recorder()
        if rec.dump_dir is not None:
            rec.dump(reason="elastic_giveup")
        raise ElasticRecoveryExhaustedError(
            f"elastic recovery exhausted after {self.recoveries} "
            f"attempt(s): {survivors} surviving device(s), "
            f"min_devices={self.min_devices}, "
            f"max_retries={self.max_retries}; {detail}") from cause

    def _recover(self, err: MeshFailureError, mesh,
                 it_lo: Optional[int] = None,
                 it_hi: Optional[int] = None):
        import time as _time

        from deeplearning4j_tpu.obs import flight as _flight
        from deeplearning4j_tpu.parallel import reshard as _reshard
        from deeplearning4j_tpu.train.model_serializer import ModelGuesser

        devices = mesh.devices_flat()
        n_from = len(devices)
        survivors = err.survivors
        if survivors is None:
            survivors = probe_devices(devices)
        self.recoveries += 1
        _flight.record("mesh_shrink", n_from=n_from, n_to=len(survivors),
                       attempt=self.recoveries,
                       error=type(err).__name__, message=str(err)[:200])
        if (self.recoveries > self.max_retries
                or len(survivors) < self.min_devices):
            self._giveup(err, len(survivors),
                         f"newest valid checkpoint is intact in "
                         f"{self.checkpoint_dir!r}")
        if self.backoff_s:
            _time.sleep(self.backoff_s * (2 ** (self.recoveries - 1)))
        try:
            path = latest_valid_checkpoint(self.checkpoint_dir)
        except FileNotFoundError as fnf:
            # died before the first checkpoint landed: there is nothing
            # to resume FROM — a typed give-up, not a raw traceback
            self._giveup(fnf, len(survivors),
                         f"the mesh failed before any checkpoint was "
                         f"written to {self.checkpoint_dir!r}")
        old = self.model
        new_model = ModelGuesser.load_model_guess(path)
        it = int(new_model.iteration)
        if it_lo is not None and not (it_lo <= it <= it_hi):
            # the newest checkpoint in the dir is from a DIFFERENT run
            # (a stale dir, or two runs sharing one checkpoint_dir):
            # adopting it would either declare the fit complete with a
            # foreign model or replay the schedule from a negative
            # offset — a typed give-up, not silent corruption
            self._giveup(err, len(survivors),
                         f"newest valid checkpoint {path!r} is at "
                         f"iteration {it}, outside this fit's range "
                         f"[{it_lo}, {it_hi}] — it belongs to a "
                         f"different run; point checkpoint_dir at a "
                         f"fresh directory")
        # the dead model's listeners (incl. the driver's checkpointer)
        # carry over — recovery is invisible to observers
        new_model.add_listeners(*old.listeners)
        # shrink, not a fresh mesh: its guard is what keeps elastic
        # re-formation DP-only (model-tiling axes can't lose devices)
        new_mesh = mesh.shrink(survivors)
        with _reshard.reshard_event(n_from, len(survivors),
                                    surface="elastic") as stats:
            _reshard.place_model(new_model, new_mesh, stats, n_from=n_from)
        self.model = new_model
        _flight.record("elastic_resume",
                       iteration=int(new_model.iteration),
                       n_devices=len(survivors), checkpoint=str(path))
        return new_mesh

    # -- the fit -------------------------------------------------------------
    def fit(self, batches, epochs: int = 1):
        """Train ``self.model`` over ``batches`` (a finite iterable of
        DataSets) for ``epochs`` passes, surviving mesh failures.
        Returns the (possibly replaced) trained model."""
        from deeplearning4j_tpu.parallel.mesh import TrainingMesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        base = list(batches)
        schedule = base * int(epochs)
        if not schedule:
            return self.model
        it0 = int(self.model.iteration)
        e0 = int(getattr(self.model, "epoch", 0))
        clock = _epoch_clock(it0, e0, len(base))
        devices = (list(self.devices) if self.devices is not None
                   else list(jax.devices()))
        mesh = TrainingMesh(data=len(devices), devices=devices)
        try:
            while True:
                done = int(self.model.iteration) - it0
                if done >= len(schedule):
                    # the flattened schedule ran as N recovery segments
                    # of one ParallelWrapper epoch each; restore the
                    # caller's epoch arithmetic
                    self.model.epoch = e0 + int(epochs)
                    return self.model
                self._attach(self.model, clock)
                pw = ParallelWrapper(self.model, mesh=mesh,
                                     sharded_update=self.sharded_update,
                                     steps_per_call=self.steps_per_call)
                stream = _ElasticSchedule(schedule, done, it0)
                try:
                    pw.fit(stream, epochs=1)
                except MeshFailureError as e:
                    mesh = self._recover(e, mesh, it0,
                                         it0 + len(schedule))
                except Exception as e:  # noqa: BLE001 — triaged below
                    if not is_mesh_failure(e):
                        raise
                    mesh = self._recover(MeshFailureError(str(e)), mesh,
                                         it0, it0 + len(schedule))
        finally:
            self._detach(self.model, clock)
