"""Early stopping: condition-driven training driver.

Reference: ``deeplearning4j-nn/src/main/java/org/deeplearning4j/earlystopping/``
— ``EarlyStoppingConfiguration.java`` (builder holding saver, score
calculator, epoch/iteration termination conditions),
``trainer/BaseEarlyStoppingTrainer.java`` (the fit loop),
``scorecalc/*`` (DataSetLoss/Classification/Regression/ROC/Autoencoder/VAE
score calculators), ``termination/*`` (MaxEpochs, ScoreImprovement,
BestScore, MaxScoreIteration, MaxTime, InvalidScore), ``saver/*``
(InMemory, LocalFile), ``EarlyStoppingResult.java``.

Works for both MultiLayerNetwork and ComputationGraph (the reference has
separate EarlyStoppingTrainer/EarlyStoppingGraphTrainer; here one trainer
handles both since the model surface is shared).
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


# --------------------------------------------------------------------------
# Score calculators (reference scorecalc/*; minimizeScore semantics)
# --------------------------------------------------------------------------
class ScoreCalculator:
    """SPI: compute a model-selection score on held-out data
    (reference ``scorecalc/ScoreCalculator.java``)."""

    minimize_score = True

    def calculate_score(self, model) -> float:
        raise NotImplementedError

    @staticmethod
    def _fresh(iterator) -> None:
        """Rewind the evaluation iterator BEFORE consuming it. Every
        calculator must score from the start of its data on every call —
        repeat evaluation of one model has to be deterministic (the
        early-stopping loop and the tuner's rung scoring both call the
        same calculator many times, and a previous pass that died
        mid-iteration, or any outside partial consumption, would
        otherwise leave the next score computed over the tail only)."""
        reset_ok = getattr(iterator, "reset_supported", None)
        if callable(reset_ok) and not reset_ok():
            return
        reset = getattr(iterator, "reset", None)
        if callable(reset):
            reset()


class ScoreCalculatorObjective:
    """Adapter: a ScoreCalculator as a tuner objective (tune/runner.py
    rung scoring) — callable ``model -> float`` carrying the calculator's
    minimize/maximize direction."""

    def __init__(self, calculator: ScoreCalculator):
        self.calculator = calculator
        self.minimize = bool(calculator.minimize_score)

    def __call__(self, model) -> float:
        return float(self.calculator.calculate_score(model))

    def __repr__(self):
        return (f"ScoreCalculatorObjective({type(self.calculator).__name__},"
                f" minimize={self.minimize})")


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over an iterator (reference
    ``DataSetLossCalculator.java`` — also covers the CG variant)."""

    minimize_score = True

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        self._fresh(self.iterator)
        total, count = 0.0, 0
        for ds in self.iterator:
            n = ds.num_examples()
            total += model.score(ds) * n
            count += n
        self.iterator.reset()
        if count == 0:
            return float("nan")
        return total / count if self.average else total


class ClassificationScoreCalculator(ScoreCalculator):
    """Maximize an Evaluation metric (accuracy/f1/...; reference
    ``ClassificationScoreCalculator.java``)."""

    minimize_score = False

    def __init__(self, metric: str, iterator):
        self.metric = metric.lower()
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        self._fresh(self.iterator)
        ev = model.evaluate(self.iterator)
        return float(getattr(ev, self.metric)())


class RegressionScoreCalculator(ScoreCalculator):
    """Minimize a RegressionEvaluation metric (reference
    ``RegressionScoreCalculator.java``)."""

    minimize_score = True

    def __init__(self, metric: str, iterator):
        self.metric = metric.lower()
        self.iterator = iterator

    _METRIC_METHODS = {
        "mse": "average_mean_squared_error",
        "mae": "average_mean_absolute_error",
        "mean_squared_error": "average_mean_squared_error",
        "mean_absolute_error": "average_mean_absolute_error",
    }

    def calculate_score(self, model) -> float:
        self._fresh(self.iterator)
        ev = model.evaluate_regression(self.iterator)
        method = self._METRIC_METHODS.get(self.metric)
        if method is None:
            raise ValueError(
                f"Unknown regression metric '{self.metric}'; "
                f"one of {sorted(self._METRIC_METHODS)}"
            )
        return float(getattr(ev, method)())


class ROCScoreCalculator(ScoreCalculator):
    """Maximize AUROC/AUPRC (reference ``ROCScoreCalculator.java``)."""

    minimize_score = False

    def __init__(self, iterator, metric: str = "auc"):
        self.iterator = iterator
        self.metric = metric.lower()

    def calculate_score(self, model) -> float:
        from deeplearning4j_tpu.evaluation import ROC

        self._fresh(self.iterator)
        roc = ROC()
        for ds in self.iterator:
            out = model.output(ds.features)
            if isinstance(out, list):
                out = out[0]
            roc.eval(ds.labels, out)
        self.iterator.reset()
        return float(
            roc.calculate_auc() if self.metric == "auc" else roc.calculate_auprc()
        )


def _resolve_pretrain_layer(model, layer_index):
    """(layer, params) for ``layer_index`` on an MLN (int index) or a
    ComputationGraph (layer name str, or int index into layer_names) — the
    reference has MLN- and CG-specific calculators
    (``AutoencoderScoreCalculator.java`` handles both Model types)."""
    if hasattr(model, "layer_names"):  # ComputationGraph
        name = (model.layer_names[layer_index]
                if isinstance(layer_index, int) else layer_index)
        return model._layer(name), model.params_[name]
    return model.layers[layer_index], model.params_[layer_index]


class AutoencoderScoreCalculator(ScoreCalculator):
    """Reconstruction error of a pretrain layer — AutoEncoder or VAE, both
    expose ``reconstruct`` (reference ``AutoencoderScoreCalculator.java``).
    Works on MLN (int layer index) and CG (layer name or index)."""

    minimize_score = True

    def __init__(self, metric: str, iterator, layer_index=0):
        self.metric = metric.lower()
        self.iterator = iterator
        self.layer_index = layer_index

    def calculate_score(self, model) -> float:
        self._fresh(self.iterator)
        total, count = 0.0, 0
        layer, lparams = _resolve_pretrain_layer(model, self.layer_index)
        for ds in self.iterator:
            x = np.asarray(ds.features)
            recon = np.asarray(layer.reconstruct(lparams, x))
            if self.metric == "mse":
                err = ((recon - x) ** 2).sum()
            else:  # mae
                err = np.abs(recon - x).sum()
            total += float(err)
            count += x.shape[0]
        self.iterator.reset()
        return total / max(count, 1)


class VAEReconErrorScoreCalculator(AutoencoderScoreCalculator):
    """Alias with reference-parity name (reference
    ``VAEReconErrorScoreCalculator.java``); same reconstruct-and-accumulate
    loop as AutoencoderScoreCalculator."""


class VAEReconProbScoreCalculator(ScoreCalculator):
    """VAE reconstruction log-probability, maximized (reference
    ``VAEReconProbScoreCalculator.java``)."""

    minimize_score = False

    def __init__(self, iterator, layer_index=0, num_samples: int = 1,
                 log_prob: bool = True):
        self.iterator = iterator
        self.layer_index = layer_index
        self.num_samples = num_samples
        self.log_prob = log_prob

    def calculate_score(self, model) -> float:
        self._fresh(self.iterator)
        total, count = 0.0, 0
        layer, lparams = _resolve_pretrain_layer(model, self.layer_index)
        for ds in self.iterator:
            x = np.asarray(ds.features)
            lp = np.asarray(
                layer.reconstruction_log_probability(
                    lparams, x, self.num_samples
                )
            )
            total += float(lp.sum())
            count += x.shape[0]
        self.iterator.reset()
        avg = total / max(count, 1)
        return avg if self.log_prob else math.exp(avg)


# --------------------------------------------------------------------------
# Termination conditions (reference termination/*)
# --------------------------------------------------------------------------
class EpochTerminationCondition:
    # score-dependent conditions are only checked on epochs where the score
    # calculator actually ran (reference BaseEarlyStoppingTrainer semantics);
    # pure epoch-count conditions check every epoch
    requires_score = True

    def initialize(self) -> None:
        pass

    def terminate(self, epoch_num: int, score: float, minimize: bool) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    requires_score = False

    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch_num, score, minimize):
        return epoch_num + 1 >= self.max_epochs

    def __str__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop when no improvement for N consecutive epochs (reference
    ``ScoreImprovementEpochTerminationCondition.java``)."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = int(max_epochs_without_improvement)
        self.min_improvement = float(min_improvement)
        self.best_score: Optional[float] = None
        self.epochs_without = 0

    def initialize(self):
        self.best_score = None
        self.epochs_without = 0

    def terminate(self, epoch_num, score, minimize):
        if self.best_score is None:
            self.best_score = score
            return False
        improvement = (self.best_score - score) if minimize else (score - self.best_score)
        if improvement > self.min_improvement:
            self.best_score = score
            self.epochs_without = 0
            return False
        self.epochs_without += 1
        return self.epochs_without >= self.patience

    def __str__(self):
        return (f"ScoreImprovementEpochTerminationCondition(patience={self.patience}, "
                f"minImprovement={self.min_improvement})")


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop as soon as score is better than a target (reference
    ``BestScoreEpochTerminationCondition.java``)."""

    def __init__(self, best_expected_score: float):
        self.best_expected_score = float(best_expected_score)

    def terminate(self, epoch_num, score, minimize):
        return score < self.best_expected_score if minimize else score > self.best_expected_score

    def __str__(self):
        return f"BestScoreEpochTerminationCondition({self.best_expected_score})"


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Stop if score exceeds a ceiling — divergence guard (reference
    ``MaxScoreIterationTerminationCondition.java``)."""

    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def terminate(self, last_score):
        return last_score > self.max_score

    def __str__(self):
        return f"MaxScoreIterationTerminationCondition({self.max_score})"


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_time_seconds: float):
        self.max_time_seconds = float(max_time_seconds)
        self._start = None

    def initialize(self):
        # EarlyStoppingTrainer.fit() calls this BEFORE the first epoch, so
        # setup/jit-compile time ahead of iteration 1 counts against the
        # time budget (tests/test_fault_tolerance.py pins this down)
        self._start = time.monotonic()

    def terminate(self, last_score):
        if self._start is None:
            # standalone use without a trainer: fall back to first-call
            # arming (the trainer path never hits this)
            self._start = time.monotonic()
        return time.monotonic() - self._start > self.max_time_seconds

    def __str__(self):
        return f"MaxTimeIterationTerminationCondition({self.max_time_seconds}s)"


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, last_score):
        return math.isnan(last_score) or math.isinf(last_score)

    def __str__(self):
        return "InvalidScoreIterationTerminationCondition()"


# --------------------------------------------------------------------------
# Model savers (reference saver/*)
# --------------------------------------------------------------------------
class EarlyStoppingModelSaver:
    def save_best_model(self, model, score: float) -> None:
        raise NotImplementedError

    def save_latest_model(self, model, score: float) -> None:
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError

    def get_latest_model(self):
        raise NotImplementedError


class InMemoryModelSaver(EarlyStoppingModelSaver):
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, model, score):
        self._best = model.clone()

    def save_latest_model(self, model, score):
        self._latest = model.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver(EarlyStoppingModelSaver):
    """Saves best/latest model zips in a directory (reference
    ``LocalFileModelSaver.java``; also covers the graph variant)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._model_cls = None

    def _save(self, model, fname):
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        self._model_cls = type(model)
        ModelSerializer.write_model(model, os.path.join(self.directory, fname))

    def _load(self, fname):
        from deeplearning4j_tpu.train.model_serializer import ModelGuesser

        path = os.path.join(self.directory, fname)
        if not os.path.exists(path):
            return None
        return ModelGuesser.load_model_guess(path)

    def save_best_model(self, model, score):
        self._save(model, "bestModel.bin")

    def save_latest_model(self, model, score):
        self._save(model, "latestModel.bin")

    def get_best_model(self):
        return self._load("bestModel.bin")

    def get_latest_model(self):
        return self._load("latestModel.bin")


# --------------------------------------------------------------------------
# Configuration + result (reference EarlyStoppingConfiguration/Result)
# --------------------------------------------------------------------------
class EarlyStoppingConfiguration:
    def __init__(
        self,
        score_calculator: ScoreCalculator,
        epoch_termination_conditions: Optional[List[EpochTerminationCondition]] = None,
        iteration_termination_conditions: Optional[List[IterationTerminationCondition]] = None,
        model_saver: Optional[EarlyStoppingModelSaver] = None,
        save_last_model: bool = False,
        evaluate_every_n_epochs: int = 1,
    ):
        self.score_calculator = score_calculator
        self.epoch_termination_conditions = list(epoch_termination_conditions or [])
        self.iteration_termination_conditions = list(iteration_termination_conditions or [])
        self.model_saver = model_saver if model_saver is not None else InMemoryModelSaver()
        self.save_last_model = save_last_model
        self.evaluate_every_n_epochs = int(evaluate_every_n_epochs)

    class Builder:
        def __init__(self):
            self._kw: Dict[str, Any] = {}

        def score_calculator(self, sc):
            self._kw["score_calculator"] = sc
            return self

        def epoch_termination_conditions(self, *conds):
            self._kw["epoch_termination_conditions"] = list(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._kw["iteration_termination_conditions"] = list(conds)
            return self

        def model_saver(self, saver):
            self._kw["model_saver"] = saver
            return self

        def save_last_model(self, b: bool = True):
            self._kw["save_last_model"] = b
            return self

        def evaluate_every_n_epochs(self, n: int):
            self._kw["evaluate_every_n_epochs"] = n
            return self

        def build(self) -> "EarlyStoppingConfiguration":
            return EarlyStoppingConfiguration(**self._kw)


class EarlyStoppingResult:
    """(reference ``EarlyStoppingResult.java``)."""

    def __init__(
        self,
        termination_reason: str,
        termination_details: str,
        score_vs_epoch: Dict[int, float],
        best_model_epoch: int,
        best_model_score: float,
        total_epochs: int,
        best_model,
    ):
        self.termination_reason = termination_reason  # "Error"|"IterationTerminationCondition"|"EpochTerminationCondition"
        self.termination_details = termination_details
        self.score_vs_epoch = score_vs_epoch
        self.best_model_epoch = best_model_epoch
        self.best_model_score = best_model_score
        self.total_epochs = total_epochs
        self.best_model = best_model

    def get_best_model(self):
        return self.best_model

    def __repr__(self):
        return (
            f"EarlyStoppingResult(reason={self.termination_reason}, "
            f"details={self.termination_details}, bestEpoch={self.best_model_epoch}, "
            f"bestScore={self.best_model_score}, totalEpochs={self.total_epochs})"
        )


# --------------------------------------------------------------------------
# Trainer (reference trainer/BaseEarlyStoppingTrainer.java fit loop)
# --------------------------------------------------------------------------
class _IterationConditionListener:
    """Hooks iteration termination conditions into the fit loop via the
    listener SPI (the reference checks them inside its own loop)."""

    def __init__(self, conditions: List[IterationTerminationCondition]):
        self.conditions = conditions
        self.triggered: Optional[IterationTerminationCondition] = None

    def iteration_done(self, model, iteration, epoch):
        # float(score_) is a host sync per iteration — only pay it when
        # there are conditions to check
        if self.triggered is not None or not self.conditions:
            return
        score = float(model.score_) if model.score_ is not None else float("nan")
        for c in self.conditions:
            if c.terminate(score):
                self.triggered = c
                raise _IterationTerminated(c, score)

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass


class _IterationTerminated(Exception):
    def __init__(self, condition, score):
        self.condition = condition
        self.score = score


class EarlyStoppingTrainer:
    """Drives training with early stopping (reference
    ``EarlyStoppingTrainer``/``EarlyStoppingGraphTrainer``)."""

    def __init__(self, config: EarlyStoppingConfiguration, model, train_iterator,
                 listener: Optional[Any] = None):
        self.config = config
        self.model = model
        self.train_iterator = train_iterator
        self.listener = listener  # EarlyStoppingListener: on_start/on_epoch/on_completion

    def _fit_epoch(self) -> None:
        """One training epoch; overridden by the parallel trainer."""
        self.model._fit_one_epoch(self.train_iterator)

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        sc = cfg.score_calculator
        minimize = sc.minimize_score
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        if self.listener is not None and hasattr(self.listener, "on_start"):
            self.listener.on_start(cfg, self.model)

        score_vs_epoch: Dict[int, float] = {}
        best_score = math.inf if minimize else -math.inf
        best_epoch = -1
        epoch = 0

        saved_listeners = list(self.model.listeners)
        if cfg.iteration_termination_conditions:
            self.model.add_listeners(
                _IterationConditionListener(cfg.iteration_termination_conditions)
            )
        last_score = float("nan")
        try:
            while True:
                try:
                    self._fit_epoch()
                except _IterationTerminated as t:
                    reason = "IterationTerminationCondition"
                    details = str(t.condition)
                    # mid-epoch abort skips _fit_one_epoch's reset; leave the
                    # iterator clean for reuse
                    self.train_iterator.reset()
                    break
                except Exception as e:  # noqa: BLE001 — reference returns
                    # TerminationReason.Error instead of propagating
                    # (BaseEarlyStoppingTrainer.java catch-all in fit())
                    reason = "Error"
                    details = f"{type(e).__name__}: {e}"
                    try:
                        self.train_iterator.reset()  # clean for retry
                    except Exception:  # noqa: BLE001 — best-effort reset; the original error wins
                        pass
                    break

                terminate = False
                reason = ""
                details = ""
                if epoch % cfg.evaluate_every_n_epochs == 0:
                    score = sc.calculate_score(self.model)
                    last_score = score
                    score_vs_epoch[epoch] = score
                    if math.isnan(score):
                        # a NaN epoch score can never improve on best
                        # (NaN < best is False), so the loop would spin to
                        # MaxEpochs without ever saving a model — surface
                        # it as an error termination instead (reference
                        # EarlyStoppingTrainer invalid-score semantics)
                        reason = "Error"
                        details = (
                            f"Invalid (NaN) epoch score from "
                            f"{type(sc).__name__} at epoch {epoch} — "
                            "empty/exhausted evaluation iterator or "
                            "diverged model"
                        )
                        epoch += 1
                        break
                    improved = score < best_score if minimize else score > best_score
                    if improved:
                        best_score = score
                        best_epoch = epoch
                        cfg.model_saver.save_best_model(self.model, score)
                    if cfg.save_last_model:
                        cfg.model_saver.save_latest_model(self.model, score)
                    if self.listener is not None and hasattr(self.listener, "on_epoch"):
                        self.listener.on_epoch(epoch, score, cfg, self.model)
                evaluated = epoch % cfg.evaluate_every_n_epochs == 0
                # epoch-count conditions run every epoch (MaxEpochs cannot
                # overshoot with sparse evaluation); score-dependent ones only
                # when a fresh score exists — a stale score would count
                # non-evaluation epochs as "no improvement"
                for c in cfg.epoch_termination_conditions:
                    if c.requires_score and not evaluated:
                        continue
                    if c.terminate(epoch, last_score, minimize):
                        terminate = True
                        reason = "EpochTerminationCondition"
                        details = str(c)
                        break
                epoch += 1
                if terminate:
                    break
        finally:
            self.model.set_listeners(*saved_listeners)

        best_model = cfg.model_saver.get_best_model()
        if best_model is None:
            best_model = self.model
        result = EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            score_vs_epoch=score_vs_epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score if best_epoch >= 0 else float("nan"),
            total_epochs=epoch + (1 if reason == "IterationTerminationCondition" else 0),
            best_model=best_model,
        )
        if self.listener is not None and hasattr(self.listener, "on_completion"):
            self.listener.on_completion(result)
        return result


# Graph alias (reference has a separate class; surface parity)
EarlyStoppingGraphTrainer = EarlyStoppingTrainer


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping driving data-parallel training (reference
    ``EarlyStoppingParallelTrainer.java`` wraps ParallelWrapper): each
    epoch runs through the mesh-sharded wrapper instead of the
    single-device fit loop."""

    def __init__(self, config, model, train_iterator, wrapper=None,
                 listener=None):
        super().__init__(config, model, train_iterator, listener)
        if wrapper is None:
            from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

            wrapper = ParallelWrapper(model)
        self.wrapper = wrapper

    def _fit_epoch(self) -> None:
        self.wrapper.fit(self.train_iterator, epochs=1)
