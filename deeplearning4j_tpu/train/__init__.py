"""Training utilities: listeners, checkpointing, early stopping."""

from deeplearning4j_tpu.train.listeners import (
    CheckpointListener,
    CollectScoresIterationListener,
    EvaluativeListener,
    PerformanceListener,
    ScoreIterationListener,
    SleepyTrainingListener,
    TimeIterationListener,
    TrainingListener,
)

from deeplearning4j_tpu.train.faults import (
    FaultPolicy,
    TrainingDivergedError,
    fault_injection,
    latest_valid_checkpoint,
    load_latest_valid,
    prune_checkpoints,
    save_checkpoint,
    validate_checkpoint,
)
from deeplearning4j_tpu.train.model_serializer import ModelGuesser, ModelSerializer
from deeplearning4j_tpu.train.orbax_serializer import OrbaxModelSerializer

__all__ = [
    "TrainingListener", "ScoreIterationListener", "PerformanceListener",
    "CollectScoresIterationListener", "EvaluativeListener", "CheckpointListener",
    "TimeIterationListener", "SleepyTrainingListener",
    "ModelSerializer", "ModelGuesser", "OrbaxModelSerializer",
    "FaultPolicy", "TrainingDivergedError", "fault_injection",
    "latest_valid_checkpoint", "load_latest_valid", "prune_checkpoints",
    "save_checkpoint", "validate_checkpoint",
]
