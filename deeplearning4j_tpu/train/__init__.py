"""Training utilities: listeners, checkpointing, early stopping."""

from deeplearning4j_tpu.train.listeners import (
    CheckpointListener,
    CollectScoresIterationListener,
    EvaluativeListener,
    PerformanceListener,
    ScoreIterationListener,
    SleepyTrainingListener,
    TimeIterationListener,
    TrainingListener,
)

__all__ = [
    "TrainingListener", "ScoreIterationListener", "PerformanceListener",
    "CollectScoresIterationListener", "EvaluativeListener", "CheckpointListener",
    "TimeIterationListener", "SleepyTrainingListener",
]
