"""Training listeners.

Reference: ``optimize/api/TrainingListener.java:23-71`` SPI +
``optimize/listeners/{ScoreIterationListener,PerformanceListener,
EvaluativeListener,CollectScoresIterationListener,TimeIterationListener,
SleepyTrainingListener}.java`` and
``optimize/listeners/checkpoint/CheckpointListener.java:72-85``.

Note on async dispatch: the jitted train step returns the score as a device
scalar without blocking; a listener that reads ``model.score()`` forces a
sync. PerformanceListener therefore reports throughput based on wall time
between iterations (ETL + compute overlap included), syncing only at its
reporting frequency — keep ``frequency`` high for accurate TPU throughput.
"""

from __future__ import annotations

import logging
import re
import time
from typing import Callable, List, Optional

log = logging.getLogger(__name__)


class TrainingListener:
    """Base listener; all hooks are no-ops (reference ``TrainingListener``).

    Introspection hooks (``on_forward_pass`` / ``on_gradient_calculation``
    / ``on_backward_pass`` — reference ``TrainingListener.java:23-71``,
    SURVEY §7 hard-part 1): the functional core computes the whole train
    step as one jitted program, so these fire only when a registered
    listener actually OVERRIDES them; the network then runs one extra
    jitted forward+grad pass per iteration with the SAME rng the train
    step consumes — the reported activations/gradients are bit-identical
    to the step's, and the training trajectory is unchanged by attaching
    the listener. Plain fit paths only (tBPTT/pretrain steps do not
    introspect)."""

    def iteration_done(self, model, iteration: int, epoch: int) -> None:  # noqa: D401
        pass

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass

    def on_forward_pass(self, model, activations) -> None:
        """Per-layer (MLN: list) / per-vertex (CG: dict) activations of
        this iteration's forward pass, as host numpy arrays."""
        pass

    def on_gradient_calculation(self, model, gradients) -> None:
        """This iteration's gradients (same pytree structure as
        ``model.params_``), as host numpy arrays."""
        pass

    def on_backward_pass(self, model) -> None:
        pass

    def on_fit_end(self, model) -> None:
        """Fires when ``fit()`` returns — including by exception. The
        hook for releasing resources a mid-epoch abort would otherwise
        leak (ProfilerListener's open trace window)."""
        pass

    def needs_introspection(self, next_iteration: int) -> bool:
        """Whether the introspection hooks should fire for the upcoming
        iteration. Listeners that only sample (e.g. StatsListener at
        reportingFrequency) override this so the extra forward+grad pass
        is skipped on non-reporting iterations."""
        return True


def _has_hook(lst, name: str) -> bool:
    """Listener provides its own implementation of ``name`` — as a class
    override or an instance-bound attribute (StatsListener binds hooks in
    __init__ only when collection is requested). Duck-typed listeners
    that don't subclass TrainingListener and don't define the hook at
    all are NOT hook providers (the listener SPI is duck-typed
    everywhere else — e.g. early stopping's internal condition
    listener)."""
    if name in lst.__dict__:
        return True
    impl = getattr(type(lst), name, None)
    return impl is not None and impl is not getattr(TrainingListener, name)


def _overrides(listeners, name: str, next_iteration: Optional[int] = None) -> bool:
    """True if any listener provides ``name`` (and, when
    ``next_iteration`` is given, wants introspection for it).
    Introspection is pay-for-use: nothing extra runs otherwise."""
    return bool(_hook_recipients(listeners, name, next_iteration))


def dispatch_fit_end(listeners, model) -> None:
    """Deliver ``on_fit_end`` to every listener providing it (duck-typed
    like the epoch hooks); called from the fit paths' ``finally`` so an
    exception mid-epoch still releases listener-held resources. Each
    listener's hook is exception-isolated: a failing cleanup must not
    stop the remaining listeners' cleanup, skip the fit path's own
    teardown (the ZeRO-1 opt-state gather), or mask the original fit
    error raised from inside the ``finally``."""
    for lst in listeners:
        hook = getattr(lst, "on_fit_end", None)
        if hook is not None:
            try:
                hook(model)
            except Exception:  # noqa: BLE001 — logged; a dying listener must not mask fit's exit path
                log.exception("on_fit_end failed for %s",
                              type(lst).__name__)


def _hook_recipients(listeners, name: str,
                     next_iteration: Optional[int] = None) -> list:
    """The listeners that provide ``name`` AND want introspection for
    ``next_iteration`` — hooks are delivered per listener, so a sampled
    listener (StatsListener at reportingFrequency) never pays device→host
    copies for iterations an always-on listener requested."""
    def wants(lst):
        gate = getattr(lst, "needs_introspection", None)
        return (next_iteration is None or gate is None
                or gate(next_iteration))

    return [lst for lst in listeners if _has_hook(lst, name) and wants(lst)]


class ScoreIterationListener(TrainingListener):
    """Logs/prints the score every N iterations (reference
    ``ScoreIterationListener``).

    Bundle-aware (train/pipeline.py): under ``steps_per_call>1`` the
    ``bundle_done`` hook replaces the per-step ``iteration_done`` calls —
    the per-step losses arrive as one stacked device array whose host
    copy is fetched at most once per bundle (and only on bundles that
    contain a reporting iteration), never one ``model.score()`` sync per
    hit."""

    def __init__(self, print_iterations: int = 10, printer: Optional[Callable[[str], None]] = None):
        self.print_iterations = max(1, int(print_iterations))
        self.printer = printer or (lambda s: log.info(s))

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.print_iterations == 0:
            self.printer(f"Score at iteration {iteration} is {model.score():.6f}")

    def bundle_done(self, model, it0, epoch, scores):
        hits = [j for j in range(len(scores))
                if (it0 + j + 1) % self.print_iterations == 0]
        if not hits:
            return
        host = scores.host()  # one fetch per bundle, shared by all hits
        for j in hits:
            self.printer(f"Score at iteration {it0 + j + 1} is "
                         f"{float(host[j]):.6f}")


class CollectScoresIterationListener(TrainingListener):
    """Collects (iteration, score) pairs (reference
    ``CollectScoresIterationListener``).

    Bundle-aware: with ``steps_per_call>1`` the scores of a whole bundle
    are recorded from ONE deferred host fetch of the stacked device
    losses instead of a ``model.score()`` sync per sampled step."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, int(frequency))
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.score()))

    def bundle_done(self, model, it0, epoch, scores):
        hits = [j for j in range(len(scores))
                if (it0 + j + 1) % self.frequency == 0]
        if not hits:
            return
        host = scores.host()  # one fetch per bundle
        for j in hits:
            self.scores.append((it0 + j + 1, float(host[j])))


class PerformanceListener(TrainingListener):
    """samples/sec + batches/sec (reference ``PerformanceListener.java:22-87``).

    Accounting: every hook call contributes ITS batch's actual size (the
    fit paths publish ``model.last_batch_size`` per dispatched batch/
    bundle), accumulated across the window — variable batch sizes and
    ragged epoch tails report true samples/sec instead of the last batch
    size extrapolated over the whole window. When the async data
    pipeline's wait counters are live (obs/metrics.py, populated by
    AsyncDataSetIterator), the report appends the share of wall time the
    fit loop spent waiting on an empty prefetch queue — the
    input-bound vs compute-bound verdict."""

    def __init__(self, frequency: int = 10, report_score: bool = False,
                 printer: Optional[Callable[[str], None]] = None):
        self.frequency = max(1, int(frequency))
        self.report_score = report_score
        self.printer = printer or (lambda s: log.info(s))
        self._last_time: Optional[float] = None
        self._last_iter = 0
        self._samples = 0
        self._wait0: Optional[float] = None
        self.last_samples_per_sec: Optional[float] = None
        self.last_batches_per_sec: Optional[float] = None
        self.last_input_bound_share: Optional[float] = None

    @staticmethod
    def _consumer_wait_s() -> float:
        # thread-local: this listener runs on its fit loop's thread, so
        # the total is THIS fit's waits even when several fits run
        # concurrently (tuner pool engine)
        from deeplearning4j_tpu.obs.metrics import (
            thread_consumer_wait_seconds,
        )

        return thread_consumer_wait_seconds()

    def _window(self, it: int, samples: int, score_fn) -> None:
        self._samples += samples
        if self._last_time is None:
            # window baseline: this iteration's samples belong to no
            # open window
            self._last_time = time.perf_counter()
            self._last_iter = it
            self._samples = 0
            self._wait0 = self._consumer_wait_s()
            return
        if (it - self._last_iter) < self.frequency:
            return
        now = time.perf_counter()
        dt = now - self._last_time
        batches = it - self._last_iter
        self.last_batches_per_sec = batches / dt
        msg = f"iteration {it}: {self.last_batches_per_sec:.2f} batches/sec"
        if self._samples:
            self.last_samples_per_sec = self._samples / dt
            msg += f", {self.last_samples_per_sec:.1f} samples/sec"
        wait1 = self._consumer_wait_s()
        if self._wait0 is not None and dt > 0:
            share = min(max(wait1 - self._wait0, 0.0) / dt, 1.0)
            self.last_input_bound_share = share
            if wait1 > self._wait0:
                msg += (f", queue-wait {share:.0%} "
                        f"({'input' if share >= 0.5 else 'compute'}-bound)")
        if self.report_score:
            msg += f", score {score_fn():.6f}"
        self.printer(msg)
        self._last_time = now
        self._last_iter = it
        self._samples = 0
        self._wait0 = wait1

    def iteration_done(self, model, iteration, epoch):
        bs = int(getattr(model, "last_batch_size", None) or 0)
        self._window(iteration, bs, lambda: model.score())

    def bundle_done(self, model, it0, epoch, scores):
        """Bundled fits time whole bundles: the per-step replay fires
        back-to-back after the fused dispatch, so per-step wall-clock
        deltas inside a bundle are ~0 and would report absurd rates.
        Batches within one bundle share a size by construction, so
        ``last_batch_size * k`` is this bundle's exact sample count."""
        k = len(scores)
        bs = int(getattr(model, "last_batch_size", None) or 0)
        self._window(it0 + k, bs * k, lambda: float(scores.host()[-1]))


class TimeIterationListener(TrainingListener):
    """ETA logging (reference ``TimeIterationListener``)."""

    def __init__(self, iteration_count: int, frequency: int = 100,
                 printer: Optional[Callable[[str], None]] = None):
        self.iteration_count = iteration_count
        self.frequency = max(1, int(frequency))
        self.printer = printer or (lambda s: log.info(s))
        self.start = time.perf_counter()

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.perf_counter() - self.start
            remaining = (self.iteration_count - iteration) * elapsed / iteration
            self.printer(f"Remaining time estimate: {remaining:.1f}s ({iteration}/{self.iteration_count})")


class SleepyTrainingListener(TrainingListener):
    """Injects latency for race/pipeline testing (reference
    ``SleepyTrainingListener`` — SURVEY.md §4 mocks)."""

    def __init__(self, timer_iteration_ms: float = 0.0, timer_epoch_ms: float = 0.0):
        self.timer_iteration_ms = timer_iteration_ms
        self.timer_epoch_ms = timer_epoch_ms

    def iteration_done(self, model, iteration, epoch):
        if self.timer_iteration_ms > 0:
            time.sleep(self.timer_iteration_ms / 1000.0)

    def on_epoch_end(self, model):
        if self.timer_epoch_ms > 0:
            time.sleep(self.timer_epoch_ms / 1000.0)


class EvaluativeListener(TrainingListener):
    """Runs evaluation every N iterations/epochs (reference
    ``EvaluativeListener``). ``callback(listener, model, count, evaluation)``
    fires after each evaluation — the reference's ``EvaluationCallback``
    SPI (``listeners/callbacks/EvaluationCallback.java``); see
    :func:`model_saving_callback` for the ``ModelSavingCallback``
    counterpart."""

    def __init__(self, iterator, frequency: int = 1, invocation: str = "epoch_end",
                 printer: Optional[Callable[[str], None]] = None,
                 callback: Optional[Callable] = None):
        self.iterator = iterator
        self.frequency = max(1, int(frequency))
        self.invocation = invocation
        # per-iteration evaluations run the MODEL as of that iteration;
        # a bundled fit's post-bundle replay only has end-of-bundle
        # params, so iteration-end invocation forces steps_per_call=1
        # (train/pipeline.py); epoch-end evaluation bundles fine
        self.requires_per_step_state = invocation == "iteration_end"
        self.printer = printer or (lambda s: log.info(s))
        self.callback = callback
        self.evaluations: List[object] = []

    def _evaluate(self, model):
        ev = model.evaluate(self.iterator)
        self.evaluations.append(ev)
        self.printer(f"Evaluation: accuracy={ev.accuracy():.4f} f1={ev.f1():.4f}")
        if self.callback is not None:
            self.callback(self, model, len(self.evaluations), ev)

    def iteration_done(self, model, iteration, epoch):
        if self.invocation == "iteration_end" and iteration % self.frequency == 0:
            self._evaluate(model)

    def on_epoch_end(self, model):
        if self.invocation == "epoch_end" and (model.epoch % self.frequency == 0):
            self._evaluate(model)


class CheckpointListener(TrainingListener):
    """Periodic checkpoints with retention (reference
    ``CheckpointListener.java:72-85``: every N epochs/iterations/minutes,
    keepLast/keepAll/keepLastAndEvery)."""

    def __init__(
        self,
        directory: str,
        save_every_n_epochs: Optional[int] = None,
        save_every_n_iterations: Optional[int] = None,
        save_every_minutes: Optional[float] = None,
        keep_mode: str = "all",  # all | last | last_and_every
        keep_last: int = 1,
        keep_every: int = 0,
        serializer: str = "zip",  # zip (reference format) | orbax
    ):
        import os

        if serializer not in ("zip", "orbax"):
            raise ValueError(f"serializer must be 'zip' or 'orbax', got "
                             f"{serializer!r}")
        if serializer == "orbax" and save_every_minutes:
            # orbax saves are COLLECTIVE across processes; a per-process
            # wall-clock trigger can fire on one host and not another,
            # deadlocking the job. Iteration/epoch triggers are
            # deterministic across processes.
            raise ValueError(
                "serializer='orbax' requires an iteration- or epoch-based "
                "trigger (save_every_minutes is per-process wall clock and "
                "would deadlock multi-host collective saves)"
            )
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        from deeplearning4j_tpu.train.faults import sweep_stale_tmp

        # orphaned staging files from a PRIOR crashed atomic write are
        # swept (and counted in a tmp_sweep flight event) on dir open
        sweep_stale_tmp(directory, surface="checkpoint")
        self.save_every_n_epochs = save_every_n_epochs
        self.save_every_n_iterations = save_every_n_iterations
        self.save_every_minutes = save_every_minutes
        self.keep_mode = keep_mode
        # iteration/wall-clock-triggered saves must observe the model AT
        # each iteration; a bundled fit (train/pipeline.py) only has
        # end-of-bundle state when it replays iteration_done, so these
        # triggers force steps_per_call=1 (epoch-triggered checkpoints
        # bundle fine — on_epoch_end always sees real state)
        self.requires_per_step_state = bool(save_every_n_iterations
                                            or save_every_minutes)
        self.keep_last = keep_last
        self.keep_every = keep_every
        self.serializer = serializer
        self._last_save_time = time.perf_counter()
        self.checkpoints: List[str] = []
        self._ids: List[int] = []  # checkpoint numbers aligned with paths
        # resume numbering after existing checkpoints: a restarted run
        # never collides with (or overwrites into) a prior run's
        # directories — required for multi-host orbax, where overwriting
        # a shared directory is refused
        existing = [
            int(m.group(1)) for f in os.listdir(directory)
            for m in [re.match(r"checkpoint_(\d+)_", f)] if m
        ]
        self._counter = max(existing, default=0)

    def _save(self, model, iteration, epoch):
        import os

        import jax

        self._counter += 1
        stem = f"checkpoint_{self._counter}_iter_{iteration}_epoch_{epoch}"
        if self.serializer == "orbax":
            from deeplearning4j_tpu.train.orbax_serializer import (
                OrbaxModelSerializer,
            )

            path = os.path.join(self.directory, stem)
            # counter resume (__init__) makes collisions with prior runs
            # impossible; overwrite stays as a single-host backstop for
            # re-saving the same step (refused on multi-host by the
            # serializer)
            OrbaxModelSerializer.save(
                model, path, save_updater=True,
                overwrite=jax.process_count() == 1)
        else:
            from deeplearning4j_tpu.train.model_serializer import ModelSerializer

            path = os.path.join(self.directory, stem + ".zip")
            ModelSerializer.write_model(model, path, save_updater=True)
        from deeplearning4j_tpu.obs import flight as _flight

        _flight.record("checkpoint_write", path=path,
                       iteration=int(iteration), epoch=int(epoch))
        self.checkpoints.append(path)
        self._ids.append(self._counter)
        self._apply_retention()

    def _apply_retention(self):
        import os
        import shutil

        import jax

        if self.keep_mode == "all":
            return
        keep = set(self.checkpoints[-self.keep_last:])
        if self.keep_mode == "last_and_every" and self.keep_every > 0:
            # index by checkpoint NUMBER, not list position — positions
            # drift as earlier checkpoints are removed
            for cid, p in zip(self._ids, self.checkpoints):
                if cid % self.keep_every == 0:
                    keep.add(p)
        # orbax checkpoints live in a SHARED directory: delete from
        # process 0 only. Zip checkpoints are written per-process (no
        # gating in ModelSerializer), so every process cleans its own.
        do_fs = self.serializer != "orbax" or jax.process_index() == 0
        for cid, p in zip(list(self._ids), list(self.checkpoints)):
            if p in keep:
                continue
            if do_fs and os.path.exists(p):
                if os.path.isdir(p):
                    shutil.rmtree(p)  # orbax checkpoints are directories
                else:
                    os.remove(p)
            i = self.checkpoints.index(p)
            del self.checkpoints[i]
            del self._ids[i]

    def iteration_done(self, model, iteration, epoch):
        if self.save_every_n_iterations and iteration % self.save_every_n_iterations == 0:
            self._save(model, iteration, epoch)
        elif self.save_every_minutes:
            if (time.perf_counter() - self._last_save_time) >= self.save_every_minutes * 60:
                self._save(model, iteration, epoch)
                self._last_save_time = time.perf_counter()

    def on_epoch_end(self, model):
        if self.save_every_n_epochs and model.epoch % self.save_every_n_epochs == 0:
            self._save(model, model.iteration, model.epoch)


class RegistryPublishListener(CheckpointListener):
    """CheckpointListener that additionally PUBLISHES every checkpoint
    it writes to a serving :class:`~serving.registry.ModelRegistry` —
    the training half of the continuous train→serve loop: a long
    ``fit()`` ships snapshots to live traffic on the checkpoint cadence,
    each gated by a held-out validation step before any serving process
    will canary it.

    - ``validator``: callable ``model → float`` scoring the LIVE model
      on held-out data at publish time (e.g.
      ``DataSetLossCalculator(val_iter).calculate_score``). The registry
      refuses non-finite or regressed scores typed — a NaN-poisoned or
      regressed snapshot is journaled ``rejected`` and never activated,
      and training CONTINUES (a refused publish must never kill the fit
      that produced it; the refusal lands in ``self.refused`` and the
      flight recorder).
    - Transient store failures (NFS hiccup, disk pressure) retry with
      bounded exponential backoff (``max_attempts`` × ``backoff_s·2^k``)
      — validation refusals are typed verdicts, not transients, and are
      never retried.
    """

    def __init__(self, directory: str, registry, model_name: str,
                 validator: Optional[Callable] = None,
                 max_attempts: int = 3, backoff_s: float = 0.25,
                 **checkpoint_kwargs):
        if checkpoint_kwargs.get("serializer", "zip") != "zip":
            raise ValueError(
                "RegistryPublishListener publishes zip checkpoints; "
                "serializer='orbax' directories are not publishable")
        super().__init__(directory, **checkpoint_kwargs)
        self.registry = registry
        self.model_name = str(model_name)
        self.validator = validator
        self.max_attempts = max(int(max_attempts), 1)
        self.backoff_s = float(backoff_s)
        #: version records the registry accepted, in publish order
        self.published: List[dict] = []
        #: (path, reason) pairs the validation gate refused
        self.refused: List[tuple] = []

    def _save(self, model, iteration, epoch):
        super()._save(model, iteration, epoch)
        self.publish(model, self.checkpoints[-1], iteration)

    def publish(self, model, path: str, iteration: int) -> Optional[dict]:
        from deeplearning4j_tpu.serving.registry import (
            SnapshotValidationError,
        )

        score = None
        if self.validator is not None:
            try:
                score = float(self.validator(model))
            except Exception:  # noqa: BLE001 — a broken validator must
                # not kill training; an unscored publish is refused by
                # the gate below, which is the safe outcome
                log.exception("validation step failed for %s at %s",
                              self.model_name, path)
        last_err: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                rec = self.registry.publish(
                    self.model_name, path, score=score,
                    iteration=int(iteration),
                    allow_unvalidated=self.validator is None)
                self.published.append(rec)
                return rec
            except SnapshotValidationError as e:
                # typed refusal — the gate worked; record and move on
                self.refused.append((path, str(e)))
                log.warning("publish refused: %s", e)
                return None
            except OSError as e:
                last_err = e
                time.sleep(self.backoff_s * (2 ** attempt))
        from deeplearning4j_tpu.obs import flight as _flight

        _flight.record("publish_failed", model=self.model_name,
                       path=str(path),
                       error=type(last_err).__name__ if last_err else None,
                       attempts=self.max_attempts)
        log.error("publish of %s failed after %d attempts: %s", path,
                  self.max_attempts, last_err)
        return None


class ProfilerListener(TrainingListener):
    """Captures an XLA/xprof trace for a window of training iterations
    (the TPU-native replacement for ND4J's executioner profiling modes,
    SURVEY.md §5 tracing: "XLA profiler/xprof traces replace (b)-(c)").

    Starts ``jax.profiler.start_trace(log_dir)`` at ``start_iteration``
    and stops after ``num_iterations``; the trace opens in TensorBoard's
    profile plugin or Perfetto."""

    # the start/stop window brackets specific iterations' device work —
    # replayed post-bundle both hooks would fire back to back around no
    # dispatches; forces steps_per_call=1 (train/pipeline.py)
    requires_per_step_state = True

    def __init__(self, log_dir: str, start_iteration: int = 5,
                 num_iterations: int = 3):
        self.log_dir = log_dir
        self.start_iteration = int(start_iteration)
        self.stop_iteration = int(start_iteration) + int(num_iterations)
        self._active = False
        self.completed = False

    def iteration_done(self, model, iteration, epoch):
        import jax

        if self.completed:
            return
        if not self._active and iteration >= self.start_iteration:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and iteration >= self.stop_iteration:
            if model.score_ is not None:
                jax.block_until_ready(model.score_)
            jax.profiler.stop_trace()
            self._active = False
            self.completed = True

    def _close(self, model) -> None:
        import jax

        if model is not None and getattr(model, "score_", None) is not None:
            try:
                jax.block_until_ready(model.score_)
            except Exception:  # noqa: BLE001 — closing the trace matters more than draining
                pass  # closing the trace matters more than draining
        jax.profiler.stop_trace()
        self._active = False
        self.completed = True

    def on_epoch_end(self, model):
        if self._active:  # epoch ended inside the window: close cleanly
            self._close(model)

    def on_fit_end(self, model):
        """A window spanning the final partial epoch (or an epoch that
        raised) would leak an open ``jax.profiler`` trace — the next
        ``start_trace`` in the process then fails. fit() exit closes it
        unconditionally."""
        if self._active:
            self._close(model)


class ComposableIterationListener(TrainingListener):
    """Delegate every hook to a list of listeners (reference
    ``ComposableIterationListener.java`` — composes listeners handed
    around as one object)."""

    def __init__(self, *listeners):
        self.listeners = list(listeners[0]) if (
            len(listeners) == 1 and isinstance(listeners[0], (list, tuple))
        ) else list(listeners)

    def iteration_done(self, model, iteration, epoch):
        for l in self.listeners:
            l.iteration_done(model, iteration, epoch)

    def on_epoch_start(self, model):
        for l in self.listeners:
            if hasattr(l, "on_epoch_start"):
                l.on_epoch_start(model)

    def on_epoch_end(self, model):
        for l in self.listeners:
            if hasattr(l, "on_epoch_end"):
                l.on_epoch_end(model)

    def on_fit_end(self, model):
        dispatch_fit_end(self.listeners, model)

    def telemetry_done(self, model, it0, epoch, telem):
        """Composed children share the one BundleTelemetry (and its
        single host fetch) exactly like top-level listeners."""
        from deeplearning4j_tpu.obs.telemetry import dispatch_telemetry

        dispatch_telemetry(self.listeners, model, it0, epoch, telem)

    def needs_introspection(self, next_iteration: int) -> bool:
        return any(
            _has_hook(l, "on_forward_pass")
            or _has_hook(l, "on_gradient_calculation")
            for l in self.listeners
            if getattr(l, "needs_introspection",
                       lambda _: True)(next_iteration)
        )

    def bundling_blockers(self):
        """Per-step-callback needs of the COMPOSED listeners
        (train/pipeline.py consults this instead of this class's own
        delegating hook overrides, which would otherwise read as
        always-blocking and silently disable bundling)."""
        from deeplearning4j_tpu.train import pipeline

        return pipeline.bundling_blockers(self.listeners)

    def bundle_done(self, model, it0, epoch, scores):
        """Bundled delivery to the composed listeners: bundle-aware
        children share the once-per-bundle score fetch, legacy children
        get the per-step replay (same contract as the fit loops')."""
        from deeplearning4j_tpu.train import pipeline

        pipeline.dispatch_bundle_to(self.listeners, model, it0, epoch,
                                    scores)

    def on_forward_pass(self, model, activations):
        for l in _hook_recipients(self.listeners, "on_forward_pass"):
            l.on_forward_pass(model, activations)

    def on_gradient_calculation(self, model, gradients):
        for l in _hook_recipients(self.listeners, "on_gradient_calculation"):
            l.on_gradient_calculation(model, gradients)

    def on_backward_pass(self, model):
        for l in _hook_recipients(self.listeners, "on_backward_pass"):
            l.on_backward_pass(model)


def _named_leaves(tree):
    import jax
    import numpy as np

    out = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        parts = []
        for k in path:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        out.append(("/".join(parts), np.asarray(leaf)))
    return out


class ParamAndGradientIterationListener(TrainingListener):
    """Per-parameter statistics of params AND gradients every
    ``iterations`` steps, tab-delimited to stdout and/or a file
    (reference ``ParamAndGradientIterationListener.java``: printMean /
    printMinMax / printMeanAbsValue flags, header line, delimiter).

    ``gradients`` selects where gradient statistics come from:

    - ``"per_param"`` (default, the reference behavior): the
      introspection hook delivers the full gradient pytree per step —
      this genuinely snapshots per-step model state, so it forces
      ``steps_per_call=1`` (train/pipeline.py bundling audit).
    - ``"telemetry"``: per-step GLOBAL norms (grad/param/update norm,
      update:param ratio, loss scale) from the in-graph telemetry stream
      (obs/telemetry.py) — exact per-step values with NO per-step host
      callback, so bundled fits keep their K. Requires the model to
      train with a TelemetryConf; without one no rows are emitted.
    - ``"none"``: parameter statistics only; bundles freely.
    """

    def __init__(self, iterations: int = 1, print_header: bool = True,
                 print_mean: bool = True, print_min_max: bool = True,
                 print_mean_abs_value: bool = True,
                 output_to_console: bool = True, file: Optional[str] = None,
                 delimiter: str = "\t", gradients: str = "per_param"):
        if gradients not in ("per_param", "telemetry", "none"):
            raise ValueError(
                f"gradients must be 'per_param', 'telemetry' or 'none', "
                f"got {gradients!r}")
        self.iterations = max(int(iterations), 1)
        self.print_header = print_header
        self.print_mean = print_mean
        self.print_min_max = print_min_max
        self.print_mean_abs_value = print_mean_abs_value
        self.output_to_console = output_to_console
        self.file = file
        self.delimiter = delimiter
        self.gradients = gradients
        if gradients == "per_param":
            # instance-bound only in this mode, so the bundling audit
            # sees the per-step hook exactly when it is really needed
            self.on_gradient_calculation = self._on_gradient_calculation
        self._grads = None
        self._telem = None  # (it0, BundleTelemetry) from telemetry_done
        self._header_written = False
        if file:  # truncate once per listener lifetime. Routed through
            # the injectable fs layer (surface=diagnostics) like every
            # other write under train/: chaos plans can target it, and
            # a full disk surfaces as a typed StorageError instead of a
            # bare OSError mid-fit
            from deeplearning4j_tpu.chaos import fslayer as _fs

            _fs.open_for_write(file, "w", surface="diagnostics").close()

    def needs_introspection(self, next_iteration: int) -> bool:
        return next_iteration % self.iterations == 0

    def _on_gradient_calculation(self, model, gradients):
        self._grads = gradients

    def telemetry_done(self, model, it0, epoch, telem):
        if self.gradients == "telemetry":
            self._telem = (it0, telem)

    def _stats(self, arr):
        import numpy as np

        cols = []
        if self.print_mean:
            cols.append(float(np.mean(arr)))
        if self.print_min_max:
            cols.extend([float(np.min(arr)), float(np.max(arr))])
        if self.print_mean_abs_value:
            cols.append(float(np.mean(np.abs(arr))))
        return cols

    def _stat_names(self):
        names = []
        if self.print_mean:
            names.append("mean")
        if self.print_min_max:
            names.extend(["min", "max"])
        if self.print_mean_abs_value:
            names.append("meanAbs")
        return names

    def _emit(self, line: str):
        if self.output_to_console:
            print(line)
        if self.file:
            from deeplearning4j_tpu.chaos import fslayer as _fs

            with _fs.open_for_write(self.file, "a",
                                    surface="diagnostics") as f:
                f.write(line + "\n")

    # -- telemetry mode: global-norm rows, bundling-compatible ---------------
    def _telem_rows(self, it0: int, k: int) -> None:
        telem = None
        if self._telem is not None and self._telem[0] == it0:
            telem = self._telem[1]
        self._telem = None
        if telem is None:
            return
        host = telem.host()  # the shared once-per-bundle fetch
        keys = sorted(host)
        if self.print_header and not self._header_written:
            self._emit(self.delimiter.join(["iteration"] + keys))
            self._header_written = True
        for j in range(k):
            it = it0 + j + 1
            if it % self.iterations:
                continue
            self._emit(self.delimiter.join(
                [str(it)] + [f"{float(host[key][j]):.6g}" for key in keys]))

    def bundle_done(self, model, it0, epoch, scores):
        if self.gradients == "telemetry":
            self._telem_rows(it0, len(scores))
        # per_param mode never sees bundles (the bound introspection hook
        # forces K=1); "none" mode park: per-parameter stats of
        # END-of-bundle params at the last in-bundle reporting hit
        elif any((it0 + j + 1) % self.iterations == 0
                 for j in range(len(scores))):
            self._param_row(model, it0 + len(scores))

    def iteration_done(self, model, iteration, epoch):
        if self.gradients == "telemetry":
            self._telem_rows(iteration - 1, 1)
            return
        if iteration % self.iterations:
            return
        self._param_row(model, iteration)

    def _param_row(self, model, iteration):
        params = _named_leaves(model.params_)
        grads = _named_leaves(self._grads) if self._grads is not None else []
        if self.print_header and not self._header_written:
            cols = ["iteration"]
            for name, _ in params:
                cols += [f"p_{name}_{s}" for s in self._stat_names()]
            for name, _ in grads:
                cols += [f"g_{name}_{s}" for s in self._stat_names()]
            self._emit(self.delimiter.join(cols))
            self._header_written = True
        vals = [str(iteration)]
        for _, a in params:
            vals += [f"{x:.6g}" for x in self._stats(a)]
        for _, a in grads:
            vals += [f"{x:.6g}" for x in self._stats(a)]
        self._emit(self.delimiter.join(vals))
        self._grads = None


def model_saving_callback(root_folder: str, filename_template: str):
    """EvaluationCallback that checkpoints the model after every
    evaluation (reference ``ModelSavingCallback.java``): ``%d`` in the
    template is replaced by the invocation count. Pass as
    ``EvaluativeListener(callback=...)``."""
    import os

    if not os.path.isdir(root_folder):
        raise ValueError(f"root_folder must be an existing directory: "
                         f"{root_folder!r}")
    if not filename_template:
        raise ValueError("filename_template can't be empty")

    def call(listener, model, count, evaluation):
        from deeplearning4j_tpu.train.model_serializer import ModelSerializer

        name = filename_template.replace("%d", str(count))
        ModelSerializer.write_model(model, os.path.join(root_folder, name))

    return call
