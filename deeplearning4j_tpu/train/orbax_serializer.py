"""TPU-native checkpointing via Orbax/TensorStore (SURVEY.md §7's
"native msgpack/tensorstore path" beside the reference-compat zip of
``model_serializer.py``).

Why a second format: the zip flattens every array to one host fp32
vector — correct, portable, but it gathers sharded params to host and
loses placement. The Orbax path saves the params/opt-state/layer-state
pytrees as TensorStore arrays: sharded (TP/EP-placed) models save
without gathering, restore onto the SAME shardings when a placed
template is supplied, and multi-host runs write cooperatively (each
process its own shards — the jax.distributed checkpoint story).

Layout: ``<dir>/conf.json``, ``<dir>/meta.json`` + Orbax trees under
``<dir>/params`` / ``<dir>/opt_state`` / ``<dir>/layer_state``.
"""

from __future__ import annotations

import json
import os
import shutil

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _barrier(name: str) -> None:
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


class OrbaxModelSerializer:
    @staticmethod
    def save(model, directory: str, save_updater: bool = True,
             overwrite: bool = False) -> str:
        """Save a MultiLayerNetwork / ComputationGraph to ``directory``.

        The directory must be absent or empty (periodic checkpointing
        should use per-step directories, e.g. ``ckpt/step_000100``);
        ``overwrite=True`` replaces an existing checkpoint atomically
        enough for single-host use (rmtree then rewrite)."""
        directory = os.path.abspath(directory)
        # during a ZeRO-1 sharded fit the live opt state is sharded and
        # model.opt_state_ is stale; the runtime installs this hook to
        # gather on demand (parallel/zero.py)
        sync = getattr(model, "_opt_state_sync", None)
        if sync is not None:
            sync()
        multi = jax.process_count() > 1
        # every process validates the PRE-EXISTING directory state BEFORE
        # anyone writes (the barrier below keeps writers from racing a
        # sibling's validation — without it, process 1 can observe
        # process 0's fresh metadata and wrongly refuse)
        error = None
        if os.path.isdir(directory) and os.listdir(directory):
            if not overwrite:
                error = (
                    f"checkpoint directory not empty: {directory} "
                    "(use per-step directories, or overwrite=True)"
                )
            elif multi:
                # no safe cross-process rmtree — refusing beats corrupting
                error = (
                    "overwrite=True is single-host only (rmtree races "
                    "concurrent writers); multi-host restarts must save "
                    "into fresh per-step directories"
                )
            else:
                shutil.rmtree(directory)
        if multi:
            # agree on validation BEFORE raising: a host that raised
            # alone would leave its siblings hanging in the barrier
            from jax.experimental import multihost_utils

            import numpy as _np

            oks = multihost_utils.process_allgather(
                _np.asarray(0 if error else 1, _np.int32))
            if int(_np.min(oks)) == 0 and error is None:
                error = ("checkpoint directory validation failed on "
                         "another process")
        if error is not None:
            raise ValueError(error)
        os.makedirs(directory, exist_ok=True)
        # metadata from one process only; Orbax coordinates the array
        # writes across processes itself
        if jax.process_index() == 0:
            from deeplearning4j_tpu.chaos import fslayer

            # stage+fsync+atomic-replace via the injectable fs layer: a
            # crash mid-write must never leave a torn conf/meta next to
            # valid Orbax arrays (typed StorageError on disk-full)
            fslayer.write_atomic(os.path.join(directory, "conf.json"),
                                 model.conf.to_json(),
                                 surface="checkpoint")
            meta = {
                "iteration": model.iteration,
                "epoch": model.epoch,
                "model_type": type(model).__name__,
                "save_updater": bool(save_updater),
                "framework": "deeplearning4j_tpu",
            }
            # data-position provenance, same contract as the zip
            # serializer's meta.json (model_serializer._build_meta)
            if getattr(model, "_data_state", None) is not None:
                meta["data"] = model._data_state
            fslayer.write_atomic(os.path.join(directory, "meta.json"),
                                 json.dumps(meta), surface="checkpoint")
        if multi:
            _barrier("dl4jtpu_orbax_meta")  # metadata visible before the
            # cooperative array writes begin
        ckptr = _checkpointer()
        try:
            ckptr.save(os.path.join(directory, "params"), model.params_)
            if save_updater and model.opt_state_ is not None:
                ckptr.save(os.path.join(directory, "opt_state"),
                           model.opt_state_)
            if model.state_ is not None:
                ckptr.save(os.path.join(directory, "layer_state"),
                           model.state_)
        finally:
            ckptr.close()  # waits for the async commits
        return directory

    @staticmethod
    def restore(directory: str, load_updater: bool = True,
                template=None):
        """Rebuild the network from ``conf.json`` and restore the pytrees.

        ``template``: an initialized (optionally mesh-PLACED) network to
        restore into — its array shardings become the restored arrays'
        shardings (the TP/EP path). Default: a fresh single-device
        ``init()`` of the saved configuration."""
        directory = os.path.abspath(directory)
        with open(os.path.join(directory, "meta.json")) as f:
            meta = json.load(f)
        net = template if template is not None else _build_from_conf(
            directory, meta)

        def abstract(tree):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=getattr(a, "sharding", None)),
                tree,
            )

        ckptr = _checkpointer()
        try:
            net.params_ = ckptr.restore(os.path.join(directory, "params"),
                                        abstract(net.params_))
            if load_updater and os.path.isdir(
                    os.path.join(directory, "opt_state")):
                net.opt_state_ = ckptr.restore(
                    os.path.join(directory, "opt_state"),
                    abstract(net.opt_state_))
            if os.path.isdir(os.path.join(directory, "layer_state")):
                state_dir = os.path.join(directory, "layer_state")
                try:
                    net.state_ = ckptr.restore(state_dir,
                                               abstract(net.state_))
                except (ValueError, KeyError, TypeError):
                    # layer-state forward compat: checkpoints written
                    # before a layer grew a state key (e.g. MoE
                    # expert_load) restore as-saved, with missing leaves
                    # filled from the freshly initialized template
                    net.state_ = _merge_state(net.state_,
                                              ckptr.restore(state_dir))
        finally:
            ckptr.close()
        net.iteration = meta.get("iteration", 0)
        net.epoch = meta.get("epoch", 0)
        if meta.get("data") is not None:
            net._data_state = meta["data"]
        return net


def _merge_state(template, saved):
    """Fill ``template``'s pytree with ``saved``'s leaves where present
    (dict keys by name, list/tuple entries by position); leaves absent
    from the checkpoint keep their initialized values."""
    if isinstance(template, dict):
        if not isinstance(saved, dict):
            return template
        return {k: _merge_state(v, saved[k]) if k in saved else v
                for k, v in template.items()}
    if isinstance(template, (list, tuple)):
        if not isinstance(saved, (list, tuple)) or len(saved) != len(template):
            return template
        merged = [_merge_state(t, s) for t, s in zip(template, saved)]
        return type(template)(merged) if isinstance(template, tuple) else merged
    if saved is None:
        return template
    sharding = getattr(template, "sharding", None)
    if sharding is not None and hasattr(sharding, "mesh"):
        # placed templates (TP/EP restore path) keep their placement even
        # for leaves coming through the target-less compat restore
        return jax.device_put(jax.numpy.asarray(saved), sharding)
    return saved


def _build_from_conf(directory: str, meta: dict):
    from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with open(os.path.join(directory, "conf.json")) as f:
        conf_json = f.read()
    if meta.get("model_type") == "ComputationGraph":
        from deeplearning4j_tpu.nn.conf.graph_builder import (
            ComputationGraphConfiguration,
        )

        conf = ComputationGraphConfiguration.from_json(conf_json)
        return ComputationGraph(conf, copy_conf=False).init()
    conf = MultiLayerConfiguration.from_json(conf_json)
    return MultiLayerNetwork(conf, copy_conf=False).init()
