"""Zip-format model checkpointing.

Reference: ``util/ModelSerializer.java:39-125`` — entries
``configuration.json`` + ``coefficients.bin`` + ``updaterState.bin``
(+ optional normalizer). Same layout here (float32 little-endian flattened
buffers; order documented in ``MultiLayerNetwork.params_flat``), plus two
additions the functional design needs: ``state.bin`` (BN running stats /
center-loss centers — the reference stores these inside params) and
``meta.json`` (iteration/epoch counters so optimizers resume exactly,
matching the reference's guarantee that updater state is part of the
checkpoint, SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Optional

import jax.numpy as jnp
import numpy as np

CONFIG_ENTRY = "configuration.json"
COEFFICIENTS_ENTRY = "coefficients.bin"
UPDATER_ENTRY = "updaterState.bin"
STATE_ENTRY = "state.bin"
META_ENTRY = "meta.json"
NORMALIZER_ENTRY = "normalizer.bin"


class ModelSerializer:
    @staticmethod
    def write_model(model, path: str, save_updater: bool = True, normalizer=None) -> None:
        from deeplearning4j_tpu.chaos import fslayer as _fs
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.obs import trace as _trace
        from deeplearning4j_tpu.train.faults import atomic_tmp_path

        # during a ZeRO-1 sharded fit the live opt state is sharded and
        # model.opt_state_ is stale; the runtime installs this hook to
        # gather on demand (parallel/zero.py)
        sync = getattr(model, "_opt_state_sync", None)
        if sync is not None:
            sync()
        # crash-safe: stage into a same-directory temp file, fsync it,
        # and publish with an atomic rename — a crash/SIGKILL mid-write
        # leaves the previous checkpoint at ``path`` untouched, never a
        # torn zip, and the rename never publishes un-synced bytes. A
        # FAILED write (disk full, failed fsync/replace — injectable via
        # the chaos fs seams) raises typed StorageError with the staging
        # file cleaned up and the previous checkpoint still loadable.
        tmp = atomic_tmp_path(path)
        try:
            # span: checkpoint writes show up in profiler traces as their
            # own box (they gather device state and hit disk — a classic
            # hidden stall between training dispatches)
            try:
                with _trace.span("checkpoint_write"), \
                        zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as z:
                    from deeplearning4j_tpu.chaos import hooks as _chaos

                    _chaos.fire("fs.write", path=str(tmp),
                                surface="checkpoint")
                    z.writestr(CONFIG_ENTRY, model.conf.to_json())
                    z.writestr(COEFFICIENTS_ENTRY, model.params_flat().astype("<f4").tobytes())
                    if save_updater and model.opt_state_ is not None:
                        z.writestr(UPDATER_ENTRY, model.opt_state_flat().astype("<f4").tobytes())
                    state_flat = _flatten_state(model.state_)
                    z.writestr(STATE_ENTRY, state_flat.astype("<f4").tobytes())
                    z.writestr(META_ENTRY, json.dumps(_build_meta(model)))
                    if normalizer is not None:
                        z.writestr(NORMALIZER_ENTRY, json.dumps(normalizer.to_dict()))
            except OSError as e:
                if isinstance(e, _fs.StorageError):
                    raise
                raise _fs.storage_error("write", tmp, "checkpoint", e) \
                    from e
            _fs.fsync_path(tmp, surface="checkpoint")
            _fs.replace(tmp, path, surface="checkpoint")
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    @staticmethod
    def _restore(path: str, conf_cls, net_cls, load_updater: bool):
        with zipfile.ZipFile(path, "r") as z:
            names = set(z.namelist())
            missing = {CONFIG_ENTRY, COEFFICIENTS_ENTRY} - names
            if missing:
                raise ValueError(
                    f"{path!r} is not a model checkpoint: required entries "
                    f"{sorted(missing)} are missing (zip contains "
                    f"{sorted(names)})"
                )
            conf = conf_cls.from_json(z.read(CONFIG_ENTRY).decode())
            net = net_cls(conf, copy_conf=False)  # conf is ours alone
            net.init()
            net.set_params_flat(np.frombuffer(z.read(COEFFICIENTS_ENTRY), dtype="<f4"))
            names = z.namelist()
            if load_updater and UPDATER_ENTRY in names:
                net.set_opt_state_flat(np.frombuffer(z.read(UPDATER_ENTRY), dtype="<f4"))
            if STATE_ENTRY in names:
                _unflatten_state(net, np.frombuffer(z.read(STATE_ENTRY), dtype="<f4"))
            if META_ENTRY in names:
                meta = json.loads(z.read(META_ENTRY).decode())
                net.iteration = meta.get("iteration", 0)
                net.epoch = meta.get("epoch", 0)
                _restore_meta_state(net, meta)
        return net

    @staticmethod
    def restore_multi_layer_network(path: str, load_updater: bool = True):
        from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        return ModelSerializer._restore(
            path, MultiLayerConfiguration, MultiLayerNetwork, load_updater
        )

    @staticmethod
    def restore_computation_graph(path: str, load_updater: bool = True):
        from deeplearning4j_tpu.nn.conf.graph_builder import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        return ModelSerializer._restore(
            path, ComputationGraphConfiguration, ComputationGraph, load_updater
        )

    @staticmethod
    def checkpoint_meta(path: str) -> dict:
        """Cheap peek at a checkpoint WITHOUT restoring it: the
        ``meta.json`` contents plus ``conf_json`` (the configuration
        entry as a string). The serving engine's hot reload compares
        ``conf_json`` against the live model to decide between a pure
        weight swap (same architecture — zero recompiles) and a full
        rebuild+rewarm; /healthz reports ``model_type`` from here."""
        with zipfile.ZipFile(path, "r") as z:
            names = set(z.namelist())
            missing = {CONFIG_ENTRY, COEFFICIENTS_ENTRY} - names
            if missing:
                raise ValueError(
                    f"{path!r} is not a model checkpoint: required entries "
                    f"{sorted(missing)} are missing")
            meta = (json.loads(z.read(META_ENTRY).decode())
                    if META_ENTRY in names else {})
            meta["conf_json"] = z.read(CONFIG_ENTRY).decode()
            meta["entries"] = sorted(names)
        return meta

    @staticmethod
    def restore_normalizer(path: str):
        with zipfile.ZipFile(path, "r") as z:
            if NORMALIZER_ENTRY not in z.namelist():
                return None
            from deeplearning4j_tpu.data.normalizers import Normalizer

            return Normalizer.from_dict(json.loads(z.read(NORMALIZER_ENTRY).decode()))


def _state_items(state):
    """Deterministic (container, key-path) walk over MLN (list-of-dict) and
    CG (dict-of-dict, sorted by vertex name) state layouts."""
    if isinstance(state, dict):
        groups = [state[k] for k in sorted(state)]
    else:
        groups = list(state or [])
    for s in groups:
        for name in sorted(s):
            yield s, name


def _flatten_state(state) -> np.ndarray:
    chunks = [
        np.asarray(s[name], np.float32).reshape(-1) for s, name in _state_items(state)
    ]
    return np.concatenate(chunks) if chunks else np.zeros((0,), np.float32)


def _unflatten_state(net, vec: np.ndarray) -> None:
    expected = sum(
        int(np.prod(s[name].shape)) for s, name in _state_items(net.state_)
    )
    if expected != vec.size:
        # layer-state layout changed since the checkpoint was written
        # (e.g. a layer grew a state key): the positional vector cannot be
        # mapped safely — keep the freshly initialized state (running
        # stats, observability signals) rather than mis-assigning slices
        import warnings

        warnings.warn(
            f"checkpoint layer-state size {vec.size} != current layout "
            f"{expected}; keeping freshly initialized layer state "
            "(params/updater are unaffected)",
            stacklevel=3,
        )
        return
    off = 0
    for s, name in _state_items(net.state_):
        n = int(np.prod(s[name].shape))
        s[name] = jnp.asarray(vec[off : off + n].reshape(s[name].shape), s[name].dtype)
        off += n


def _build_meta(model) -> dict:
    """``meta.json`` body. Besides the iteration/epoch counters this
    carries everything a device-count-portable resume needs that is not
    derivable from the weight entries (parallel/reshard.py):

    - ``rng``: the dropout-RNG chain position (the model's live PRNG
      key), so a resumed fit consumes the exact stream an uninterrupted
      run would have — including runs whose chain diverged from the
      pure split-``iteration``-times derivation (NaN-skipped bundles,
      tuner fast-forwards);
    - ``fault_state``: the in-graph fault-guard carry (bad/consec/good
      counters, loss scale) so Adam's ``good_count`` bias-correction
      clock and the loss-scale schedule survive a crash exactly;
    - ``topology``: device count + backend the checkpoint was written
      on — provenance only (the weight entries are canonical and
      topology-free), consumed for reshard N→M flight events.
    """
    import jax

    # the mesh the fit ACTUALLY used, read off the params' sharding —
    # not len(jax.devices()): a --workers 2 run on an 8-device host must
    # record n_devices=2 or every downstream N→M provenance is wrong
    n_devices = None
    for leaf in jax.tree_util.tree_leaves(getattr(model, "params_", None)):
        if isinstance(leaf, jax.Array):
            try:
                n_devices = len(leaf.sharding.device_set)
            except Exception:  # noqa: BLE001 — sharding is advisory meta
                pass
            break
    if n_devices is None:
        n_devices = len(jax.devices())
    meta = {
        "iteration": model.iteration,
        "epoch": model.epoch,
        "model_type": type(model).__name__,
        "framework": "deeplearning4j_tpu",
        "topology": {
            "n_devices": n_devices,
            "backend": jax.default_backend(),
        },
    }
    rng = getattr(model, "_rng", None)
    if rng is not None:
        meta["rng"] = [int(v) for v in np.asarray(rng).ravel()]
    fstate = getattr(model, "fault_state_", None)
    if fstate is not None:
        host = {k: np.asarray(v) for k, v in fstate.items()}
        fs = {k: (float(v) if np.issubdtype(v.dtype, np.floating)
                  else int(v))
              for k, v in host.items()}
        meta["fault_state"] = fs
    # data-position provenance (data/loader.py ShardedLoader.data_state):
    # the RNG chain above pins WHAT randomness resumes; this pins WHERE
    # in the batch stream — together a SIGKILL-mid-epoch resume replays
    # the exact stream an uninterrupted run would have consumed
    dstate = getattr(model, "_data_state", None)
    if dstate is not None:
        meta["data"] = dstate
    return meta


def _restore_meta_state(net, meta: dict) -> None:
    """Inverse of the portable-resume half of :func:`_build_meta`
    (missing keys — pre-PR-8 checkpoints — leave the freshly
    initialized chain/state, the old behavior)."""
    rng = meta.get("rng")
    if rng is not None and hasattr(net, "_rng"):
        net._rng = jnp.asarray(np.asarray(rng, np.uint32))
    if meta.get("data") is not None:
        net._data_state = meta["data"]
    fs = meta.get("fault_state")
    if fs and hasattr(net, "fault_state_"):
        st = {
            "bad_count": jnp.asarray(int(fs.get("bad_count", 0)), jnp.int32),
            "consec": jnp.asarray(int(fs.get("consec", 0)), jnp.int32),
            "good_count": jnp.asarray(
                int(fs.get("good_count", net.iteration)), jnp.int32),
        }
        if "loss_scale" in fs:
            st["loss_scale"] = jnp.asarray(float(fs["loss_scale"]),
                                           jnp.float32)
            st["scale_good"] = jnp.asarray(int(fs.get("scale_good", 0)),
                                           jnp.int32)
        net.fault_state_ = st


class ModelGuesser:
    """Sniff a saved file (reference ``util/ModelGuesser.java``)."""

    @staticmethod
    def load_model_guess(path: str):
        try:
            with zipfile.ZipFile(path, "r") as z:
                names = z.namelist()
                meta = (json.loads(z.read(META_ENTRY).decode())
                        if META_ENTRY in names else {})
        except zipfile.BadZipFile as e:
            raise ValueError(
                f"Cannot identify model format for {path!r}: not a readable "
                f"zip ({e})"
            ) from e
        if CONFIG_ENTRY in names and COEFFICIENTS_ENTRY in names:
            model_type = meta.get("model_type", "MultiLayerNetwork")
            if model_type == "ComputationGraph":
                return ModelSerializer.restore_computation_graph(path)
            return ModelSerializer.restore_multi_layer_network(path)
        raise ValueError(
            f"Cannot identify model format for {path!r}: expected checkpoint "
            f"entries [{CONFIG_ENTRY!r}, {COEFFICIENTS_ENTRY!r}] but the zip "
            f"contains {sorted(names)}"
        )
