"""Continuous-batching autoregressive generation engine.

The /predict path batches REQUESTS; autoregressive generation has to
batch TOKENS. A naive serving loop decodes one request at a time (the
device idles at batch 1) or dispatch-then-waits a fixed batch (every
request waits for the slowest's last token). Continuous batching — the
Orca/vLLM scheduling discipline — keeps ONE fixed-shape decode program
in flight and lets requests join and leave it **between token steps**:

- the engine owns a persistent **slot slab**: for TransformerLM an
  ``(n_layers, n_slots, heads, max_length, head_dim)`` KV cache pair
  (``init_decode_cache``); for recurrent nets (TextGenerationLSTM) the
  per-layer carried (h, c) state stacked to ``(n_slots, units)``;
- a request claims a free slot, **prefills** its prompt at a bucketed
  length (``prefill_bucket_lengths`` — the ``serving_seq_buckets``
  discipline, so prefill compiles a bounded program set), and joins the
  next decode step;
- every token step is ONE jitted dispatch for ALL active slots: the
  per-row-position ``decode_step`` + in-graph ``sample_next_device``
  (greedy/temperature/top-k/top-p as data, not program structure), so
  steady-state decode never recompiles and never round-trips the host
  per request — one small host sync per step streams every slot's new
  token;
- finished or deadline-expired requests free their slot **at token
  granularity**; the freed slot is re-prefilled by the next queued
  request while the other slots keep decoding.

Zero-recompile discipline (1810.09868 fixed-shape rationale) extended
to token granularity: the decode program's shapes are
``(n_slots, ...)`` forever; activity is a boolean mask. Parity: a slot
decoded among other slots is bit-identical to the same request decoded
alone (row-independent attention math — asserted in
tests/test_generate.py), so continuous batching is an *throughput*
optimization, never an output change. Documented tolerances: MoE
routing competes across co-resident slots (capacity effects — same
caveat as ``decode_step``), and top-p nucleus cutoffs can differ from
the host sampler at boundary ties (``sample_next_device``).

Typed failures reuse the batcher vocabulary: queue-full →
:class:`~.batcher.ServerOverloadedError` (HTTP 503), deadline →
:class:`~.batcher.RequestDeadlineExceeded` (504), window overflow →
:class:`~models.transformer_lm.ContextWindowExceeded` (400), slab
memory over budget → :class:`GenerationMemoryError` at build time.

Observability: flight-recorder slot lifecycle events (``slot_claim`` /
``slot_free`` / ``decode_stall``), rtrace stage timelines
(queue → prefill → decode → respond), and a
:class:`~.metrics.GenerationMetrics` registry surface
(``generation_tokens_per_sec``, slot occupancy, prefill/decode split).
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.chaos import hooks as chaos_hooks
from deeplearning4j_tpu.obs.lockwitness import witnessed_lock
from deeplearning4j_tpu.serving import rtrace
from deeplearning4j_tpu.serving.batcher import (
    RequestDeadlineExceeded,
    ServerOverloadedError,
    ServerShutdownError,
    ServingError,
)
from deeplearning4j_tpu.serving.metrics import GenerationMetrics


class GenerationMemoryError(ServingError):
    """The requested ``n_slots × max_length`` decode slab would not fit
    the memory budget — raised at engine BUILD time (the estimator says
    no before the allocator does)."""


class DecodeStalledError(ServingError):
    """A decode dispatch hung past the watchdog limit (a configurable
    multiple of the rolling per-step time). The engine's worker thread
    is wedged inside the dispatch; the active requests are failed typed
    by the watchdog so their callers unblock instead of hanging with
    it, and the slab is rebuilt when (if) the dispatch returns."""


class GenerationRequest:
    """One generation request: prompt + sampling policy + streaming
    output. Completion (``finish``/``fail``) is idempotent first-wins,
    mirroring :class:`~.batcher.InferenceRequest`. Tokens stream into a
    bounded-latency queue as they are decoded (``stream()``); callers
    that want the whole sequence block on ``result()``."""

    _END = object()

    __slots__ = ("prompt", "max_new", "temperature", "top_k", "top_p",
                 "seed", "deadline", "enqueued_at", "trace", "tokens",
                 "slot", "_event", "_lock", "_stream", "result_", "error_",
                 "on_done", "draft_proposed", "draft_accepted")

    def __init__(self, prompt_ids, max_new: int, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0, seed: int = 0,
                 deadline: Optional[float] = None, trace: bool = False):
        self.prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        #: absolute time.monotonic() deadline, or None
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        self.trace = rtrace.RequestTrace() if trace else None
        #: generated token ids, in order (grows as decoding proceeds)
        self.tokens: List[int] = []
        #: speculative-decoding accounting: draft tokens proposed for /
        #: accepted by this request's verify dispatches
        self.draft_proposed = 0
        self.draft_accepted = 0
        #: slot index while decoding, else None
        self.slot: Optional[int] = None
        self._event = threading.Event()
        self._lock = witnessed_lock("generate.request")
        self._stream: "queue.Queue" = queue.Queue()
        self.result_: Optional[np.ndarray] = None
        self.error_: Optional[BaseException] = None
        #: optional completion observer ``fn(request, error_or_None)``,
        #: invoked exactly once (first-wins with the completion) AFTER
        #: the event is set, outside the request lock. The router's
        #: per-version generation counters — the canary metric gate's
        #: /generate leg — hang off this.
        self.on_done: Optional[Callable] = None

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                > self.deadline)

    def done(self) -> bool:
        return self._event.is_set()

    def push_token(self, tok: int) -> None:
        self.tokens.append(int(tok))
        self._stream.put(int(tok))

    def finish(self) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self.result_ = np.concatenate(
                [self.prompt, np.asarray(self.tokens, np.int32)])
            self._event.set()
            self._stream.put(self._END)
        self._notify(None)
        return True

    def fail(self, error: BaseException) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self.error_ = error
            self._event.set()
            self._stream.put(self._END)
        self._notify(error)
        return True

    def _notify(self, error: Optional[BaseException]) -> None:
        cb = self.on_done
        if cb is None:
            return
        try:
            cb(self, error)
        except Exception:  # noqa: BLE001 — an observer must never fail
            # the completion path (the caller is already unblocked)
            pass

    def stream(self, timeout: Optional[float] = None):
        """Yield token ids as they are decoded; raises the request's
        typed error at the point of failure. ``timeout`` bounds the wait
        for EACH token (a stalled engine raises
        :class:`RequestDeadlineExceeded` instead of hanging the
        consumer)."""
        while True:
            try:
                item = self._stream.get(timeout=timeout)
            except queue.Empty:
                raise RequestDeadlineExceeded(
                    f"no token within timeout={timeout}s") from None
            if item is self._END:
                if self.error_ is not None:
                    raise self.error_
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block for the full sequence (prompt + generated), 1-D int32.
        On timeout the request is failed idempotently (a concurrent
        engine completion wins) and the typed error raises."""
        if not self._event.wait(timeout):
            self.fail(RequestDeadlineExceeded(
                f"request not served within timeout={timeout}s"))
            self._event.wait()
        if self.error_ is not None:
            raise self.error_
        return self.result_


# --------------------------------------------------------------------------
# speculative drafting + shared-prefix KV cache
# --------------------------------------------------------------------------
class _NgramDraft:
    """Per-engine order-2 n-gram draft table for self-speculative
    decoding: ``(t[i-2], t[i-1]) → t[i]`` learned from every prompt and
    every emitted token (last-writer-wins, so the table adapts). Drafts
    are chained lookups from a slot's last two tokens — free to produce,
    and on repetitive traffic (shared-prefix storms, templated output)
    acceptance approaches 1. The table is bounded: crossing ``cap``
    clears it whole (``draft_flush`` flight event) rather than tracking
    per-entry LRU — n-gram stats rebuild in a few hundred tokens."""

    __slots__ = ("cap", "table", "flushes")

    def __init__(self, cap: int = 65536):
        self.cap = int(cap)
        self.table: Dict = {}
        self.flushes = 0

    def learn(self, a: int, b: int, c: int) -> None:
        self.table[(int(a), int(b))] = int(c)
        if len(self.table) > self.cap:
            from deeplearning4j_tpu.obs import flight as _flight

            self.table.clear()
            self.flushes += 1
            _flight.record("draft_flush", entries=self.cap,
                           flushes=self.flushes)

    def learn_seq(self, toks) -> None:
        for i in range(len(toks) - 2):
            self.learn(toks[i], toks[i + 1], toks[i + 2])

    def propose(self, a: int, b: int, n: int) -> List[int]:
        """Up to n draft tokens continuing context (a, b); stops at the
        first context the table has never seen."""
        out: List[int] = []
        a, b = int(a), int(b)
        for _ in range(n):
            c = self.table.get((a, b))
            if c is None:
                break
            out.append(c)
            a, b = b, c
        return out


class PrefixCache:
    """LRU-bytes cache of prefilled prompt state keyed by the EXACT
    prompt (backend kind, length, sha1 of the token bytes). A hit
    replaces the prefill dispatch with a per-bucket KV-block copy into
    the claiming slot plus a (1, V) sample of the STORED last-position
    logits — prefill logits are deterministic for a given prompt, so the
    hit path's first token and key chain are bit-identical to a real
    prefill. Entries are backend-opaque dicts carrying ``bytes`` (device
    memory held) and ``tb`` (the prompt's prefill bucket); eviction is
    LRU by bytes against ``limit_bytes``.

    Flight/metrics contract: every ``lookup`` counts toward the lazily
    created ``generation_prefix_hit_rate`` gauge; ``commit_hit`` (called
    only after the copy-in succeeded) fires ``prefix_hit``; ``drop``
    fires ``prefix_evict`` with the reason (lru / poisoned / cleared).
    Entries hold KV computed by the CURRENT params — a hot params
    reload must ``clear()`` (see ``GenerationEngine.clear_prefix_cache``)."""

    def __init__(self, limit_bytes: int, metrics: GenerationMetrics):
        self.limit_bytes = int(limit_bytes)
        self.metrics = metrics
        self._entries: "OrderedDict" = OrderedDict()
        self._bytes = 0
        self.lookups = 0
        self.hits = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def bytes(self) -> int:
        return self._bytes

    @staticmethod
    def key_for(kind: str, prompt: np.ndarray):
        return (kind, int(prompt.size),
                hashlib.sha1(np.ascontiguousarray(prompt).tobytes())
                .hexdigest())

    def lookup(self, key):
        """One admission-time probe; returns the entry or None. The hit
        is NOT committed here — the caller commits only after the
        copy-in succeeded (a poisoned entry must count as a miss)."""
        self.lookups += 1
        self.metrics.record_prefix_lookup()
        return self._entries.get(key)

    def commit_hit(self, key, prompt_len: int, slot: int,
                   flops_avoided: int = 0) -> None:
        from deeplearning4j_tpu.obs import flight as _flight

        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
        self.hits += 1
        self.metrics.record_prefix_hit(flops_avoided)
        _flight.record("prefix_hit", slot=int(slot),
                       prompt_len=int(prompt_len),
                       bucket=int(entry["tb"]) if entry else -1,
                       flops_avoided=int(flops_avoided))

    def drop(self, key, reason: str) -> None:
        from deeplearning4j_tpu.obs import flight as _flight

        entry = self._entries.pop(key, None)
        if entry is None:
            return
        self._bytes -= int(entry["bytes"])
        self.metrics.record_prefix_evict()
        self.metrics.set_prefix_bytes(self._bytes)
        _flight.record("prefix_evict", reason=reason,
                       bucket=int(entry["tb"]),
                       bytes=int(entry["bytes"]),
                       resident=len(self._entries))

    def put(self, key, entry: dict) -> bool:
        """Insert (replacing any stale entry for the key), evicting LRU
        entries until the budget fits; refuses entries larger than the
        whole budget."""
        if int(entry["bytes"]) > self.limit_bytes:
            return False
        if key in self._entries:
            self.drop(key, reason="replaced")
        while self._bytes + int(entry["bytes"]) > self.limit_bytes \
                and self._entries:
            oldest = next(iter(self._entries))
            self.drop(oldest, reason="lru")
        self._entries[key] = entry
        self._bytes += int(entry["bytes"])
        self.metrics.set_prefix_bytes(self._bytes)
        return True

    def attach_completion(self, key, toks) -> None:
        """Record the prompt's FIRST greedy completion on its entry:
        later hits replay it as the slot's draft source. Only the first
        one sticks (greedy is deterministic, so later ones are
        identical anyway); a handful of host ints, not counted against
        the byte budget."""
        entry = self._entries.get(key)
        if entry is not None and "completion" not in entry:
            entry["completion"] = [int(t) for t in toks]

    def clear(self, reason: str = "cleared") -> int:
        n = len(self._entries)
        for key in list(self._entries):
            self.drop(key, reason=reason)
        return n


# --------------------------------------------------------------------------
# decode backends
# --------------------------------------------------------------------------
class _TransformerBackend:
    """TransformerLM decode backend: fixed (L, S, hn, T, hd) KV slab,
    per-slot positions, per-bucket prefill programs."""

    kind = "transformer"

    def __init__(self, model, n_slots: int, max_length: Optional[int],
                 prefill_buckets: Optional[Sequence[int]], trace_hook,
                 spec_k: int = 1, draft_layers: int = 0):
        from deeplearning4j_tpu.models.transformer_lm import (
            decode_step,
            decode_steps,
            init_decode_cache,
            prefill_bucket_lengths,
            prefill_cache,
            sample_next_device,
            sample_next_rows,
        )

        self.model = model
        cfg = model.cfg
        self.n_slots = int(n_slots)
        self.max_length = (cfg.max_length if max_length is None
                           else min(int(max_length), cfg.max_length))
        self.buckets = prefill_bucket_lengths(
            self.max_length,
            prefill_buckets or getattr(model, "serving_seq_buckets", None))
        self._cfg = cfg
        #: speculation lane width K: column 0 is the current token,
        #: columns 1..K-1 draft proposals. MoE pins K=1 — decode_steps'
        #: routing would compete b*K tokens where sequential decode
        #: competes b, so acceptance would no longer be exact.
        self.spec_k = 1 if cfg.n_experts > 0 else max(1, int(spec_k))
        #: truncated-layer draft model depth (0 = n-gram drafting only);
        #: only meaningful with spec_k > 1 and 0 < draft_layers < L
        self.draft_layers = (int(draft_layers)
                             if self.spec_k > 1
                             and 0 < int(draft_layers) < cfg.n_layers
                             else 0)
        self.reset()
        self.cache_bytes = 2 * int(np.prod(self._kc.shape)) * \
            self._kc.dtype.itemsize
        if self.draft_layers:
            self.cache_bytes += 2 * int(np.prod(self._dkc.shape)) * \
                self._dkc.dtype.itemsize
        #: per-bucket prefix-cache copy programs (capture = slab→entry
        #: slice-out, restore = entry→slab splice-in), compiled lazily
        #: and pre-warmed by GenerationEngine.warmup
        self._cap_fns: Dict[int, Callable] = {}
        self._res_fns: Dict[int, Callable] = {}

        def _decode(p, kc, vc, toks, pos, active, t, k, pp, keys):
            trace_hook("generation_decode")
            logits, c = decode_step(cfg, p, {"k": kc, "v": vc, "pos": pos},
                                    toks)
            nxt, nkeys = sample_next_rows(logits, t, k, pp, keys)
            nxt = jnp.where(active, nxt, toks)
            nkeys = jnp.where(active[:, None], nkeys, keys)
            return nxt, nkeys, c["k"], c["v"]

        T = self.max_length
        Ld = self.draft_layers

        def _slice_draft(p):
            return {**p, "blocks": jax.tree_util.tree_map(
                lambda a: a[:Ld], p["blocks"])}

        def _prefill(p, kc, vc, dkc, dvc, ids, ln, slot, t, k, pp, key):
            trace_hook("generation_prefill")
            tmp = init_decode_cache(cfg, 1, max_length=T)
            logits, tmp = prefill_cache(cfg, p, tmp, ids, length=ln)
            kc = jax.lax.dynamic_update_slice(kc, tmp["k"],
                                              (0, slot, 0, 0, 0))
            vc = jax.lax.dynamic_update_slice(vc, tmp["v"],
                                              (0, slot, 0, 0, 0))
            if Ld:
                # the truncated draft model prefills its own (shallower)
                # slab from the same prompt
                dp = _slice_draft(p)
                dtmp = {"k": jnp.zeros((Ld,) + tmp["k"].shape[1:],
                                       tmp["k"].dtype),
                        "v": jnp.zeros((Ld,) + tmp["v"].shape[1:],
                                       tmp["v"].dtype),
                        "pos": jnp.zeros((), jnp.int32)}
                _dl, dtmp = prefill_cache(cfg, dp, dtmp, ids, length=ln)
                dkc = jax.lax.dynamic_update_slice(dkc, dtmp["k"],
                                                   (0, slot, 0, 0, 0))
                dvc = jax.lax.dynamic_update_slice(dvc, dtmp["v"],
                                                   (0, slot, 0, 0, 0))
            tok0, key = sample_next_device(logits, t, k, pp, key)
            return tok0[0], key, kc, vc, dkc, dvc, logits[0]

        self._decode_fn = jax.jit(_decode, donate_argnums=(1, 2))
        self._prefill_fn = jax.jit(_prefill, donate_argnums=(1, 2, 3, 4))

        def _sample1(logits, t, k, pp, key):
            trace_hook("generation_prefix_sample")
            tok0, key = sample_next_device(logits, t, k, pp, key)
            return tok0[0], key

        self._sample1_fn = jax.jit(_sample1)

        K = self.spec_k
        if K > 1:
            def _verify(p, kc, vc, toks, dlen, pos, active, t, k, pp,
                        keys):
                """One dispatch verifying K columns per slot. toks
                (S, K): col 0 = current token, cols 1..dlen = drafts.
                Emits s (S, K) — the tokens sequential decode WOULD have
                produced at each column — plus e (S,) the number of
                leading columns that are real output: e = 1 + longest
                draft prefix where draft j == s[j-1] (the exact
                acceptance rule: a draft survives iff the verifier
                sampled exactly it, so the emitted stream and the key
                chain are those of token-by-token decode)."""
                trace_hook("generation_verify")
                logits, c = decode_steps(
                    cfg, p, {"k": kc, "v": vc, "pos": pos}, toks)
                outs, kstack, ks = [], [keys], keys
                for j in range(K):
                    sj, ks = sample_next_rows(logits[:, j], t, k, pp, ks)
                    outs.append(sj)
                    kstack.append(ks)
                s = jnp.stack(outs, axis=1)          # (S, K)
                kst = jnp.stack(kstack, axis=1)      # (S, K+1, 2)
                jj = jnp.arange(1, K)
                ok = (s[:, :-1] == toks[:, 1:]) & \
                    (jj[None, :] <= dlen[:, None])
                e = 1 + jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(1)
                # the key chain advanced exactly e times (once per
                # emitted token) — select that state per row
                nkeys = jnp.take_along_axis(
                    kst, e[:, None, None], axis=1)[:, 0]
                last = jnp.take_along_axis(
                    s, (e - 1)[:, None], axis=1)[:, 0]
                last = jnp.where(active, last, toks[:, 0])
                nkeys = jnp.where(active[:, None], nkeys, keys)
                e = jnp.where(active, e, 0)
                return s, e, last, nkeys, c["k"], c["v"]

            self._verify_fn = jax.jit(_verify, donate_argnums=(1, 2))

        if Ld:
            def _draft(p, dkc, dvc, toks, pos, active):
                """K-1 greedy steps of the truncated-layer draft model —
                one dispatch proposing drafts for every slot."""
                trace_hook("generation_draft")
                dp = _slice_draft(p)
                c = {"k": dkc, "v": dvc, "pos": pos}
                tok = toks
                outs = []
                for _ in range(K - 1):
                    logits, c = decode_step(cfg, dp, c, tok)
                    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    outs.append(tok)
                return jnp.stack(outs, axis=1), c["k"], c["v"]

            self._draft_fn = jax.jit(_draft, donate_argnums=(1, 2))

    def reset(self) -> None:
        """(Re)build the KV slab — at construction, and for engine
        decode-failure recovery (the failed dispatch consumed the
        donated buffers)."""
        from deeplearning4j_tpu.models.transformer_lm import (
            init_decode_cache,
        )

        slab = init_decode_cache(self._cfg, self.n_slots,
                                 max_length=self.max_length)
        self._kc, self._vc = slab["k"], slab["v"]
        if self.draft_layers:
            self._dkc = self._kc[:self.draft_layers]
            self._dvc = self._vc[:self.draft_layers]
        else:
            # zero-size placeholders keep the prefill signature uniform
            self._dkc = self._kc[:0]
            self._dvc = self._vc[:0]

    def bucket_for(self, prompt_len: int) -> int:
        return next(t for t in self.buckets if t >= prompt_len)

    def prefill(self, slot: int, prompt: np.ndarray, temperature: float,
                top_k: int, top_p: float, key: np.ndarray):
        """Prefill one slot; returns (first token int, advanced key,
        prompt bucket, last-position logits (V,) fp32 device array —
        the prefix cache stores these so a hit can re-sample the first
        token bit-identically under any policy/key). One host sync per
        REQUEST (the first token), amortized over its whole decode. MoE
        prompts skip bucketing — pad tokens would compete for expert
        capacity and perturb real-token logits (same exemption, and the
        same one-program-per-distinct-length cost, as
        ``generate_cached``)."""
        tp = int(prompt.shape[0])
        tb = tp if self._cfg.n_experts > 0 else self.bucket_for(tp)
        ids = np.zeros((1, tb), np.int32)
        ids[0, :tp] = prompt
        tok0, key, self._kc, self._vc, self._dkc, self._dvc, logits0 = \
            self._prefill_fn(
                self.model.params_, self._kc, self._vc, self._dkc,
                self._dvc, jnp.asarray(ids),
                jnp.asarray(tp, jnp.int32),
                jnp.asarray(int(slot), jnp.int32),
                jnp.asarray(temperature, jnp.float32),
                jnp.asarray(int(top_k), jnp.int32),
                jnp.asarray(top_p, jnp.float32), jnp.asarray(key))
        return int(tok0), np.asarray(key), tb, logits0

    def decode(self, tokens, pos, active, temperature, top_k, top_p, keys):
        """One batched token step for all slots; returns
        (next tokens (S,), advanced keys (S, 2)) as host arrays — the
        single per-token host sync for the whole batch."""
        nxt, nkeys, self._kc, self._vc = self._decode_fn(
            self.model.params_, self._kc, self._vc,
            jnp.asarray(tokens), jnp.asarray(pos), jnp.asarray(active),
            jnp.asarray(temperature), jnp.asarray(top_k),
            jnp.asarray(top_p), jnp.asarray(keys))
        return np.asarray(nxt), np.asarray(nkeys)

    def verify(self, toks_k, dlen, pos, active, temperature, top_k, top_p,
               keys):
        """One batched draft-verify step (spec_k > 1 only): toks_k
        (S, K) proposal lane, dlen (S,) per-slot draft counts. Returns
        host arrays (emitted (S, K), accepted counts e (S,), new current
        token (S,), advanced keys (S, 2)) — still ONE host sync for up
        to K tokens per slot."""
        s, e, last, nkeys, self._kc, self._vc = self._verify_fn(
            self.model.params_, self._kc, self._vc,
            jnp.asarray(toks_k), jnp.asarray(dlen), jnp.asarray(pos),
            jnp.asarray(active), jnp.asarray(temperature),
            jnp.asarray(top_k), jnp.asarray(top_p), jnp.asarray(keys))
        return (np.asarray(s), np.asarray(e), np.asarray(last),
                np.asarray(nkeys))

    def draft(self, tokens, pos, active):
        """Truncated-layer draft proposals: (S, K-1) greedy tokens from
        the first ``draft_layers`` blocks, one dispatch for all slots."""
        drafts, self._dkc, self._dvc = self._draft_fn(
            self.model.params_, self._dkc, self._dvc,
            jnp.asarray(tokens), jnp.asarray(pos), jnp.asarray(active))
        return np.asarray(drafts)

    # -- shared-prefix cache hooks ------------------------------------------
    def prefix_capture(self, slot: int, tb: int, logits0) -> dict:
        """Slice the slot's first ``tb`` KV columns (and the truncated
        draft slab's, when speculating through it) out of the slab into
        a self-contained cache entry. The slab is donated to every
        decode dispatch, so the entry must be a COPY, not a view."""
        fn = self._cap_fns.get(tb)
        if fn is None:
            L, _S, hn, _T, hd = self._kc.shape
            Ld = self.draft_layers

            def _cap(kc, vc, dkc, dvc, slot):
                sl = (0, slot, 0, 0, 0)
                out = (jax.lax.dynamic_slice(kc, sl, (L, 1, hn, tb, hd)),
                       jax.lax.dynamic_slice(vc, sl, (L, 1, hn, tb, hd)))
                if Ld:
                    out += (jax.lax.dynamic_slice(dkc, sl,
                                                  (Ld, 1, hn, tb, hd)),
                            jax.lax.dynamic_slice(dvc, sl,
                                                  (Ld, 1, hn, tb, hd)))
                return out

            fn = self._cap_fns[tb] = jax.jit(_cap)
        blocks = fn(self._kc, self._vc, self._dkc, self._dvc,
                    jnp.asarray(int(slot), jnp.int32))
        nbytes = sum(int(b.size) * b.dtype.itemsize for b in blocks) \
            + int(logits0.size) * 4
        return {"blocks": blocks, "logits": logits0, "tb": int(tb),
                "bytes": int(nbytes)}

    def prefix_restore(self, slot: int, entry: dict, temperature: float,
                       top_k: int, top_p: float, key: np.ndarray):
        """Splice a cached KV block into ``slot`` and sample the first
        token from the STORED prefill logits — bit-identical to the real
        prefill this entry was captured from (same logits, same sampler
        program shape, same key chain)."""
        tb = int(entry["tb"])
        fn = self._res_fns.get(tb)
        if fn is None:
            Ld = self.draft_layers

            def _res(kc, vc, dkc, dvc, blocks, slot):
                sl = (0, slot, 0, 0, 0)
                kc = jax.lax.dynamic_update_slice(kc, blocks[0], sl)
                vc = jax.lax.dynamic_update_slice(vc, blocks[1], sl)
                if Ld:
                    dkc = jax.lax.dynamic_update_slice(dkc, blocks[2], sl)
                    dvc = jax.lax.dynamic_update_slice(dvc, blocks[3], sl)
                return kc, vc, dkc, dvc

            fn = self._res_fns[tb] = jax.jit(_res, donate_argnums=(0, 1,
                                                                   2, 3))
        self._kc, self._vc, self._dkc, self._dvc = fn(
            self._kc, self._vc, self._dkc, self._dvc, entry["blocks"],
            jnp.asarray(int(slot), jnp.int32))
        tok0, key = self._sample1_fn(
            entry["logits"][None],
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(int(top_k), jnp.int32),
            jnp.asarray(top_p, jnp.float32), jnp.asarray(key))
        return int(tok0), np.asarray(key)

    def window_check(self, prompt_len: int, max_new: int) -> None:
        from deeplearning4j_tpu.models.transformer_lm import (
            ContextWindowExceeded,
        )

        if prompt_len + max_new > self.max_length:
            raise ContextWindowExceeded(prompt_len, max_new,
                                        self.max_length)


def _cell_decode_supported(model) -> bool:
    """True when the model's layer stack can decode through the direct
    cell path: no preprocessors, every recurrent layer exposes ``_step``
    (the single-timestep cell the fused Pallas kernel backs), and every
    other layer is a rank-polymorphic per-timestep head. Anything else
    (Bidirectional, pooling wrappers, conv stacks) keeps the generic
    ``_forward`` path."""
    from deeplearning4j_tpu.nn.conf.layers.core import (
        ActivationLayer,
        DenseLayer,
        LossLayer,
    )
    from deeplearning4j_tpu.nn.conf.layers.recurrent import (
        BaseRecurrentLayer,
        RnnLossLayer,
        RnnOutputLayer,
    )

    if getattr(model.conf, "preprocessors", None):
        return False
    for layer in model.layers:
        if isinstance(layer, BaseRecurrentLayer):
            if not hasattr(layer, "_step"):
                return False
        elif not isinstance(layer, (RnnOutputLayer, RnnLossLayer,
                                    DenseLayer, ActivationLayer,
                                    LossLayer)):
            return False
    return True


class _RecurrentBackend:
    """Incremental-decode backend for recurrent MultiLayerNetworks
    (TextGenerationLSTM): per-slot carried (h, c) state stacked to
    ``(n_slots, ...)`` leaves. No KV slab — the carry IS the whole
    decode state, so ``max_length`` only bounds the request window, not
    memory.

    Two decode-step programs (PR 9 residue fix):

    - **cell path** (default when the stack supports it): one direct
      ``layer._step`` call per recurrent layer on rank-2 ``(S, d)``
      activations — no ``lax.scan`` machinery, no time-axis reshapes —
      so the per-token program is exactly the fused LSTM cell dispatches
      (Pallas on TPU, the reference composition elsewhere) plus the
      output head and the in-graph sampler;
    - **legacy path** (``cell_path=False`` or unsupported stacks): the
      generic ``_forward`` carry path over a T=1 sequence.

    Both are one jitted dispatch per token for all slots, bit-identical
    outputs (asserted in tests), zero steady-state recompiles."""

    kind = "recurrent"

    def __init__(self, model, n_slots: int, max_length: Optional[int],
                 prefill_buckets: Optional[Sequence[int]], trace_hook,
                 cell_path: Optional[bool] = None):
        import os as _os

        from deeplearning4j_tpu.models.transformer_lm import (
            prefill_bucket_lengths,
            sample_next_device,
            sample_next_rows,
        )
        from deeplearning4j_tpu.nn.conf.layers.recurrent import (
            BaseRecurrentLayer,
        )

        self.model = model
        self.n_slots = int(n_slots)
        self.max_length = int(max_length) if max_length else 256
        self.buckets = prefill_bucket_lengths(
            self.max_length,
            prefill_buckets or getattr(model, "serving_seq_buckets", None))
        self.vocab = int(model.layers[0].n_in)
        if cell_path is None:
            cell_path = (_os.environ.get("DL4J_TPU_LSTM_DECODE_CELL", "1")
                         != "0")
        self.cell_path = bool(cell_path) and _cell_decode_supported(model)
        self.reset()
        self.cache_bytes = sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(self._carries))
        V = self.vocab

        def _cell_forward(p, st, carries, x):
            """Direct per-timestep stack: (S, V) one-hot → (S, vocab)
            head output + updated carries. Mirrors ``_forward``'s
            semantics for the supported layer set (train=False: no
            dropout, no weight noise; recurrent masks are irrelevant at
            T=1 with all-real rows)."""
            if model._compute_dtype is not None:
                p = model._cast_for_compute(p)
                x = x.astype(model._compute_dtype)
            nc = [None] * len(model.layers)
            for idx, layer in enumerate(model.layers):
                if isinstance(layer, BaseRecurrentLayer):
                    c_new, x = layer._step(p[idx], carries[idx], x)
                    nc[idx] = c_new
                else:
                    x, _ = layer.apply(p[idx], x, state=st[idx],
                                       train=False)
            return x, nc

        def _decode(p, st, carries, toks, active, t, k, pp, keys):
            trace_hook("generation_decode")
            if self.cell_path:
                x = jax.nn.one_hot(toks, V, dtype=jnp.float32)
                y, nc = _cell_forward(p, st, carries, x)
                logits = jnp.log(jnp.clip(y.astype(jnp.float32),
                                          1e-30, None))
            else:
                x = jax.nn.one_hot(toks, V, dtype=jnp.float32)[:, None, :]
                y, _, _, nc, _ = model._forward(p, st, x, train=False,
                                                rng=None, carries=carries)
                logits = jnp.log(jnp.clip(y[:, -1, :].astype(jnp.float32),
                                          1e-30, None))
            nxt, nkeys = sample_next_rows(logits, t, k, pp, keys)
            nxt = jnp.where(active, nxt, toks)
            nkeys = jnp.where(active[:, None], nkeys, keys)
            nc = jax.tree_util.tree_map(
                lambda new, old: jnp.where(
                    active.reshape((-1,) + (1,) * (new.ndim - 1)), new, old),
                nc, carries)
            return nxt, nkeys, nc

        def _prefill(p, st, carries, ids, ln, slot, t, k, pp, key):
            trace_hook("generation_prefill")
            tb = ids.shape[0]
            x = jax.nn.one_hot(ids, V, dtype=jnp.float32)[None]
            mask = (jnp.arange(tb) < ln).astype(jnp.float32)[None]
            c1 = model._init_carries(1)
            y, _, _, nc1, _ = model._forward(p, st, x, train=False, rng=None,
                                             fmask=mask, carries=c1)
            y_last = jax.lax.dynamic_index_in_dim(y, ln - 1, axis=1,
                                                  keepdims=False)
            logits = jnp.log(jnp.clip(y_last.astype(jnp.float32),
                                      1e-30, None))
            tok0, key = sample_next_device(logits, t, k, pp, key)
            carries = jax.tree_util.tree_map(
                lambda big, row: big.at[slot].set(row[0]), carries, nc1)
            return tok0[0], key, carries, logits[0]

        self._decode_fn = jax.jit(_decode, donate_argnums=(2,))
        self._prefill_fn = jax.jit(_prefill, donate_argnums=(2,))

        def _sample1(logits, t, k, pp, key):
            trace_hook("generation_prefix_sample")
            tok0, key = sample_next_device(logits, t, k, pp, key)
            return tok0[0], key

        self._sample1_fn = jax.jit(_sample1)

        def _cap(carries, slot):
            trace_hook("generation_prefix_capture")
            return jax.tree_util.tree_map(lambda a: a[slot], carries)

        def _res(carries, rows, slot):
            trace_hook("generation_prefix_restore")
            return jax.tree_util.tree_map(
                lambda big, row: big.at[slot].set(row), carries, rows)

        self._cap_fn = jax.jit(_cap)
        self._res_fn = jax.jit(_res, donate_argnums=(0,))

    def reset(self) -> None:
        """(Re)build the carried state — at construction, and for
        engine decode-failure recovery (the failed dispatch consumed
        the donated carries)."""
        self._carries = self.model._init_carries(self.n_slots)

    def bucket_for(self, prompt_len: int) -> int:
        return next(t for t in self.buckets if t >= prompt_len)

    def prefill(self, slot, prompt, temperature, top_k, top_p, key):
        tp = int(prompt.shape[0])
        tb = self.bucket_for(tp)
        ids = np.zeros((tb,), np.int32)
        ids[:tp] = prompt
        tok0, key, self._carries, logits0 = self._prefill_fn(
            self.model.params_, self.model.state_, self._carries,
            jnp.asarray(ids), jnp.asarray(tp, jnp.int32),
            jnp.asarray(int(slot), jnp.int32),
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(int(top_k), jnp.int32),
            jnp.asarray(top_p, jnp.float32), jnp.asarray(key))
        return int(tok0), np.asarray(key), tb, logits0

    # -- shared-prefix cache hooks ------------------------------------------
    def prefix_capture(self, slot, tb, logits0) -> dict:
        """The recurrent decode state is the carry, so a prefix entry is
        the slot's carry rows + the stored prefill logits — one gather
        program regardless of bucket."""
        rows = self._cap_fn(self._carries, jnp.asarray(int(slot),
                                                       jnp.int32))
        nbytes = sum(int(a.size) * a.dtype.itemsize
                     for a in jax.tree_util.tree_leaves(rows)) \
            + int(logits0.size) * 4
        return {"rows": rows, "logits": logits0, "tb": int(tb),
                "bytes": int(nbytes)}

    def prefix_restore(self, slot, entry, temperature, top_k, top_p, key):
        self._carries = self._res_fn(
            self._carries, entry["rows"],
            jnp.asarray(int(slot), jnp.int32))
        tok0, key = self._sample1_fn(
            entry["logits"][None],
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(int(top_k), jnp.int32),
            jnp.asarray(top_p, jnp.float32), jnp.asarray(key))
        return int(tok0), np.asarray(key)

    def decode(self, tokens, pos, active, temperature, top_k, top_p, keys):
        nxt, nkeys, self._carries = self._decode_fn(
            self.model.params_, self.model.state_, self._carries,
            jnp.asarray(tokens), jnp.asarray(active),
            jnp.asarray(temperature), jnp.asarray(top_k),
            jnp.asarray(top_p), jnp.asarray(keys))
        return np.asarray(nxt), np.asarray(nkeys)

    def window_check(self, prompt_len: int, max_new: int) -> None:
        from deeplearning4j_tpu.models.transformer_lm import (
            ContextWindowExceeded,
        )

        if prompt_len + max_new > self.max_length:
            raise ContextWindowExceeded(prompt_len, max_new,
                                        self.max_length)


def _pick_backend(model, n_slots, max_length, prefill_buckets, trace_hook,
                  cell_path: Optional[bool] = None, spec_k: int = 1,
                  draft_layers: int = 0):
    from deeplearning4j_tpu.models.transformer_lm import TransformerLM

    if isinstance(model, TransformerLM):
        return _TransformerBackend(model, n_slots, max_length,
                                   prefill_buckets, trace_hook,
                                   spec_k=spec_k,
                                   draft_layers=draft_layers)
    layers = getattr(model, "layers", None)
    if layers is not None:
        from deeplearning4j_tpu.nn.conf.layers.recurrent import (
            BaseRecurrentLayer,
        )

        if any(isinstance(l, BaseRecurrentLayer) for l in layers):
            return _RecurrentBackend(model, n_slots, max_length,
                                     prefill_buckets, trace_hook,
                                     cell_path=cell_path)
    raise TypeError(
        f"{type(model).__name__} has no incremental-decode path: expected "
        "a TransformerLM (KV-cache slab) or a MultiLayerNetwork with "
        "recurrent layers (carried h/c state)")


# --------------------------------------------------------------------------
# memory validation
# --------------------------------------------------------------------------
def generation_memory_report(model, n_slots: int,
                             max_length: Optional[int] = None,
                             draft_layers: int = 0) -> dict:
    """Analytic 'will the decode slab fit' answer BEFORE allocating it —
    the nn/conf/memory.py estimator discipline applied to generation
    state: per-slot cache bytes × n_slots + resident params.
    ``draft_layers`` > 0 adds the truncated-layer speculation slab (the
    draft model keeps its own KV over the first ``draft_layers``
    blocks)."""
    from deeplearning4j_tpu.models.transformer_lm import TransformerLM

    if isinstance(model, TransformerLM):
        cfg = model.cfg
        T = cfg.max_length if max_length is None else min(int(max_length),
                                                          cfg.max_length)
        hd = cfg.d_model // cfg.n_heads
        itemsize = 2 if cfg.compute_dtype == "bfloat16" else 4
        cache = 2 * (cfg.n_layers + int(draft_layers)) * int(n_slots) \
            * cfg.n_heads * T * hd * itemsize
        params = sum(int(np.prod(p.shape)) * p.dtype.itemsize
                     for p in jax.tree_util.tree_leaves(model.params_))
    else:
        # recurrent nets: the carry is the decode state; lean on the
        # layer-wise estimator for params + per-slot activation state
        from deeplearning4j_tpu.nn.conf.memory import memory_report_mln

        report = memory_report_mln(model.conf)
        params = report.total_params * 4
        cache = report.total_memory_bytes(batch_size=int(n_slots),
                                          training=False) - params
        cache = max(cache, 0)
    return {"cache_bytes": int(cache), "param_bytes": int(params),
            "total_bytes": int(cache) + int(params),
            "n_slots": int(n_slots), "max_length": max_length}


def _device_bytes_limit() -> Optional[int]:
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — backend without a memory_stats API
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None


# --------------------------------------------------------------------------
# the engine
# --------------------------------------------------------------------------
class GenerationEngine:
    """Slotted continuous-batching decode engine over one model.

    One background worker owns ALL device state (slab / carries, under
    ``_dev_lock``); callers only touch the bounded admission queue and
    their own :class:`GenerationRequest`. Hot params reload composes:
    the jitted programs read ``model.params_`` per dispatch, so an
    atomic params swap (same shapes) takes effect at the next token.

    ``memory_limit_bytes``: explicit budget, ``"auto"`` (device
    ``bytes_limit`` when the backend reports one, else unchecked), or
    None to skip the check."""

    def __init__(self, model, n_slots: int = 8,
                 max_length: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 queue_limit: int = 64, default_timeout_s: float = 120.0,
                 metrics: Optional[GenerationMetrics] = None,
                 memory_limit_bytes="auto", stall_ms: float = 2000.0,
                 trace_requests: bool = True,
                 traces: Optional["rtrace.TraceBuffer"] = None,
                 watchdog_mult: Optional[float] = 20.0,
                 watchdog_min_s: float = 30.0,
                 decode_cell_path: Optional[bool] = None,
                 spec_decode_k: int = 1, draft_mode: str = "ngram",
                 prefix_cache_mb: float = 0.0):
        self.metrics = metrics if metrics is not None else GenerationMetrics()
        self.trace_requests = bool(trace_requests)
        self.traces = traces
        self.default_timeout_s = float(default_timeout_s)
        self.stall_ms = float(stall_ms)
        #: a decode dispatch in flight longer than
        #: ``max(watchdog_min_s, watchdog_mult × rolling step time)``
        #: trips the watchdog: escalated ``decode_stall`` flight event +
        #: active requests failed typed (:class:`DecodeStalledError`) —
        #: a HUNG dispatch must not wedge every caller the way a FAILED
        #: one already doesn't. None disables the watchdog.
        self.watchdog_mult = (None if watchdog_mult is None
                              else float(watchdog_mult))
        self.watchdog_min_s = float(watchdog_min_s)
        self._step_ewma_s: Optional[float] = None
        self._dispatch_t0: Optional[float] = None
        #: dispatch generation counter + the generation a trip belongs
        #: to: the watchdog tags its trip with the generation it
        #: observed hung, and the worker only honors a trip for the
        #: dispatch it actually fired on — a dispatch that completes
        #: just past the limit must not get its trip charged to the
        #: NEXT, healthy dispatch
        self._dispatch_gen = 0
        self._stall_gen = -1
        self._stall_tripped = False
        #: identity tags merged into this engine's chaos seam ctx — the
        #: router tags canary generation engines so a drill can target
        #: exactly the canary's decode dispatches
        self.chaos_ctx: Dict[str, object] = {}
        #: EWMA of tokens decoded per finished request — the
        #: Retry-After estimator's occupancy term (a queued request
        #: holds a slot for ~this many steps, not one)
        self._req_steps_ewma: Optional[float] = None
        #: fn-name → XLA programs traced (retrace-guard instrument)
        self.trace_counts: Dict[str, int] = {}
        self._retrace_counters = {}

        def trace_hook(fn: str) -> None:
            # trace-time side effect (never runs at dispatch time):
            # bump the host count, the registry counter and the flight
            # recorder — a steady-state recompile must be LOUD
            self.trace_counts[fn] = self.trace_counts.get(fn, 0) + 1
            if fn not in self._retrace_counters:
                self._retrace_counters[fn] = self.metrics.registry.counter(
                    "jit_retraces_total",
                    "distinct XLA programs traced per jitted function",
                    labels={"fn": fn})
            self._retrace_counters[fn].inc()
            from deeplearning4j_tpu.obs import flight as _flight

            _flight.record("retrace", fn=fn)

        if draft_mode not in ("ngram", "truncated"):
            raise ValueError(f"draft_mode must be 'ngram' or 'truncated',"
                             f" got {draft_mode!r}")
        if int(spec_decode_k) < 1:
            raise ValueError(
                f"spec_decode_k must be >= 1, got {spec_decode_k}")
        draft_layers = 0
        if draft_mode == "truncated" and int(spec_decode_k) > 1:
            draft_layers = max(
                getattr(getattr(model, "cfg", None), "n_layers", 0) // 2,
                0)
        #: None → auto (env ``DL4J_TPU_LSTM_DECODE_CELL``, else on for
        #: supported recurrent stacks); False forces the legacy
        #: ``_forward``-over-T=1 decode program (the bench's reference
        #: leg). Ignored by the transformer backend.
        self.backend = _pick_backend(model, n_slots, max_length,
                                     prefill_buckets, trace_hook,
                                     cell_path=decode_cell_path,
                                     spec_k=int(spec_decode_k),
                                     draft_layers=draft_layers)
        self.n_slots = self.backend.n_slots
        self.max_length = self.backend.max_length
        #: effective speculation width: the backend may pin K=1 (MoE,
        #: recurrent stacks) regardless of the requested knob
        self.spec_decode_k = getattr(self.backend, "spec_k", 1)
        self.draft_mode = (
            None if self.spec_decode_k <= 1
            else ("truncated" if getattr(self.backend, "draft_layers", 0)
                  else "ngram"))
        self._draft = (_NgramDraft() if self.draft_mode == "ngram"
                       else None)
        #: per-slot (t[-2], t[-1]) context feeding the n-gram draft
        self._ctx = np.zeros((self.n_slots, 2), np.int64)
        self._prefix_cache = (
            PrefixCache(int(float(prefix_cache_mb) * (1 << 20)),
                        self.metrics)
            if prefix_cache_mb and float(prefix_cache_mb) > 0 else None)
        #: per-slot completion replay: a prefix-cache entry remembers
        #: the prompt's first greedy completion, and later hits replay
        #: it as the slot's draft source (the exact verify rule keeps
        #: correctness — a replayed token is a PROPOSAL, never an
        #: output). Invalidated at the first emitted token that
        #: diverges. _slot_pk remembers the claiming request's cache
        #: key so its finished greedy completion can be attached.
        self._replay: List[Optional[List[int]]] = [None] * self.n_slots
        self._slot_pk: List[Optional[tuple]] = [None] * self.n_slots
        self.metrics.set_slots(self.n_slots)

        self.memory_report = generation_memory_report(
            model, self.n_slots, self.backend.max_length,
            draft_layers=getattr(self.backend, "draft_layers", 0))
        self._param_count = max(
            self.memory_report["param_bytes"] // 4, 1)
        if self._prefix_cache is not None:
            # the prefix cache's byte budget is device memory too —
            # count it against the same limit the slab answers to
            self.memory_report["prefix_cache_limit_bytes"] = \
                self._prefix_cache.limit_bytes
            self.memory_report["total_bytes"] += \
                self._prefix_cache.limit_bytes
        limit = (_device_bytes_limit() if memory_limit_bytes == "auto"
                 else memory_limit_bytes)
        self.memory_report["limit_bytes"] = limit
        from deeplearning4j_tpu.obs import flight as _flight

        _flight.record("generation_memory_check",
                       **{k: v for k, v in self.memory_report.items()
                          if v is not None})
        if limit is not None and self.memory_report["total_bytes"] > limit:
            raise GenerationMemoryError(
                f"decode slab needs {self.memory_report['cache_bytes']:,} "
                f"cache bytes (+{self.memory_report['param_bytes']:,} "
                f"params) for n_slots={self.n_slots} × "
                f"max_length={self.backend.max_length}, over the "
                f"{limit:,}-byte budget; lower n_slots or max_length")

        S = self.n_slots
        self._queue: "queue.Queue[GenerationRequest]" = queue.Queue(
            maxsize=max(int(queue_limit), 1))
        self._slots: List[Optional[GenerationRequest]] = [None] * S
        self._active = np.zeros((S,), bool)
        self._tokens = np.zeros((S,), np.int32)
        self._pos = np.zeros((S,), np.int32)
        self._temp = np.zeros((S,), np.float32)
        self._topk = np.zeros((S,), np.int32)
        self._topp = np.zeros((S,), np.float32)
        self._keys = np.zeros((S, 2), np.uint32)
        self._shutdown = False
        self._dev_lock = witnessed_lock("generate.device")
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="dl4j-tpu-generate")
        self._worker.start()
        self._watchdog: Optional[threading.Thread] = None
        if self.watchdog_mult is not None:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="dl4j-tpu-generate-watchdog")
            self._watchdog.start()

    # -- client side --------------------------------------------------------
    def submit(self, prompt_ids, max_new: int = 20, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 0.0, seed: int = 0,
               timeout: Optional[float] = None,
               trace: Optional[bool] = None,
               on_done: Optional[Callable] = None) -> GenerationRequest:
        """Enqueue a generation request; returns immediately (consume
        ``req.stream()`` or block on ``req.result()``). Raises the typed
        batcher-vocabulary failures: window overflow, queue-full
        overload, shutdown. ``on_done`` (``fn(request, error_or_None)``)
        is installed BEFORE the request is enqueued, so even a
        completion that races the submit return (instant decode
        failure, an already-expired deadline) is observed — the
        router's canary metric gate depends on every completion being
        counted."""
        from deeplearning4j_tpu.models.transformer_lm import (
            _validate_sampling,
        )

        if self._shutdown:
            raise ServerShutdownError("generation engine is shut down")
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        self.backend.window_check(prompt.size, int(max_new))
        _validate_sampling(temperature, top_k, top_p)
        timeout = self.default_timeout_s if timeout is None else timeout
        req = GenerationRequest(
            prompt, max_new, temperature, top_k, top_p, seed,
            deadline=None if timeout is None
            else time.monotonic() + float(timeout),
            trace=self.trace_requests if trace is None else bool(trace))
        req.on_done = on_done
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self.metrics.record_reject()
            from deeplearning4j_tpu.obs import flight as _flight

            _flight.record("overload_reject", surface="generate",
                           prompt_len=int(prompt.size),
                           queue_limit=self._queue.maxsize)
            err = ServerOverloadedError(
                f"generation queue full ({self._queue.maxsize} requests); "
                "retry with backoff or add slots")
            err.retry_after_s = self.retry_after_s()
            raise err from None
        if self._shutdown and req.fail(
                ServerShutdownError("engine shut down while enqueuing")):
            raise ServerShutdownError("engine shut down while enqueuing")
        self.metrics.record_request()
        return req

    def generate(self, prompt_ids, timeout: Optional[float] = None,
                 **kwargs) -> np.ndarray:
        """Blocking convenience: submit + result."""
        req = self.submit(prompt_ids, timeout=timeout, **kwargs)
        return req.result(timeout=timeout or self.default_timeout_s)

    # -- introspection ------------------------------------------------------
    @property
    def active_slots(self) -> int:
        return int(self._active.sum())

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def inflight(self) -> int:
        """Accepted-but-unfinished requests (decoding slots + queued):
        what a draining replica must let run out before it can be
        retired without dropping a stream."""
        return self.active_slots + self._queue.qsize()

    def retry_after_s(self) -> float:
        """Backoff hint for overloaded clients (the ``Retry-After``
        header on 503s), clamped to [1, 60]s. The batcher's
        depth×per-dispatch formula is wrong for the token loop — one
        decode dispatch retires one TOKEN for every slot, not one
        queued request — so the occupancy term scales by the typical
        tokens-per-request and the slot count: ``queued / n_slots ×
        steps-per-request × step time`` ≈ when a queued request will
        actually have drained."""
        steps = self._req_steps_ewma or 20.0
        waves = self._queue.qsize() / max(self.n_slots, 1)
        est = waves * steps * (self._step_ewma_s or 0.0)
        return min(max(est, 1.0), 60.0)

    def describe(self) -> dict:
        return {
            "backend": self.backend.kind,
            "decode_cell_path": getattr(self.backend, "cell_path", None),
            "n_slots": self.n_slots,
            "active_slots": self.active_slots,
            "max_length": self.backend.max_length,
            "prefill_buckets": list(self.backend.buckets),
            "queue_depth": self.queue_depth(),
            "spec_decode_k": self.spec_decode_k,
            "draft_mode": self.draft_mode,
            "prefix_cache": (None if self._prefix_cache is None else {
                "limit_bytes": self._prefix_cache.limit_bytes,
                "bytes": self._prefix_cache.bytes,
                "entries": len(self._prefix_cache),
                "lookups": self._prefix_cache.lookups,
                "hits": self._prefix_cache.hits,
            }),
            "trace_counts": dict(self.trace_counts),
            "memory": dict(self.memory_report),
        }

    def clear_prefix_cache(self, reason: str = "cleared") -> int:
        """Drop every cached prefix entry; returns the count dropped.
        MUST be called after a hot params reload — entries hold KV
        computed by the OLD weights, and serving them would silently
        change outputs (the one staleness hazard the exact-prompt key
        cannot see)."""
        if self._prefix_cache is None:
            return 0
        with self._dev_lock:
            return self._prefix_cache.clear(reason=reason)

    # -- warmup -------------------------------------------------------------
    def warmup(self, verbose: bool = False) -> dict:
        """Pre-compile the whole program set — one prefill per bucket +
        the single batched decode step — so steady-state generation
        never compiles. Runs on the caller thread under the device lock;
        skipped (returns ``{"skipped": ...}``) while slots are active
        (the programs are then warm by construction)."""
        t0 = time.perf_counter()
        before = dict(self.trace_counts)
        with self._dev_lock:
            if self._active.any():
                return {"skipped": "slots active (already warm)"}
            key = np.asarray(jax.random.PRNGKey(0))
            for tb in self.backend.buckets:
                # a tb-long prompt lands exactly in bucket tb (warmup
                # bypasses the window check — no decode follows)
                prompt = np.zeros((tb,), np.int32)
                _tok, _key, _tb, logits0 = self.backend.prefill(
                    0, prompt, 0.0, 0, 0.0, key)
                if self._prefix_cache is not None:
                    # compile the per-bucket capture/restore copy
                    # programs + the stored-logits sampler (entry
                    # discarded — warmup prompts must not spend budget)
                    entry = self.backend.prefix_capture(0, tb, logits0)
                    self.backend.prefix_restore(0, entry, 0.0, 0, 0.0,
                                                key)
                if verbose:
                    print(f"generation warmup: prefill bucket {tb}",
                          flush=True)
            self.backend.decode(self._tokens, self._pos,
                                np.zeros_like(self._active), self._temp,
                                self._topk, self._topp, self._keys)
            if self.spec_decode_k > 1:
                # the proposal-lane programs: truncated draft rollout
                # (when that mode is on) + the batched verify
                if self.draft_mode == "truncated":
                    self.backend.draft(self._tokens, self._pos,
                                       np.zeros_like(self._active))
                K = self.spec_decode_k
                self.backend.verify(
                    np.zeros((self.n_slots, K), np.int32),
                    np.zeros((self.n_slots,), np.int32), self._pos,
                    np.zeros_like(self._active), self._temp, self._topk,
                    self._topp, self._keys)
        compiles = {k: self.trace_counts.get(k, 0) - before.get(k, 0)
                    for k in self.trace_counts}
        return {"buckets": list(self.backend.buckets),
                "compiles": compiles,
                "seconds": round(time.perf_counter() - t0, 3)}

    # -- worker -------------------------------------------------------------
    def _free_slots(self) -> List[int]:
        return [i for i in range(self.n_slots) if self._slots[i] is None]

    def _admit(self, block_s: float) -> None:
        from deeplearning4j_tpu.obs import flight as _flight

        for slot in self._free_slots():
            try:
                req = (self._queue.get(timeout=block_s) if block_s > 0
                       else self._queue.get_nowait())
            except queue.Empty:
                return
            block_s = 0.0
            if req.done():
                continue  # caller-side timeout while queued
            if req.expired():
                self.metrics.record_deadline()
                req.fail(RequestDeadlineExceeded(
                    "request deadline passed while queued"))
                continue
            t0 = time.monotonic()
            if req.trace is not None:
                req.trace.mark("slot_claimed", t0)
            key0 = np.asarray(jax.random.PRNGKey(req.seed),
                              np.uint32).reshape(2)
            hit = False
            pk = None
            if self._prefix_cache is not None:
                pk = PrefixCache.key_for(self.backend.kind, req.prompt)
                entry = self._prefix_cache.lookup(pk)
                if entry is not None:
                    try:
                        # chaos seam: a poisoned/stale entry fails typed
                        # here, BEFORE any device copy — the fallback is
                        # a real prefill with the untouched key0, so the
                        # request's output is bit-identical either way
                        chaos_hooks.fire("generate.prefix_cache",
                                         op="hit", slot=slot,
                                         prompt_len=int(req.prompt.size),
                                         **self.chaos_ctx)
                        tok0, key = self.backend.prefix_restore(
                            slot, entry, req.temperature, req.top_k,
                            req.top_p, key0)
                    except BaseException:  # noqa: BLE001 — poisoned entry
                        # dropped + counted; the miss path below re-runs
                        # the REAL prefill with the untouched key0, so
                        # the caller sees a bit-identical result, never
                        # the cache failure
                        self._prefix_cache.drop(pk, reason="poisoned")
                    else:
                        bucket = int(entry["tb"])
                        hit = True
                        self._prefix_cache.commit_hit(
                            pk, prompt_len=int(req.prompt.size),
                            slot=slot,
                            flops_avoided=2 * self._param_count
                            * int(req.prompt.size))
            if not hit:
                try:
                    tok0, key, bucket, logits0 = self.backend.prefill(
                        slot, req.prompt, req.temperature, req.top_k,
                        req.top_p, key0)
                except BaseException as e:  # keep the worker alive
                    self.metrics.record_error()
                    req.fail(e)
                    continue
                if pk is not None:
                    self._prefix_cache.put(
                        pk,
                        self.backend.prefix_capture(slot, bucket,
                                                    logits0))
            dt = time.monotonic() - t0
            if not hit:
                self.metrics.record_prefill(dt)
            self.metrics.record_first_token()
            _flight.record("slot_claim", slot=slot,
                           prompt_len=int(req.prompt.size),
                           prompt_bucket=int(bucket),
                           max_new=req.max_new, prefix_hit=hit)
            self._slot_pk[slot] = pk
            self._replay[slot] = None
            if hit:
                comp = entry.get("completion")
                if comp:
                    self._replay[slot] = list(comp)
            self._slots[slot] = req
            req.slot = slot
            self._active[slot] = True
            self._tokens[slot] = tok0
            self._pos[slot] = req.prompt.size
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._topp[slot] = req.top_p
            self._keys[slot] = key
            if self._draft is not None:
                # teach the n-gram table the prompt + first token; seed
                # this slot's draft context with the last two tokens
                self._draft.learn_seq(req.prompt.tolist() + [int(tok0)])
            self._ctx[slot, 0] = int(req.prompt[-1])
            self._ctx[slot, 1] = int(tok0)
            if req.trace is not None:
                req.trace.mark("prefill_done")
                req.trace.note(slot=slot, prompt_len=int(req.prompt.size),
                               prompt_bucket=int(bucket), prefix_hit=hit)
            req.push_token(tok0)
            self._replay_advance(slot, int(tok0), 1)
            if len(req.tokens) >= req.max_new:
                self._finish_slot(slot, reason="done")

    def _replay_advance(self, slot: int, tok: int, n: int) -> None:
        """Invalidate the slot's completion replay at the first emitted
        token that diverges from the recorded completion (``n`` = the
        request's emitted-token count AFTER this token)."""
        comp = self._replay[slot]
        if comp is None:
            return
        if n > len(comp) or comp[n - 1] != tok:
            self._replay[slot] = None

    def _finish_slot(self, slot: int, reason: str,
                     error: Optional[BaseException] = None) -> None:
        from deeplearning4j_tpu.obs import flight as _flight

        req = self._slots[slot]
        self._slots[slot] = None
        self._active[slot] = False
        pk = self._slot_pk[slot]
        self._slot_pk[slot] = None
        self._replay[slot] = None
        if req is None:
            return
        req.slot = None
        if req.trace is not None:
            req.trace.mark("decode_done")
        n_tok = len(req.tokens)
        if n_tok:
            self._req_steps_ewma = (
                float(n_tok) if self._req_steps_ewma is None
                else 0.8 * self._req_steps_ewma + 0.2 * n_tok)
        if error is not None:
            if isinstance(error, RequestDeadlineExceeded):
                self.metrics.record_deadline()
            else:
                self.metrics.record_error()
            req.fail(error)
        else:
            if req.trace is not None:
                req.trace.mark("respond")
                req.trace.note(tokens=len(req.tokens))
            req.finish()
            self.metrics.record_finish(time.monotonic() - req.enqueued_at)
        if self.traces is not None and req.trace is not None:
            self.traces.add(req.trace)
        if (pk is not None and self._prefix_cache is not None
                and reason == "done" and error is None
                and req.temperature == 0.0):
            # greedy completion for this exact prompt — deterministic,
            # so it doubles as the replay draft for the NEXT hit
            self._prefix_cache.attach_completion(pk, req.tokens)
        if req.draft_proposed:
            _flight.record("draft_accept", slot=slot,
                           proposed=int(req.draft_proposed),
                           accepted=int(req.draft_accepted),
                           rate=round(req.draft_accepted
                                      / req.draft_proposed, 4))
        _flight.record("slot_free", slot=slot, reason=reason,
                       tokens=len(req.tokens))

    def _watchdog_loop(self) -> None:
        """Monitor thread: the decode dispatch runs on the worker
        thread, so a HUNG device call (driver wedge, deadlocked
        collective) freezes the worker where the except-clause recovery
        can never run. The watchdog observes the dispatch start stamp
        from outside, and past the limit fails the active requests
        typed and records the escalated stall — callers unblock, the
        blocked worker performs slab cleanup when (if) the dispatch
        finally returns."""
        from deeplearning4j_tpu.obs import flight as _flight

        while True:
            if self._shutdown and not self._worker.is_alive():
                return
            poll = min(max(self.watchdog_min_s / 4.0, 0.02), 1.0)
            time.sleep(poll)
            gen = self._dispatch_gen
            t0 = self._dispatch_t0
            if t0 is None or self._stall_tripped:
                continue
            limit = max(self.watchdog_min_s,
                        self.watchdog_mult * (self._step_ewma_s or 0.0))
            elapsed = time.monotonic() - t0
            if elapsed <= limit:
                continue
            if self._dispatch_gen != gen or self._dispatch_t0 is None:
                continue  # that dispatch completed while we measured
            self._stall_gen = gen
            self._stall_tripped = True
            if self._dispatch_gen != gen or self._dispatch_t0 is None:
                # completed in the set window: withdraw the trip before
                # failing anyone — these slots now belong to a healthy
                # (or no) dispatch
                self._stall_tripped = False
                continue
            n_active = int(self._active.sum())
            _flight.record("decode_stall", escalated=True,
                           wall_ms=round(elapsed * 1e3, 1),
                           limit_ms=round(limit * 1e3, 1),
                           active=n_active)
            err = DecodeStalledError(
                f"decode dispatch stuck for {elapsed:.1f}s (limit "
                f"{limit:.1f}s = max(watchdog_min_s, watchdog_mult × "
                "rolling step time)); active requests failed, worker "
                "thread still wedged in the dispatch")
            self.metrics.record_error()
            for slot in range(self.n_slots):
                req = self._slots[slot]
                if req is not None:
                    req.fail(err)

    def _build_drafts(self, K: int):
        """Assemble the fixed (S, K) proposal lane: column 0 = each
        slot's current token, columns 1..dlen[s] = draft proposals from
        the active draft source. Draft lengths are DATA (clamped per
        slot to the remaining token budget and the slab window — a
        column past either must never be accepted), shapes never
        change."""
        S = self.n_slots
        toks_k = np.zeros((S, K), np.int32)
        toks_k[:, 0] = self._tokens
        dlen = np.zeros((S,), np.int32)
        rooms: Dict[int, int] = {}
        for slot in range(S):
            if not self._active[slot]:
                continue
            req = self._slots[slot]
            if req is None:
                continue
            room = min(K - 1, req.max_new - len(req.tokens) - 1,
                       self.max_length - 1 - int(self._pos[slot]))
            if room > 0:
                rooms[slot] = room
        if not rooms:
            return toks_k, dlen
        if self.draft_mode == "truncated":
            drafts = self.backend.draft(self._tokens, self._pos,
                                        self._active)
            for slot, room in rooms.items():
                dlen[slot] = room
                toks_k[slot, 1:1 + room] = drafts[slot, :room]
        else:
            for slot, room in rooms.items():
                # replay first: a prefix hit carrying the prompt's
                # recorded greedy completion predicts perfectly as long
                # as the emitted tokens track it (invalidated on the
                # first divergence); n-gram table is the fallback
                ds: List[int] = []
                comp = self._replay[slot]
                if comp is not None:
                    n = len(self._slots[slot].tokens)
                    ds = comp[n:n + room]
                if not ds:
                    ds = self._draft.propose(self._ctx[slot, 0],
                                             self._ctx[slot, 1], room)
                if ds:
                    dlen[slot] = len(ds)
                    toks_k[slot, 1:1 + len(ds)] = ds
        return toks_k, dlen

    def _step(self) -> None:
        from deeplearning4j_tpu.obs import flight as _flight

        n_active = int(self._active.sum())
        K = self.spec_decode_k
        use_spec = False
        t0 = time.monotonic()
        self._dispatch_gen += 1
        gen = self._dispatch_gen
        self._dispatch_t0 = t0
        try:
            # chaos seam: error ≡ decode dispatch failure (typed
            # completion below); delay past the watchdog limit ≡ a hung
            # dispatch — the sleep happens with _dispatch_t0 stamped, so
            # the watchdog observes exactly what a wedged device call
            # looks like
            chaos_hooks.fire("generate.decode_dispatch",
                             active=n_active, **self.chaos_ctx)
            if K > 1:
                # draft building may itself dispatch (truncated mode) —
                # keep it inside the watchdog's stamped window
                toks_k, dlen = self._build_drafts(K)
                use_spec = bool(dlen.any())
            if use_spec:
                s_all, e_all, last, keys = self.backend.verify(
                    toks_k, dlen, self._pos, self._active, self._temp,
                    self._topk, self._topp, self._keys)
            else:
                toks, keys = self.backend.decode(
                    self._tokens, self._pos, self._active, self._temp,
                    self._topk, self._topp, self._keys)
        except BaseException as e:  # keep the worker alive: a decode
            # failure (bad hot-swapped params, transient device error)
            # fails the ACTIVE requests typed instead of silently
            # killing the loop and hanging every present and future
            # caller. The donated slab is gone with the failed dispatch,
            # so the slots cannot continue — but freed slots + a live
            # worker mean the next prefill rebuilds per-slot state.
            self._dispatch_t0 = None
            self._stall_tripped = False
            _flight.record("decode_error", error=type(e).__name__,
                           active=n_active)
            for slot in range(self.n_slots):
                if self._slots[slot] is not None:
                    self._finish_slot(slot, reason="decode_error", error=e)
            self.backend.reset()
            return
        self._dispatch_t0 = None
        dt = time.monotonic() - t0
        if self._stall_tripped:
            self._stall_tripped = False
            if self._stall_gen != gen:
                # a stale trip for an earlier dispatch that completed
                # inside the watchdog's set window — this dispatch is
                # healthy, keep its results
                pass
            else:
                # the watchdog already failed the active requests while
                # this dispatch hung; its result is stale — free the
                # slots and rebuild per-slot state like the
                # decode-failure path
                _flight.record("decode_stall_recovered",
                               wall_ms=round(dt * 1e3, 1), active=n_active)
                err = DecodeStalledError("decode dispatch exceeded the "
                                         "watchdog limit")
                for slot in range(self.n_slots):
                    if self._slots[slot] is not None:
                        self._finish_slot(slot, reason="decode_stall",
                                          error=err)
                self.backend.reset()
                return
        self._step_ewma_s = (dt if self._step_ewma_s is None
                             else 0.8 * self._step_ewma_s + 0.2 * dt)
        if use_spec:
            emitted = int(e_all.sum())
            self.metrics.record_decode_step(dt, emitted)
            self.metrics.record_draft(int(dlen[self._active].sum()),
                                      emitted - n_active)
        else:
            self.metrics.record_decode_step(dt, n_active)
        if dt * 1e3 > self.stall_ms:
            _flight.record("decode_stall", wall_ms=round(dt * 1e3, 1),
                           active=n_active)
        # copy: np.asarray on a device array is a read-only view, and
        # the admit path writes per-slot lanes into these
        if use_spec:
            self._tokens = np.array(last, np.int32)
            self._keys = np.array(keys, np.uint32)
            # accepted counts are data: each slot advances by its own e
            # (masked to 0 on inactive rows)
            self._pos += e_all.astype(np.int32)
        else:
            self._tokens = np.array(toks, np.int32)
            self._keys = np.array(keys, np.uint32)
            self._pos[self._active] += 1
        now = time.monotonic()
        for slot in range(self.n_slots):
            if not self._active[slot]:
                continue
            req = self._slots[slot]
            if use_spec:
                m = int(e_all[slot])
                req.draft_proposed += int(dlen[slot])
                req.draft_accepted += m - 1
                for j in range(m):
                    tok = int(s_all[slot, j])
                    self._learn(slot, tok)
                    req.push_token(tok)
                    self._replay_advance(slot, tok, len(req.tokens))
            else:
                tok = int(toks[slot])
                self._learn(slot, tok)
                req.push_token(tok)
                self._replay_advance(slot, tok, len(req.tokens))
            if len(req.tokens) >= req.max_new:
                self._finish_slot(slot, reason="done")
            elif req.expired(now) or req.done():
                # done() → the caller gave up (result timeout); either
                # way the slot frees at token granularity (deadline
                # expiry mid-verify frees it just like mid-decode — the
                # already-accepted tokens were pushed above)
                self._finish_slot(
                    slot, reason="deadline",
                    error=RequestDeadlineExceeded(
                        "request deadline passed mid-decode"))

    def _learn(self, slot: int, tok: int) -> None:
        """Advance the slot's 2-token draft context and teach the n-gram
        table (ngram mode) each emitted token."""
        if self._draft is not None:
            self._draft.learn(self._ctx[slot, 0], self._ctx[slot, 1], tok)
        self._ctx[slot, 0] = self._ctx[slot, 1]
        self._ctx[slot, 1] = tok

    def _loop(self) -> None:
        while True:
            with self._dev_lock:
                self._admit(block_s=0.0)
                any_active = self._active.any()
                if any_active:
                    self._step()
            self.metrics.set_active_slots(int(self._active.sum()))
            if not any_active:
                if self._shutdown and self._queue.empty():
                    return
                # idle: wait for work without holding the device lock
                try:
                    req = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                # put it back and admit under the lock (single admission
                # path keeps slot bookkeeping in one place)
                self._requeue_front(req)

    def _requeue_front(self, req: GenerationRequest) -> None:
        # queue.Queue has no putleft; a transient overflow past the
        # bound here is acceptable (the request was already admitted
        # once) — deque directly to preserve order
        with self._queue.mutex:
            self._queue.queue.appendleft(req)
            self._queue.not_empty.notify()

    # -- lifecycle ----------------------------------------------------------
    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting work; ``drain=True`` finishes active and
        queued requests first, else they fail typed. Idempotent."""
        self._shutdown = True
        if not drain:
            self._fail_queued()
            with self._dev_lock:
                for slot in range(self.n_slots):
                    if self._slots[slot] is not None:
                        self._finish_slot(
                            slot, reason="shutdown",
                            error=ServerShutdownError(
                                "engine shut down mid-decode"))
        self._worker.join(timeout=timeout)
        self._fail_queued()

    def _fail_queued(self) -> None:
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            req.fail(ServerShutdownError(
                "engine shut down before serving request"))
