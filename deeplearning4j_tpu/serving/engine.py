"""Model engine: jitted forward + bucket padding + atomic hot reload.

The engine owns the compiled serving surface for one model:

- a **pure jitted forward** ``fn(params, state, x, mask)`` built once
  per architecture (for :class:`MultiLayerNetwork` it closes over the
  layer graph only — params/state flow through as arguments, which is
  what makes zero-recompile hot reload possible);
- a **compile-count hook**: the traced function bumps a host counter at
  trace time, so ``engine.compile_count`` is exactly the number of
  distinct XLA programs built — the acceptance signal for "warmup
  pre-compiled everything, steady state never compiles";
- ``warmup()``: runs every shape the bucket policy can emit
  (``BucketPolicy.warmup_shapes``) through the forward at startup;
- **atomic hot-swap reload**: a reload builds a complete replacement
  snapshot (params, state, fn) off to the side — re-warming first if
  the architecture changed — and installs it with one reference
  assignment. Serving threads read the snapshot reference once per
  batch, so a batch is always computed entirely under one model:
  serving never observes a half-loaded or mixed model. Checkpoints come
  from ``train.faults.latest_valid_checkpoint`` (crash-safe, falls back
  past truncated newest) or an explicit zip path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.obs.lockwitness import witnessed_lock
from deeplearning4j_tpu.serving.buckets import BucketPolicy
from deeplearning4j_tpu.serving.metrics import ServingMetrics


class _Snapshot:
    """One immutable serving model version. All fields are set before the
    snapshot becomes visible; after that it is only read."""

    __slots__ = ("model", "params", "state", "fn", "conf_json", "version",
                 "source", "loaded_at")

    def __init__(self, model, fn, conf_json, version, source):
        self.model = model
        self.params = model.params_
        self.state = model.state_
        self.fn = fn  # None → generic model.output fallback
        self.conf_json = conf_json
        self.version = int(version)
        self.source = source
        self.loaded_at = time.time()


def conf_example_shape(conf) -> Optional[Tuple[int, ...]]:
    """Per-example input shape declared by a configuration's input type
    (None when it declares none) — the one derivation shared by engine
    warmup, reload re-warming, and ``ZooModel.serving_input_shape``."""
    itype = getattr(conf, "input_type", None)
    if itype is None:
        return None
    return tuple(itype.shape(1)[1:])


def resolve_checkpoint_source(source: str) -> str:
    """Resolve a checkpoint zip from a path or directory (newest VALID
    one via the fault-tolerance layer). An EXPLICIT zip path that fails
    validation falls back to the newest valid sibling in its directory
    instead of killing server start — a truncated newest checkpoint next
    to keep-last-k valid older snapshots is exactly the crash the
    retention policy exists for. Every fallback (this explicit-path one
    and the directory scan inside ``latest_valid_checkpoint``) emits a
    ``checkpoint_fallback`` flight event naming the SKIPPED path and the
    error class, so a truncated snapshot mid-publish shows up in the
    black box. Shared by engine construction, ``/reload``, and
    ``ModelRegistry.publish``."""
    from deeplearning4j_tpu.train.faults import (
        latest_valid_checkpoint,
        validate_checkpoint,
    )

    if os.path.isdir(source):
        return latest_valid_checkpoint(source)
    if not os.path.exists(source):
        # a missing path is a caller error (409 at the server), not a
        # corrupt checkpoint to route around
        raise FileNotFoundError(f"checkpoint {source!r} does not exist")
    ok, reason = validate_checkpoint(source)
    if ok:
        return source
    parent = os.path.dirname(os.path.abspath(source))
    fallback = (latest_valid_checkpoint(parent, missing_ok=True)
                if os.path.isdir(parent) else None)
    if fallback is None:
        raise ValueError(
            f"checkpoint {source!r} is invalid ({reason}) and no valid "
            f"sibling checkpoint exists in {parent!r}")
    import warnings

    warnings.warn(
        f"checkpoint {source!r} is invalid ({reason}); serving the "
        f"newest valid sibling {fallback!r} instead", stacklevel=3)
    from deeplearning4j_tpu.obs import flight as _flight
    from deeplearning4j_tpu.train.faults import checkpoint_error_class

    _flight.record("checkpoint_fallback", requested=str(source),
                   skipped=str(source), served=str(fallback),
                   error_class=checkpoint_error_class(reason),
                   reason=reason)
    return fallback


class InferenceEngine:
    """Serving engine over one model + bucket policy.

    ``mesh`` (a ``TrainingMesh``) shards each dispatched batch over the
    data axis (GSPMD: replicated params, batch-sharded input); bucket
    sizes must then be multiples of the data-axis size so shards are
    even — the default power-of-two buckets are filtered accordingly.
    """

    def __init__(self, model, buckets: Optional[BucketPolicy] = None,
                 mesh=None, checkpoint_dir: Optional[str] = None,
                 metrics: Optional[ServingMetrics] = None,
                 int8_serving: bool = False):
        # own copy: mesh filtering + oversize growth must never mutate a
        # policy object shared with another engine
        self.buckets = (buckets if buckets is not None
                        else BucketPolicy()).copy()
        self.mesh = mesh
        self.checkpoint_dir = checkpoint_dir
        self.metrics = metrics if metrics is not None else ServingMetrics()
        #: opt-in int8 weight-only quantization of the dense/output
        #: heads (nn/ops/int8_matmul.py): every snapshot this engine
        #: builds — init AND hot reloads — serves int8 weights with
        #: per-channel scales; the MODEL's params stay fp32 (training/
        #: checkpointing never see the quantized form)
        self.int8_serving = bool(int8_serving)
        self.int8_report: Optional[dict] = None
        if self.int8_serving and not hasattr(model, "layers"):
            raise TypeError(
                f"int8_serving needs a layered model with a functional "
                f"forward; {type(model).__name__} serves through the "
                "generic output path")
        self._compile_count = 0
        #: byte ledger of the snapshot placement (parallel/reshard.py);
        #: None for mesh-less engines (placement is implicit at dispatch)
        self.reshard_stats = None
        self._reload_lock = witnessed_lock("serving.reload")
        self._fingerprint: Optional[Tuple[float, int]] = None
        self.warm = False
        if mesh is not None and mesh.n_data > 1:
            # shards must be even: keep only buckets divisible by the
            # data axis (drops the small power-of-two defaults a 1-row
            # request would otherwise pad to)
            keep = [b for b in self.buckets.batch_buckets
                    if b % mesh.n_data == 0]
            dropped = [b for b in self.buckets.batch_buckets
                       if b % mesh.n_data]
            if not keep:
                raise ValueError(
                    f"no batch bucket in {self.buckets.batch_buckets} is "
                    f"divisible by the mesh data axis ({mesh.n_data}); "
                    "raise batch_limit or pass batch_buckets that are "
                    "multiples of it")
            if dropped:
                import warnings

                warnings.warn(
                    f"dropping batch buckets {dropped}: not divisible by "
                    f"the mesh data axis ({mesh.n_data}); serving with "
                    f"{keep}", stacklevel=2)
                self.buckets.batch_buckets = keep
        self._snap = self._build_snapshot(model, version=0, source="init")

    # -- construction -------------------------------------------------------
    @classmethod
    def from_checkpoint(cls, source: str, **kwargs) -> "InferenceEngine":
        """Engine from a checkpoint zip or a checkpoint DIRECTORY (the
        newest valid checkpoint; corrupt/truncated ones are skipped —
        an explicit zip path that fails validation also falls back to
        its newest valid sibling). A directory also becomes the default
        ``/reload`` source.

        Checkpoints are topology-portable: the canonical entries carry
        no device-count assumptions, so a checkpoint written by an
        8-device training mesh serves on 1 device (or any ``mesh``)
        without a host-side re-gather — the train-on-N/serve-on-M leg
        of parallel/reshard.py. The reshard is recorded as
        ``reshard_start``/``reshard_done`` flight events with
        N→M provenance from the checkpoint's ``meta.json``."""
        from deeplearning4j_tpu.parallel import reshard as _reshard
        from deeplearning4j_tpu.train.model_serializer import (
            ModelGuesser,
            ModelSerializer,
        )

        path = resolve_checkpoint_source(source)
        topo = ModelSerializer.checkpoint_meta(path).get("topology") or {}
        n_from = topo.get("n_devices")
        model = ModelGuesser.load_model_guess(path)
        if os.path.isdir(source):
            kwargs.setdefault("checkpoint_dir", source)
        mesh = kwargs.get("mesh")
        n_to = mesh.n_data if mesh is not None else 1
        with _reshard.reshard_event(n_from, n_to, surface="serving") as st:
            eng = cls(model, **kwargs)
            if eng.reshard_stats is not None:
                st.merge(eng.reshard_stats)
        eng._snap.source = path
        eng._fingerprint = cls._path_fingerprint(path)
        from deeplearning4j_tpu.obs import flight as _flight

        _flight.record("checkpoint_load", path=str(path), surface="serving")
        return eng

    @staticmethod
    def _path_fingerprint(path: str) -> Optional[Tuple[float, int]]:
        from deeplearning4j_tpu.train.faults import checkpoint_fingerprint

        try:
            return checkpoint_fingerprint(path)
        except OSError:
            return None

    def _build_snapshot(self, model, version: int, source) -> "_Snapshot":
        conf = getattr(model, "conf", None)
        conf_json = conf.to_json() if hasattr(conf, "to_json") else None
        fn = self._build_fn(model)
        if self.mesh is not None:
            # replicated placement through the reshard planner: same
            # device_put semantics as before, plus the byte ledger
            # (reshard_stats) the from_checkpoint N→M event reports
            from deeplearning4j_tpu.parallel import reshard as _reshard

            stats = _reshard.TransferStats()
            _reshard.place_model(model, self.mesh, stats)
            self.reshard_stats = stats
        snap = _Snapshot(model, fn, conf_json, version, source)
        if self.int8_serving:
            snap.params = self._quantize_params(model)
        return snap

    def _quantize_params(self, model):
        """Int8-quantize a model's params for a serving snapshot (the
        model object keeps its fp32 params). Mesh engines re-place the
        quantized leaves replicated."""
        if not hasattr(model, "layers"):
            # same guard as __init__ — a hot reload can hand this engine
            # a different-arch checkpoint that loads as a layer-less
            # model, and that must fail typed (reload refused, old
            # snapshot keeps serving), not AttributeError mid-swap
            raise TypeError(
                f"int8_serving needs a layered model with a functional "
                f"forward; {type(model).__name__} serves through the "
                "generic output path")
        from deeplearning4j_tpu.nn.ops.int8_matmul import (
            quantize_model_params,
        )

        qparams, report = quantize_model_params(model)
        self.int8_report = report
        from deeplearning4j_tpu.obs import flight as _flight

        _flight.record("int8_quantize", surface="serving", **report)
        if self.mesh is not None:
            qparams = jax.device_put(qparams, self.mesh.replicated())
        return qparams

    def _build_fn(self, model):
        """Pure jitted forward for models exposing the functional
        ``_forward`` (MultiLayerNetwork family). Returns None for other
        models — they serve through ``model.output`` (no compile-count
        hook, still batched/bucketed/hot-swapped)."""
        if not hasattr(model, "_forward"):
            if not hasattr(model, "output"):
                raise TypeError(
                    f"{type(model).__name__} has neither _forward nor "
                    "output; cannot serve it")
            return None

        retraces = self.metrics.registry.counter(
            "jit_retraces_total",
            "distinct XLA programs traced per jitted function",
            labels={"fn": "serving_forward"})

        def run(params, state, x, fmask):
            # trace-time side effect: one bump per distinct input shape
            # (= per compiled XLA program). Never executes at run time.
            # Mirrored into the metrics registry (obs/trace.py retrace
            # monitor), so steady-state serving recompiles are a
            # scrapeable counter, not just an in-process int — and into
            # the flight recorder, so a recompile storm shows up in the
            # black box ordered against the requests it slowed down.
            self._compile_count += 1
            retraces.inc()
            from deeplearning4j_tpu.obs import flight as _flight

            _flight.record("retrace", fn="serving_forward",
                           shape=str(tuple(x.shape)))
            y, _, _, _, _ = model._forward(params, state, x, train=False,
                                           rng=None, fmask=fmask)
            return y

        return jax.jit(run)

    # -- properties ---------------------------------------------------------
    @property
    def compile_count(self) -> int:
        """Distinct XLA programs traced by this engine (all versions)."""
        return self._compile_count

    @property
    def compile_count_supported(self) -> bool:
        return self._snap.fn is not None

    @property
    def model_version(self) -> int:
        return self._snap.version

    @property
    def model(self):
        """The live snapshot's layer graph. NOTE: after a same-arch hot
        reload this is still the ORIGINAL model object (its layer graph
        carries the compiled programs); the weights actually served are
        the snapshot's params — read results through ``infer``, not
        ``model.output``."""
        return self._snap.model

    def describe(self) -> dict:
        snap = self._snap
        return {
            "model_type": type(snap.model).__name__,
            "version": snap.version,
            "source": str(snap.source),
            "loaded_at": snap.loaded_at,
            "num_params": (int(snap.model.num_params())
                           if hasattr(snap.model, "num_params") else None),
            "warm": self.warm,
            "compile_count": self._compile_count,
            "buckets": repr(self.buckets),
            "int8_serving": self.int8_serving,
            "int8_report": self.int8_report,
            # canary/rollback tooling keys on these: WHICH on-disk
            # checkpoint is live (content fingerprint, None for
            # fresh-weights engines) and which snapshot generation
            "checkpoint_fingerprint": (None if self._fingerprint is None
                                       else list(self._fingerprint)),
        }

    # -- inference ----------------------------------------------------------
    def example_shape(self) -> Optional[Tuple[int, ...]]:
        """Per-example input shape from the model conf's input type
        (None when the conf does not declare one — warmup then needs an
        explicit shape)."""
        return conf_example_shape(getattr(self._snap.model, "conf", None))

    def infer(self, x, mask=None) -> np.ndarray:
        """One bucketed forward: pad up to the bucket, run, slice back."""
        return self.infer_versioned(x, mask)[0]

    def infer_versioned(self, x, mask=None) -> Tuple[np.ndarray, int]:
        """:meth:`infer` plus the version of the snapshot that actually
        computed the result. The snapshot reference is read exactly once,
        so concurrent reloads can never mix model versions inside a call
        — and re-reading ``model_version`` after the call would
        misattribute results that raced a hot reload. This is the single
        serving override point: the HTTP server and ``infer`` both route
        through it (wrap THIS method for chaos/test tooling; warmup
        deliberately bypasses it to reach not-yet-published snapshots)."""
        snap = self._snap
        return self._infer_on(snap, x, mask), snap.version

    def _infer_on(self, snap: "_Snapshot", x, mask=None) -> np.ndarray:
        import time as _time

        from deeplearning4j_tpu.obs import trace as _trace
        from deeplearning4j_tpu.serving import rtrace as _rtrace

        x = np.asarray(x)
        t_orig = x.shape[1] if x.ndim >= 3 else None
        xp, mp, n = self.buckets.pad_batch(x, mask)
        t_padded = xp.shape[1] if t_orig is not None else None
        self.metrics.record_dispatch(xp.shape[0], real_rows=n)
        info = _rtrace.current_dispatch()
        if info is not None:
            info.bucket = int(xp.shape[0])
            info.rows_real = int(n)
            info.rows_padded = int(xp.shape[0])
            info.seq_real = t_orig
            info.seq_padded = t_padded
        with _trace.span("serving_dispatch"):
            y = self._forward_raw(snap, xp, mp)
        if info is not None:
            # async backends return from the dispatch before the device
            # finishes; the remaining device wait lands in the "slice"
            # interval (the first host read below blocks on it)
            info.t_forward_done = _time.monotonic()
        from deeplearning4j_tpu.serving.buckets import slice_result

        out = slice_result(y, n, t_orig, t_padded)
        if info is not None:
            info.t_sliced = _time.monotonic()
        return out

    def _forward_raw(self, snap: "_Snapshot", xp, mp=None) -> np.ndarray:
        """The exact-shape forward under ``snap`` — no bucket padding,
        no dispatch metrics. The dispatch core of :meth:`_infer_on`,
        and the primitive :meth:`retune_buckets` uses to pre-compile a
        CANDIDATE bucket set's shapes while the current policy is still
        the one serving traffic."""
        if snap.fn is None:
            m = snap.model
            if hasattr(m, "output_single"):  # ComputationGraph surface
                return m.output_single(xp,
                                       masks=None if mp is None else [mp])
            return m.output(xp, mask=mp)
        xd = xp
        md = mp
        if self.mesh is not None:
            xd = jax.device_put(xp, self.mesh.batch_sharded())
            if mp is not None:
                md = jax.device_put(mp, self.mesh.batch_sharded())
        return snap.fn(snap.params, snap.state, xd, md)

    # -- warmup -------------------------------------------------------------
    def _warm_snapshot(self, snap: "_Snapshot",
                       example_shape: Sequence[int],
                       verbose: bool = False) -> int:
        """Run every bucket shape through ``snap``'s forward; returns
        the shape count. Shared by startup warmup and reload re-warming."""
        shapes = self.buckets.warmup_shapes(tuple(example_shape))
        for full_shape, with_mask in shapes:
            x = np.zeros(full_shape, np.float32)
            mask = (np.ones(full_shape[:2], np.float32)
                    if with_mask else None)
            self._infer_on(snap, x, mask)
            if verbose:
                print(f"warmup {full_shape} mask={with_mask}", flush=True)
        return len(shapes)

    def warmup(self, example_shape: Optional[Sequence[int]] = None,
               verbose: bool = False) -> dict:
        """Pre-compile every bucket shape so steady-state serving never
        recompiles. Returns a report {shapes, compiles, seconds}."""
        shape = tuple(example_shape) if example_shape is not None \
            else self.example_shape()
        if shape is None:
            raise ValueError(
                "cannot infer the per-example input shape from the model "
                "conf; pass warmup(example_shape=...)")
        before = self._compile_count
        t0 = time.perf_counter()
        n_shapes = self._warm_snapshot(self._snap, shape, verbose=verbose)
        self.warm = True
        return {
            "shapes": n_shapes,
            "compiles": self._compile_count - before,
            "seconds": round(time.perf_counter() - t0, 3),
        }

    def retune_buckets(self, new_policy: BucketPolicy,
                       example_shape: Optional[Sequence[int]] = None
                       ) -> dict:
        """Adopt a new bucket set with **zero steady-state retraces**:
        pre-compile-before-switch.

        Under the reload lock (a retune and a hot reload must not
        interleave): copy the candidate policy, apply the same
        mesh-divisibility filter as ``__init__``, run every shape the
        candidate can emit through :meth:`_forward_raw` at its EXACT
        padded shape — jit caches the new programs while ``self.buckets``
        (the old policy) is still the one padding live traffic — then
        atomically ref-assign the new policy. In-flight ``_infer_on``
        calls read ``self.buckets`` once per request, so every request
        pads entirely under one policy or the other, and the first
        request after the swap hits an already-compiled program.

        Returns ``{shapes, compiles, seconds, buckets}`` — ``compiles``
        is the trace-counter delta during the pre-compile (the switch
        itself adds none; the bench asserts that)."""
        shape = tuple(example_shape) if example_shape is not None \
            else self.example_shape()
        if shape is None:
            raise ValueError(
                "cannot infer the per-example input shape from the model "
                "conf; pass retune_buckets(..., example_shape=...)")
        with self._reload_lock:
            pol = new_policy.copy()
            if self.mesh is not None and self.mesh.n_data > 1:
                keep = [b for b in pol.batch_buckets
                        if b % self.mesh.n_data == 0]
                if not keep:
                    raise ValueError(
                        f"no batch bucket in {pol.batch_buckets} is "
                        f"divisible by the mesh data axis "
                        f"({self.mesh.n_data})")
                pol.batch_buckets = keep
            snap = self._snap
            before = self._compile_count
            t0 = time.perf_counter()
            shapes = pol.warmup_shapes(shape)
            for full_shape, with_mask in shapes:
                x = np.zeros(full_shape, np.float32)
                mask = (np.ones(full_shape[:2], np.float32)
                        if with_mask else None)
                self._forward_raw(snap, x, mask)
            self.buckets = pol  # atomic ref swap: old policy until here
            return {
                "shapes": len(shapes),
                "compiles": self._compile_count - before,
                "seconds": round(time.perf_counter() - t0, 3),
                "buckets": list(pol.batch_buckets),
            }

    # -- hardware-efficiency profile ----------------------------------------
    def publish_cost_metrics(self, example_shape: Optional[Sequence[int]]
                             = None, bucket: Optional[int] = None
                             ) -> dict:
        """Static cost sheet of the serving forward (obs/cost.py):
        lower+compile the snapshot's jitted forward at ``bucket``
        (default: the largest batch bucket — the shape a loaded server
        actually runs) and publish FLOPs / bytes-accessed / peak-memory
        gauges plus a serving MFU gauge into this engine's metrics
        registry. The MFU throughput term is the measured
        ``serving_real_samples_total`` rate — REAL dispatched rows, so
        bucket pad waste counts against utilization, exactly as it
        should.
        Call once after ``warmup()`` (re-lowering per request would
        re-trace); returns the analysis dict."""
        from deeplearning4j_tpu.obs import cost as _cost

        snap = self._snap
        if snap.fn is None:
            return {"error": f"{type(snap.model).__name__} serves through "
                             "the generic output path; no compiled "
                             "forward to analyze"}
        shape = (tuple(example_shape) if example_shape is not None
                 else self.example_shape())
        if shape is None:
            return {"error": "cannot infer the per-example input shape; "
                             "pass example_shape=..."}
        b = int(bucket) if bucket is not None else self.buckets.batch_buckets[-1]
        seq = self.buckets.seq_buckets is not None and len(shape) >= 2
        if seq:
            # the time axis pads to a seq bucket at dispatch — analyze
            # the program the server actually runs, not a never-served
            # raw-T shape (which would also compile a fresh executable
            # right after warmup closed the shape set)
            shape = (self.buckets.seq_bucket_for(shape[0]),) + tuple(
                shape[1:])
        full = (b,) + tuple(shape)
        x = np.zeros(full, np.float32)
        mask = np.ones(full[:2], np.float32) if seq else None
        out = _cost.compiled_analysis(snap.fn, snap.params, snap.state,
                                      x, mask)
        out["bucket"] = b
        if "error" in out:
            return out
        reg = self.metrics.registry
        _cost.publish_step_cost(reg, "serving", out,
                                labels={"bucket": str(b)})
        flops_per_example = float(out.get("flops", 0.0)) / b
        bytes_per_example = float(out.get("bytes_accessed", 0.0)) / b
        out["flops_per_example"] = flops_per_example
        _cost.publish_utilization(
            reg, "serving",
            flops_per_unit=flops_per_example,
            bytes_per_unit=bytes_per_example,
            # REAL rows dispatched (all buckets), counted by the engine
            # itself — covers batcher traffic AND direct infer callers,
            # and excludes padding rows from "useful FLOPs"
            units_per_sec=_cost.family_rate_fn(
                reg, "serving_real_samples_total"))
        from deeplearning4j_tpu.obs import flight as _flight

        _flight.record("cost_published", step="serving", bucket=b,
                       flops_per_example=flops_per_example)
        return out

    # -- hot reload ---------------------------------------------------------
    def reload(self, source: Optional[str] = None, force: bool = False
               ) -> dict:
        """Atomically swap in a new model version.

        ``source``: checkpoint zip, checkpoint directory, or None for
        the engine's ``checkpoint_dir``. A reload that resolves to the
        checkpoint already serving is a no-op unless ``force`` (the
        fingerprint check makes a periodic ``/reload`` poll free).

        Same architecture (identical conf JSON) keeps the compiled
        forward — the swap is pure params/state, zero recompiles. A
        different architecture builds and (if the engine was warmed)
        warms a fresh forward BEFORE the swap, so serving latency never
        absorbs the compiles.
        """
        from deeplearning4j_tpu.train.model_serializer import (
            ModelGuesser,
            ModelSerializer,
        )

        src = source or self.checkpoint_dir
        if src is None:
            raise ValueError("no reload source: pass a checkpoint path or "
                             "configure checkpoint_dir")
        with self._reload_lock:
            path = resolve_checkpoint_source(src)
            fp = self._path_fingerprint(path)
            if (not force and fp is not None and fp == self._fingerprint
                    and str(path) == str(self._snap.source)):
                return {"reloaded": False, "version": self._snap.version,
                        "path": path, "reason": "unchanged"}
            # cheap validation + provenance peek before the full restore
            meta = ModelSerializer.checkpoint_meta(path)
            new_model = ModelGuesser.load_model_guess(path)
            old = self._snap
            conf = getattr(new_model, "conf", None)
            conf_json = conf.to_json() if hasattr(conf, "to_json") else None
            same_arch = (conf_json is not None
                         and conf_json == old.conf_json
                         and old.fn is not None)
            if same_arch:
                # pure weight swap: reuse the old layer graph + compiled
                # programs; only the param/state pytrees change (same
                # shapes → jit cache hits, zero recompiles)
                snap = _Snapshot.__new__(_Snapshot)
                snap.model = old.model
                snap.params = (self._quantize_params(new_model)
                               if self.int8_serving else new_model.params_)
                snap.state = new_model.state_
                snap.fn = old.fn
                snap.conf_json = old.conf_json
                snap.version = old.version + 1
                snap.source = path
                snap.loaded_at = time.time()
                if self.mesh is not None:
                    snap.params = jax.device_put(snap.params,
                                                 self.mesh.replicated())
                    snap.state = jax.device_put(snap.state,
                                                self.mesh.replicated())
            else:
                snap = self._build_snapshot(new_model,
                                            version=old.version + 1,
                                            source=path)
                if self.warm:
                    # warm the NEW snapshot before exposing it (its own
                    # input type — the architecture changed)
                    shape = (conf_example_shape(conf)
                             or self.example_shape())
                    if shape is not None:
                        self._warm_snapshot(snap, shape)
            self._snap = snap  # the atomic publish
            self._fingerprint = fp
            self.metrics.record_reload()
            from deeplearning4j_tpu.obs import flight as _flight

            _flight.record("hot_reload", version=snap.version,
                           path=str(path), same_arch=bool(same_arch))
            return {"reloaded": True, "version": snap.version, "path": path,
                    "same_arch": bool(same_arch),
                    "checkpoint_iteration": meta.get("iteration"),
                    "checkpoint_epoch": meta.get("epoch")}
