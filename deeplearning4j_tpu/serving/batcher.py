"""Deadline-based dynamic batcher with bounded-queue backpressure.

The dispatch rule is the standard serving one (TF Serving's
BatchScheduler): a batch launches when it reaches ``batch_limit``
examples OR when ``max_wait_ms`` has elapsed since its first request —
whichever comes first. Low traffic pays at most ``max_wait_ms`` extra
latency; high traffic fills batches immediately and the wait never
triggers.

Three deliberate departures from the old ``ParallelInference`` loop:

- **No overshoot**: the old loop checked ``total < batch_limit`` before
  pulling the next request, so a dispatched batch could exceed the
  limit by up to one request's rows. Here a request that would overflow
  the limit stays queued (a one-slot ``pending`` carry) and opens the
  next batch.
- **Backpressure, not unbounded blocking**: the queue is bounded and a
  full queue rejects with a typed :class:`ServerOverloadedError`
  immediately — callers (and the HTTP front-end, as a 503) get a signal
  they can act on, instead of threads silently piling up on a blocking
  ``put``.
- **Race-free shutdown**: ``shutdown`` flips the flag BEFORE joining,
  the worker drains what is queued, and a submit that slips past the
  flag check re-checks after enqueue and fails its own request — so no
  caller can block forever on a request nobody will serve (the old
  code's put-after-drain hang).

The batcher is model-agnostic: ``dispatch(batch)`` receives the
coalesced :class:`InferenceRequest` list on the worker thread and must
complete each one (the engine/front-end own padding, bucketing and
result slicing). Completion is idempotent first-wins, which makes
caller-side timeouts and shutdown races safe by construction.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from deeplearning4j_tpu.chaos import hooks as chaos_hooks
from deeplearning4j_tpu.obs.lockwitness import witnessed_lock
from deeplearning4j_tpu.serving import rtrace
from deeplearning4j_tpu.serving.metrics import ServingMetrics


class ServingError(RuntimeError):
    """Base of the typed serving failures."""


class ServerOverloadedError(ServingError):
    """Bounded request queue is full — shed load upstream (HTTP 503).

    ``retry_after_s`` (when the rejecting surface can estimate one) is
    the backoff hint the HTTP front-end forwards as a ``Retry-After``
    header: current queue depth × recent per-dispatch wall time, i.e.
    roughly when the queue as it stands now will have drained."""

    retry_after_s: Optional[float] = None


class ServerShutdownError(ServingError):
    """Request arrived at (or survived into) server shutdown."""


class RequestDeadlineExceeded(ServingError, TimeoutError):
    """The request's deadline passed before (or while) serving it."""


class InferenceRequest:
    """One submitted request: input rows + synchronization.

    Completion (``finish``/``fail``) is idempotent and first-wins: a
    late worker result after a caller-side timeout, or a shutdown
    failure racing a drain dispatch, is a silent no-op instead of a
    double-set/torn state.
    """

    __slots__ = ("x", "mask", "deadline", "enqueued_at", "_event", "_lock",
                 "result_", "error_", "model_version", "trace")

    def __init__(self, x, mask=None, deadline: Optional[float] = None,
                 trace: bool = False):
        self.x = np.asarray(x)
        self.mask = None if mask is None else np.asarray(mask)
        #: absolute time.monotonic() deadline, or None
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        #: per-request stage timeline (serving/rtrace.py), or None
        self.trace = rtrace.RequestTrace() if trace else None
        self._event = threading.Event()
        self._lock = witnessed_lock("serving.batcher")
        self.result_: Optional[np.ndarray] = None
        self.error_: Optional[BaseException] = None
        #: version of the model snapshot that served this request (set by
        #: the dispatcher when the infer callable reports one)
        self.model_version: Optional[int] = None

    @property
    def rows(self) -> int:
        return int(self.x.shape[0]) if self.x.ndim else 1

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                > self.deadline)

    def done(self) -> bool:
        return self._event.is_set()

    def finish(self, result) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self.result_ = result
            self._event.set()
            return True

    def fail(self, error: BaseException) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self.error_ = error
            self._event.set()
            return True

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block for the outcome. On timeout the request is failed
        (idempotently — a concurrent worker completion wins) and
        :class:`RequestDeadlineExceeded` raises."""
        if not self._event.wait(timeout):
            self.fail(RequestDeadlineExceeded(
                f"request not served within timeout={timeout}s"))
            self._event.wait()  # lost the race → a result exists; reread
        if self.error_ is not None:
            raise self.error_
        return self.result_


def make_dispatcher(infer: Callable[..., np.ndarray],
                    metrics: Optional[ServingMetrics] = None,
                    traces: Optional["rtrace.TraceBuffer"] = None
                    ) -> Callable[[List[InferenceRequest]], None]:
    """Standard dispatch: group coalesced requests by compatible shape
    (same per-row shape, same mask presence/shape), concatenate each
    group into one ``infer(x, mask)`` call, slice the rows back out to
    their requests. Incompatible stragglers just form their own groups —
    never an error, only a smaller batch.

    ``infer`` may return either the output rows, or ``(rows, version)``
    (``InferenceEngine.infer_versioned``) — the version is stamped onto
    each request before completion so callers can attribute results to
    the exact model snapshot that computed them, even across a
    concurrent hot reload.

    Requests carrying a :class:`~serving.rtrace.RequestTrace` get their
    dispatch/forward/slice marks stamped here, with bucket and
    pad-waste facts flowing back from the engine through the rtrace
    dispatch context; completed timelines land in ``traces`` (the
    ``GET /trace`` window).
    """

    def signature(r: InferenceRequest):
        return (r.x.shape[1:], None if r.mask is None else r.mask.shape[1:])

    def dispatch(batch: List[InferenceRequest]) -> None:
        groups: dict = {}
        for r in batch:
            groups.setdefault(signature(r), []).append(r)
        for reqs in groups.values():
            if len(reqs) == 1:
                x, mask = reqs[0].x, reqs[0].mask
            else:
                x = np.concatenate([r.x for r in reqs], axis=0)
                mask = (None if reqs[0].mask is None
                        else np.concatenate([r.mask for r in reqs], axis=0))
            traced = [r for r in reqs if r.trace is not None]
            info = None
            if traced:
                info = rtrace.begin_dispatch()
                t_ds = time.monotonic()
                for r in traced:
                    r.trace.mark("dispatch_start", t_ds)
            try:
                try:
                    # chaos seam: injected error ≡ a device/dispatch
                    # failure, injected delay ≡ a slow dispatch — both
                    # flow through the same typed completion below
                    chaos_hooks.fire("serving.batch_dispatch",
                                     rows=sum(r.rows for r in reqs))
                    out = infer(x, mask)
                finally:
                    if traced:
                        rtrace.end_dispatch()
            except BaseException as e:  # noqa: BLE001 — routed to every request's typed failure path
                if metrics is not None:
                    metrics.record_error()
                for r in reqs:
                    r.fail(e)
                continue
            version = None
            if isinstance(out, tuple):
                out, version = out
            if traced:
                now = time.monotonic()
                padded = info.rows_padded
                real = info.rows_real
                waste = (None if not padded or real is None
                         else round((padded - real) / padded, 4))
                for r in traced:
                    r.trace.mark("forward_done", info.t_forward_done or now)
                    r.trace.mark("sliced", info.t_sliced or now)
                    r.trace.note(
                        rows=r.rows, bucket=info.bucket,
                        batch_rows_real=real, batch_rows_padded=padded,
                        pad_waste=waste, model_version=version,
                        seq_real=info.seq_real, seq_padded=info.seq_padded)
            off = 0
            now = time.monotonic()
            for r in reqs:
                n = r.rows
                r.model_version = version  # before finish: the waiter
                # reads it as soon as the event fires
                if r.trace is not None:
                    r.trace.mark("respond")
                r.finish(out[off:off + n])
                off += n
                if metrics is not None:
                    metrics.record_latency(now - r.enqueued_at)
                if traces is not None and r.trace is not None:
                    traces.add(r.trace)  # object ref; timeline built at
                    # /trace read time, off the worker thread

    return dispatch


class DynamicBatcher:
    def __init__(self, dispatch: Callable[[List[InferenceRequest]], None],
                 batch_limit: int = 32, max_wait_ms: float = 5.0,
                 queue_limit: int = 64,
                 metrics: Optional[ServingMetrics] = None,
                 trace_requests: bool = False):
        self._dispatch = dispatch
        self.batch_limit = max(int(batch_limit), 1)
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        self._queue: "queue.Queue[InferenceRequest]" = queue.Queue(
            maxsize=max(int(queue_limit), 1))
        self.metrics = metrics if metrics is not None else ServingMetrics()
        #: default for ``submit(trace=None)``: stamp a stage timeline on
        #: every request (the HTTP server turns this on so /trace always
        #: has a recent window; per-request opt-in/out overrides)
        self.trace_requests = bool(trace_requests)
        self._shutdown = False
        # EWMA of per-dispatch wall seconds: the Retry-After estimator's
        # service-time term (seeded pessimistically by the first real
        # dispatch; until then overloads suggest a 1s floor)
        self._dispatch_ewma_s: Optional[float] = None
        self._pending: Optional[InferenceRequest] = None  # worker-only slot
        self._worker = threading.Thread(
            target=self._loop, daemon=True, name="dl4j-tpu-batcher")
        self._worker.start()

    # -- client side --------------------------------------------------------
    def queue_depth(self) -> int:
        return self._queue.qsize()

    def set_max_wait_ms(self, max_wait_ms: float) -> float:
        """Retune the dispatch deadline live. The worker reads
        ``max_wait_s`` fresh at every batch boundary, so the new
        deadline applies from the next coalescing window — no restart,
        no queued-request disruption. This is the adaptive-capacity
        controllers' cheapest knob (latency-vs-throughput trade, zero
        recompiles). Returns the applied milliseconds."""
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        return self.max_wait_s * 1e3

    def retry_after_s(self) -> float:
        """Backoff hint for overloaded clients: the current queue depth
        × the recent per-dispatch wall time (EWMA), clamped to [1, 60]s
        — roughly when today's queue will have drained. Served as the
        ``Retry-After`` header on 503s so clients back off instead of
        hammering."""
        per_dispatch = self._dispatch_ewma_s or 0.0
        est = self._queue.qsize() * per_dispatch
        return min(max(est, 1.0), 60.0)

    def submit(self, x, mask=None, timeout: Optional[float] = None,
               trace: Optional[bool] = None) -> InferenceRequest:
        """Enqueue a request; returns immediately (block on
        ``req.result()``). ``timeout`` sets the request's deadline —
        enforced both while queued (expired requests are dropped, not
        dispatched) and by ``result``'s wait. ``trace`` overrides the
        batcher's ``trace_requests`` default for this request."""
        if self._shutdown:
            raise ServerShutdownError("server is shut down")
        req = InferenceRequest(
            x, mask,
            deadline=None if timeout is None
            else time.monotonic() + float(timeout),
            trace=self.trace_requests if trace is None else bool(trace))
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self.metrics.record_reject()
            from deeplearning4j_tpu.obs import flight as _flight

            _flight.record("overload_reject", rows=req.rows,
                           queue_limit=self._queue.maxsize)
            err = ServerOverloadedError(
                f"request queue full ({self._queue.maxsize} requests); "
                "retry with backoff or scale out")
            err.retry_after_s = self.retry_after_s()
            raise err from None
        # shutdown may have drained the queue between the flag check and
        # the put — fail our own request so the caller can never block
        # on a request no worker will look at (first-wins: if the drain
        # DID serve it, this is a no-op)
        if self._shutdown and req.fail(
                ServerShutdownError("server shut down while enqueuing")):
            raise ServerShutdownError("server shut down while enqueuing")
        self.metrics.record_request(req.rows)
        return req

    # -- worker side --------------------------------------------------------
    def _next(self, timeout: Optional[float]) -> Optional[InferenceRequest]:
        if self._pending is not None:
            req, self._pending = self._pending, None
            return req
        try:
            if timeout is None or timeout <= 0:
                return self._queue.get_nowait()
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def _loop(self) -> None:
        while True:
            first = self._next(0.05)
            if first is None:
                if self._shutdown:
                    return
                continue
            batch = [first]
            total = first.rows
            # coalesce up to batch_limit or the wait window, WITHOUT
            # overshooting: a request that would overflow stays pending
            window_end = time.monotonic() + self.max_wait_s
            while total < self.batch_limit:
                wait = window_end - time.monotonic()
                if self._shutdown:
                    wait = 0.0  # draining: take only what's already here
                nxt = self._next(wait)
                if nxt is None:
                    break
                if total + nxt.rows > self.batch_limit:
                    self._pending = nxt
                    break
                batch.append(nxt)
                total += nxt.rows
            now = time.monotonic()
            live: List[InferenceRequest] = []
            for r in batch:
                if r.done():
                    continue  # timed out caller-side / failed at shutdown
                if r.expired(now):
                    self.metrics.record_deadline()
                    r.fail(RequestDeadlineExceeded(
                        "request deadline passed while queued"))
                    continue
                live.append(r)
            if not live:
                continue
            t_assembled = time.monotonic()
            for r in live:
                if r.trace is not None:
                    r.trace.mark("batch_assembled", t_assembled)
            t_dispatch = time.monotonic()
            try:
                self._dispatch(live)
                for r in live:
                    if not r.done():  # dispatcher contract violation
                        r.fail(ServingError(
                            "dispatch returned without completing request"))
            except BaseException as e:  # noqa: BLE001 — routed to every request's typed failure path
                self.metrics.record_error()
                for r in live:
                    r.fail(e)
            dt = time.monotonic() - t_dispatch
            self._dispatch_ewma_s = (
                dt if self._dispatch_ewma_s is None
                else 0.8 * self._dispatch_ewma_s + 0.2 * dt)

    # -- lifecycle ----------------------------------------------------------
    def shutdown(self, drain: bool = True, timeout: float = 10.0) -> None:
        """Stop accepting work, serve (``drain=True``) or fail what is
        queued, and join the worker. Idempotent."""
        self._shutdown = True  # BEFORE join: unblocks the worker's exit
        if not drain:
            self._fail_queued(ServerShutdownError(
                "server shut down before serving request"))
        self._worker.join(timeout=timeout)
        # belt and braces: if the worker died or overran the join
        # timeout, nobody will ever serve the leftovers — fail them
        self._fail_queued(ServerShutdownError(
            "server shut down before serving request"))

    def _fail_queued(self, err: ServingError) -> None:
        if self._pending is not None and not self._worker.is_alive():
            self._pending.fail(err)
            self._pending = None
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            req.fail(err)
