"""Stdlib HTTP serving front-end.

``ThreadingHTTPServer`` (one thread per connection) in front of the
dynamic batcher: handler threads submit into the bounded queue and
block on their own request, the single batcher worker coalesces across
them into bucketed device dispatches. No third-party web framework —
the container ships none, and the stdlib server is enough to express
the production contract:

- ``POST /predict``        JSON ``{"inputs": [[...]], "mask": [...]?,
                           "timeout_ms": n?}`` → ``{"outputs": [...]}``
- ``POST /predict_npy``    raw ``.npy`` body → ``.npy`` response
                           (zero JSON float cost for bulk clients)
- ``POST /generate``       continuous-batching autoregressive
                           generation (serving/generate.py; requires a
                           ``generation=`` engine — 409 otherwise).
                           JSON ``{"prompt": [ids], "max_new": n,
                           "temperature": t?, "top_k": k?, "top_p": p?,
                           "seed": s?, "timeout_ms": n?,
                           "stream": bool?}``. ``stream=true``
                           (default) answers with chunked
                           newline-delimited JSON: one ``{"token": id}``
                           line per decoded token AS IT DECODES, then a
                           ``{"done": true, "tokens": [...], ...}``
                           summary line; ``stream=false`` buffers and
                           returns one JSON body.
- ``GET  /healthz``        liveness + model version/warm state +
                           checkpoint fingerprint/snapshot version/
                           uptime (the keys canary & rollback tooling
                           watches)
- ``POST /reload``         hot-swap to the newest valid checkpoint
                           (optional JSON ``{"path": ...,
                           "force": bool}``)
- ``GET  /metrics``        counters, queue depth, per-bucket hits +
                           pad-waste ratios, latency quantiles (ring
                           buffer). Content-negotiated: JSON by default
                           (the original surface), Prometheus text
                           exposition when the client Accepts
                           ``text/plain``/openmetrics or asks
                           ``?format=prometheus`` — one scrape config
                           covers serving and training (obs/exporter.py)
- ``GET  /trace``          recent per-request timelines (bounded ring;
                           ``?last=N`` trims) — the "where did THIS
                           request's latency go" window. A client that
                           wants its own timeline inline passes
                           ``{"trace": true}`` in /predict and gets a
                           ``trace`` key back in the response.
- ``GET  /debug/flight``   the process flight-recorder ring
                           (obs/flight.py) as JSON
- ``GET  /debug/profile``  on-demand ``jax.profiler`` capture for
                           ``?ms=`` milliseconds (409 while another
                           capture runs)

Typed failures map to transport codes: queue-full backpressure → 503
(clients back off), request deadline → 504, malformed input → 400,
shutdown → 503, concurrent profiler capture → 409.
"""

from __future__ import annotations

import io
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.serving.batcher import (
    DynamicBatcher,
    RequestDeadlineExceeded,
    ServerOverloadedError,
    ServerShutdownError,
    make_dispatcher,
)
from deeplearning4j_tpu.serving.engine import InferenceEngine
from deeplearning4j_tpu.serving.metrics import ServingMetrics


class InferenceServer:
    """Engine + batcher + HTTP listener. ``port=0`` binds an ephemeral
    port (read it back from ``server.port`` — the test/CI pattern)."""

    def __init__(self, engine: InferenceEngine, host: str = "127.0.0.1",
                 port: int = 8080, batch_limit: int = 32,
                 max_wait_ms: float = 5.0, queue_limit: int = 256,
                 default_timeout_s: float = 30.0,
                 trace_requests: bool = True,
                 trace_buffer_size: int = 256,
                 generation=None):
        from deeplearning4j_tpu.serving.rtrace import TraceBuffer

        self.engine = engine
        #: optional serving/generate.py GenerationEngine behind
        #: POST /generate (None → the route answers 409)
        self.generation = generation
        self.metrics: ServingMetrics = engine.metrics
        self.default_timeout_s = float(default_timeout_s)
        #: recent per-request timelines (GET /trace). trace_requests
        #: stamps a timeline on EVERY request (a handful of monotonic
        #: reads — the bench gates its p99 cost at <=5%); off, only
        #: requests that opt in via {"trace": true} are traced.
        self.traces = TraceBuffer(trace_buffer_size)
        # bind the socket BEFORE starting the batcher worker: a bind
        # failure (EADDRINUSE) must raise without leaking a polling
        # thread nobody holds a handle to
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((host, int(port)), handler)
        self._httpd.daemon_threads = True
        # late-bound engine lookup: hot tooling (tests, chaos drills)
        # may wrap engine.infer after construction. infer_versioned
        # stamps each request with the snapshot version that actually
        # computed it (a concurrent hot reload must not mislabel
        # responses).
        self.batcher = DynamicBatcher(
            make_dispatcher(
                lambda x, mask=None: self.engine.infer_versioned(x, mask),
                metrics=self.metrics, traces=self.traces),
            batch_limit=batch_limit, max_wait_ms=max_wait_ms,
            queue_limit=queue_limit, metrics=self.metrics,
            trace_requests=trace_requests)
        if self.generation is not None and self.generation.traces is None:
            # generation request timelines land in the same /trace ring
            self.generation.traces = self.traces
        self._thread: Optional[threading.Thread] = None
        self._serving = False
        self._closed = False

    # -- lifecycle ----------------------------------------------------------
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "InferenceServer":
        self._serving = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dl4j-tpu-http")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._serving = True
        self._httpd.serve_forever()

    def shutdown(self) -> None:
        """Stop the listener, then drain the batcher (in-flight requests
        finish; the bounded queue is served, not dropped). Idempotent —
        a supervisor's double-shutdown (or shutdown of a server whose
        serve loop never ran) must not hang or double-close."""
        if self._serving:  # BaseServer.shutdown deadlocks if the serve
            self._httpd.shutdown()  # loop never ran
            self._serving = False
        if not self._closed:
            self._closed = True
            self._httpd.server_close()
        self.batcher.shutdown(drain=True)
        if self.generation is not None:
            self.generation.shutdown(drain=True)
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- request plumbing (called from handler threads) ----------------------
    def predict(self, x: np.ndarray, mask=None,
                timeout_s: Optional[float] = None,
                trace: Optional[bool] = None):
        """Returns ``(outputs, model_version)`` — the version of the
        snapshot that actually computed them (stamped in the dispatch,
        so a concurrent hot reload cannot mislabel the response).
        ``trace=True`` forces a stage timeline onto this request even
        when batcher-level tracing is off; read it from
        :meth:`predict_request`."""
        out, version, _ = self.predict_request(x, mask, timeout_s, trace)
        return out, version

    def predict_request(self, x: np.ndarray, mask=None,
                        timeout_s: Optional[float] = None,
                        trace: Optional[bool] = None):
        """Like :meth:`predict` but also returns the completed
        :class:`~serving.batcher.InferenceRequest` (its ``trace`` holds
        the stage timeline when tracing was on)."""
        timeout = self.default_timeout_s if timeout_s is None else timeout_s
        req = self.batcher.submit(x, mask, timeout=timeout, trace=trace)
        out = req.result(timeout=timeout)
        version = req.model_version
        return out, (self.engine.model_version if version is None
                     else version), req


def _make_handler(server: InferenceServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # quiet by default: per-request stderr lines are noise at load
        def log_message(self, fmt, *args):  # noqa: N802
            pass

        # -- helpers --------------------------------------------------------
        def _send(self, code: int, body: bytes,
                  ctype: str = "application/json") -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, obj: dict) -> None:
            self._send(code, json.dumps(obj).encode())

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length", 0) or 0)
            return self.rfile.read(n) if n else b""

        def _error(self, e: BaseException) -> None:
            name = type(e).__name__
            if isinstance(e, ServerOverloadedError):
                code = 503
            elif isinstance(e, RequestDeadlineExceeded):
                code = 504
            elif isinstance(e, ServerShutdownError):
                code = 503
            elif isinstance(e, (ValueError, KeyError, TypeError)):
                code = 400
            else:
                code = 500
            self._send_json(code, {"error": name, "message": str(e)})

        # -- routes ---------------------------------------------------------
        def do_GET(self):  # noqa: N802
            from urllib.parse import urlparse

            from deeplearning4j_tpu.obs.exporter import (
                PROMETHEUS_CTYPE,
                wants_prometheus,
            )

            try:
                url = urlparse(self.path)
                if url.path == "/healthz":
                    info = server.engine.describe()
                    info["snapshot_version"] = info.get("version")
                    info["uptime_s"] = round(
                        time.time() - server.metrics.started_at, 3)
                    if server.generation is not None:
                        info["generation"] = server.generation.describe()
                    self._send_json(200, {"status": "ok", **info})
                elif url.path == "/metrics":
                    depth = server.batcher.queue_depth()
                    if wants_prometheus(self.headers.get("Accept", ""),
                                        url.query):
                        self._send(200, server.metrics.prometheus_text(
                            queue_depth=depth).encode(), PROMETHEUS_CTYPE)
                    else:
                        body = server.metrics.snapshot(queue_depth=depth)
                        if server.generation is not None:
                            body["generation"] = \
                                server.generation.metrics.snapshot()
                        self._send_json(200, body)
                elif url.path == "/trace":
                    from urllib.parse import parse_qs

                    last = parse_qs(url.query).get("last", [None])[0]
                    body = server.traces.snapshot(
                        last=None if last is None else int(last))
                    body["pad_waste"] = {
                        str(k): v
                        for k, v in sorted(
                            server.metrics.pad_waste().items())}
                    self._send_json(200, body)
                elif url.path == "/debug/flight":
                    from deeplearning4j_tpu.obs.exporter import (
                        debug_flight_response,
                    )

                    self._send_json(*debug_flight_response())
                elif url.path == "/debug/profile":
                    from deeplearning4j_tpu.obs.exporter import (
                        debug_profile_response,
                    )

                    self._send_json(*debug_profile_response(url.query))
                else:
                    self._send_json(404, {"error": "NotFound",
                                          "message": self.path})
            except BaseException as e:  # never kill the connection thread
                self._error(e)

        def do_POST(self):  # noqa: N802
            try:
                if self.path == "/predict":
                    self._predict_json()
                elif self.path == "/predict_npy":
                    self._predict_npy()
                elif self.path == "/generate":
                    self._generate()
                elif self.path == "/reload":
                    self._reload()
                else:
                    self._send_json(404, {"error": "NotFound",
                                          "message": self.path})
            except BaseException as e:
                self._error(e)

        def _predict_json(self) -> None:
            try:
                payload = json.loads(self._body() or b"{}")
                x = np.asarray(payload["inputs"], np.float32)
            except (ValueError, KeyError, TypeError) as e:
                raise ValueError(f"bad /predict payload: {e}") from e
            if x.ndim == 1:
                x = x[None, :]  # single example convenience
            mask = payload.get("mask")
            if mask is not None:
                mask = np.asarray(mask, np.float32)
            timeout_ms = payload.get("timeout_ms")
            want_trace = bool(payload.get("trace", False))
            out, version, req = server.predict_request(
                x, mask,
                timeout_s=None if timeout_ms is None
                else float(timeout_ms) / 1e3,
                # None keeps the batcher default; True forces a
                # timeline even when server-level tracing is off
                trace=True if want_trace else None)
            body = {"outputs": np.asarray(out).tolist(),
                    "model_version": version}
            if want_trace and req.trace is not None:
                body["trace"] = req.trace.timeline()
            self._send_json(200, body)

        def _generate(self) -> None:
            """Continuous-batching generation. Submit errors (overload,
            window overflow, shutdown) raise BEFORE any header is sent
            and map to their typed transport codes; once a stream has
            started, a mid-decode failure becomes a terminal
            ``{"error": ...}`` chunk (the status line is already on the
            wire)."""
            if server.generation is None:
                self._send_json(409, {
                    "error": "NoGenerationEngine",
                    "message": "server started without a generation "
                               "engine (cli serve --gen-slots N)"})
                return
            try:
                payload = json.loads(self._body() or b"{}")
                prompt = np.asarray(payload["prompt"], np.int32).reshape(-1)
            except (ValueError, KeyError, TypeError) as e:
                raise ValueError(f"bad /generate payload: {e}") from e
            timeout_ms = payload.get("timeout_ms")
            timeout_s = (None if timeout_ms is None
                         else float(timeout_ms) / 1e3)
            want_trace = payload.get("trace")
            req = server.generation.submit(
                prompt,
                max_new=int(payload.get("max_new", 20)),
                temperature=float(payload.get("temperature", 0.0)),
                top_k=int(payload.get("top_k", 0)),
                top_p=float(payload.get("top_p", 0.0)),
                seed=int(payload.get("seed", 0)),
                timeout=timeout_s,
                trace=None if want_trace is None else bool(want_trace))
            wait_s = (server.generation.default_timeout_s
                      if timeout_s is None else timeout_s)
            if not payload.get("stream", True):
                out = req.result(timeout=wait_s)
                body = {"tokens": [int(t) for t in req.tokens],
                        "sequence": out.tolist(),
                        "prompt_len": int(prompt.size)}
                if want_trace and req.trace is not None:
                    body["trace"] = req.trace.timeline()
                self._send_json(200, body)
                return
            # chunked newline-delimited JSON: tokens land on the wire
            # as the decode loop emits them
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(obj: dict) -> None:
                data = (json.dumps(obj) + "\n").encode()
                self.wfile.write(f"{len(data):x}\r\n".encode()
                                 + data + b"\r\n")
                self.wfile.flush()

            try:
                for tok in req.stream(timeout=wait_s):
                    chunk({"token": int(tok)})
                summary = {"done": True,
                           "tokens": [int(t) for t in req.tokens],
                           "prompt_len": int(prompt.size)}
                if want_trace and req.trace is not None:
                    summary["trace"] = req.trace.timeline()
                chunk(summary)
            except BaseException as e:
                # the status line is on the wire; a decode failure
                # becomes a terminal chunk. If writing THAT fails too
                # (client went away mid-stream), swallow it — letting
                # it propagate would re-enter do_POST's _error(),
                # which injects a second status line into the chunked
                # body on a half-writable socket.
                try:
                    chunk({"error": type(e).__name__, "message": str(e)})
                except OSError:
                    return
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

        def _predict_npy(self) -> None:
            body = self._body()
            try:
                x = np.load(io.BytesIO(body), allow_pickle=False)
            except (ValueError, EOFError, OSError) as e:
                # empty/truncated bodies raise EOFError/OSError from
                # np.load — all are the client's malformed input (400)
                raise ValueError(f"bad /predict_npy body: {e}") from e
            out, _ = server.predict(np.asarray(x, np.float32))
            buf = io.BytesIO()
            np.save(buf, np.asarray(out), allow_pickle=False)
            self._send(200, buf.getvalue(), ctype="application/x-npy")

        def _reload(self) -> None:
            body = self._body()
            payload = json.loads(body) if body else {}
            try:
                result = server.engine.reload(
                    source=payload.get("path"),
                    force=bool(payload.get("force", False)))
            except FileNotFoundError as e:
                self._send_json(409, {"error": "FileNotFoundError",
                                      "message": str(e)})
                return
            self._send_json(200, result)

    return Handler
