"""Stdlib HTTP serving front-end.

``ThreadingHTTPServer`` (one thread per connection) in front of the
dynamic batcher: handler threads submit into the bounded queue and
block on their own request, the single batcher worker coalesces across
them into bucketed device dispatches. No third-party web framework —
the container ships none, and the stdlib server is enough to express
the production contract:

- ``POST /predict``        JSON ``{"inputs": [[...]], "mask": [...]?,
                           "timeout_ms": n?}`` → ``{"outputs": [...]}``
- ``POST /predict_npy``    raw ``.npy`` body → ``.npy`` response
                           (zero JSON float cost for bulk clients)
- ``POST /generate``       continuous-batching autoregressive
                           generation (serving/generate.py; requires a
                           ``generation=`` engine — 409 otherwise).
                           JSON ``{"prompt": [ids], "max_new": n,
                           "temperature": t?, "top_k": k?, "top_p": p?,
                           "seed": s?, "timeout_ms": n?,
                           "stream": bool?}``. ``stream=true``
                           (default) answers with chunked
                           newline-delimited JSON: one ``{"token": id}``
                           line per decoded token AS IT DECODES, then a
                           ``{"done": true, "tokens": [...], ...}``
                           summary line; ``stream=false`` buffers and
                           returns one JSON body.
- ``GET  /healthz``        liveness + model version/warm state +
                           checkpoint fingerprint/snapshot version/
                           uptime (the keys canary & rollback tooling
                           watches) + the SLO alert engine's
                           ``verdict`` (healthy/degraded/critical —
                           obs/alerts.py)
- ``GET  /alerts``         the alert engine's rule states and health
                           verdict (obs/slo.py default pack over this
                           server's registry + the flight ring).
                           Content-negotiated: JSON by default, a
                           Prometheus-style ``ALERTS`` firing list via
                           Accept/?format=prometheus. Evaluation is
                           scrape-driven: each hit runs at most one
                           throttled evaluator tick
- ``POST /reload``         hot-swap to the newest valid checkpoint
- ``POST /drain``          enter drain mode: new requests are refused
                           typed (503 + Retry-After) while in-flight
                           work — streaming /generate included —
                           finishes; the replica-loss/rollout front
                           moves new sessions to live replicas
                           (optional JSON ``{"path": ...,
                           "force": bool}``)
- ``GET  /metrics``        counters, queue depth, per-bucket hits +
                           pad-waste ratios, latency quantiles (ring
                           buffer). Content-negotiated: JSON by default
                           (the original surface), Prometheus text
                           exposition when the client Accepts
                           ``text/plain``/openmetrics or asks
                           ``?format=prometheus`` — one scrape config
                           covers serving and training (obs/exporter.py)
- ``GET  /trace``          recent per-request timelines (bounded ring;
                           ``?last=N`` trims) — the "where did THIS
                           request's latency go" window. A client that
                           wants its own timeline inline passes
                           ``{"trace": true}`` in /predict and gets a
                           ``trace`` key back in the response.
- ``GET  /debug/flight``   the process flight-recorder ring
                           (obs/flight.py) as JSON;
                           ``?since_seq=N`` returns only events newer
                           than seq N (incremental polling — pass the
                           response's ``next_since_seq`` back)
- ``GET  /debug/profile``  on-demand ``jax.profiler`` capture for
                           ``?ms=`` milliseconds (409 while another
                           capture runs)

Registry mode (``router=``, serving/registry.py) adds multi-model
routing:

- ``POST /models/<name>/predict``      route by model name (canary
                                       routing + per-tenant quotas in
                                       the router); plain ``/predict``
                                       with a ``"model"`` payload key
                                       routes too
- ``POST /models/<name>/predict_npy``  raw-npy variant
- ``POST /models/<name>/generate``     the model's continuous-batching
                                       generation engine
- ``GET  /models/<name>/healthz``      per-model readiness
                                       (active/canary versions, warm
                                       state — 503 until a version is
                                       active)

Typed failures map to transport codes: queue-full backpressure → 503
(clients back off), request deadline → 504, malformed input → 400,
shutdown → 503, concurrent profiler capture → 409, unknown model →
404, per-tenant quota / canary rolled back mid-request → 503. Every
503 carries a ``Retry-After`` header derived from the rejecting
surface's queue depth × recent per-dispatch time.
"""

from __future__ import annotations

import io
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.serving.batcher import (
    DynamicBatcher,
    RequestDeadlineExceeded,
    ServerOverloadedError,
    ServerShutdownError,
    make_dispatcher,
)
from deeplearning4j_tpu.serving.engine import InferenceEngine
from deeplearning4j_tpu.serving.metrics import ServingMetrics


class ServerDrainingError(ServerOverloadedError):
    """This replica is draining: new requests are refused (503 +
    Retry-After → the front routes them to a live replica) while
    already-accepted work — including in-flight /generate streams —
    runs to completion."""


class InferenceServer:
    """Engine + batcher + HTTP listener. ``port=0`` binds an ephemeral
    port (read it back from ``server.port`` — the test/CI pattern).

    Two mounting modes:

    - **single-model** (``engine=...``): the original PR-3 surface —
      one engine behind /predict, unchanged.
    - **registry** (``router=...``, a
      :class:`~serving.registry.ModelRouter`): multi-model serving —
      ``POST /models/<name>/predict`` and ``POST /models/<name>/generate``
      route by model name across the router's warmed engines (canary
      routing, per-tenant quotas, LRU eviction all live in the router);
      ``GET /models/<name>/healthz`` is the per-model readiness probe;
      plain ``/predict`` also routes when the payload carries a
      ``"model"`` key. Tenants come from the ``X-Tenant`` header or a
      ``"tenant"`` payload key. Both modes attach a ``Retry-After``
      header to every 503 (backpressure clients can act on).
    """

    def __init__(self, engine: Optional[InferenceEngine] = None,
                 host: str = "127.0.0.1",
                 port: int = 8080, batch_limit: int = 32,
                 max_wait_ms: float = 5.0, queue_limit: int = 256,
                 default_timeout_s: float = 30.0,
                 trace_requests: bool = True,
                 trace_buffer_size: int = 256,
                 generation=None, router=None, alerts=None):
        from deeplearning4j_tpu.serving.rtrace import TraceBuffer

        if engine is None and router is None:
            raise ValueError("InferenceServer needs an engine (single-"
                             "model) and/or a router (registry serving)")
        self.engine = engine
        #: optional serving/registry.py ModelRouter behind /models/...
        self.router = router
        #: optional serving/generate.py GenerationEngine behind
        #: POST /generate (None → the route answers 409)
        self.generation = generation
        self.metrics: ServingMetrics = (engine.metrics if engine is not None
                                        else router.metrics)
        self.default_timeout_s = float(default_timeout_s)
        #: recent per-request timelines (GET /trace). trace_requests
        #: stamps a timeline on EVERY request (a handful of monotonic
        #: reads — the bench gates its p99 cost at <=5%); off, only
        #: requests that opt in via {"trace": true} are traced.
        self.traces = TraceBuffer(trace_buffer_size)
        if router is not None and router.traces is None:
            router.traces = self.traces
        # bind the socket BEFORE starting the batcher worker: a bind
        # failure (EADDRINUSE) must raise without leaking a polling
        # thread nobody holds a handle to
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((host, int(port)), handler)
        self._httpd.daemon_threads = True
        # late-bound engine lookup: hot tooling (tests, chaos drills)
        # may wrap engine.infer after construction. infer_versioned
        # stamps each request with the snapshot version that actually
        # computed it (a concurrent hot reload must not mislabel
        # responses).
        self.batcher = None
        if engine is not None:
            self.batcher = DynamicBatcher(
                make_dispatcher(
                    lambda x, mask=None: self.engine.infer_versioned(x,
                                                                     mask),
                    metrics=self.metrics, traces=self.traces),
                batch_limit=batch_limit, max_wait_ms=max_wait_ms,
                queue_limit=queue_limit, metrics=self.metrics,
                trace_requests=trace_requests)
        if self.generation is not None and self.generation.traces is None:
            # generation request timelines land in the same /trace ring
            self.generation.traces = self.traces
        #: the SLO alert evaluator behind GET /alerts and the /healthz
        #: verdict (obs/alerts.py): the default rule pack over THIS
        #: server's metrics registry, watching the flight ring.
        #: Scrape-driven (the Prometheus model) — each /alerts or
        #: /healthz hit runs at most one throttled tick.
        if alerts is not None:
            self.alerts = alerts
        else:
            from deeplearning4j_tpu.obs.slo import build_default_evaluator

            self.alerts = build_default_evaluator(
                registry=self.metrics.registry, queue_limit=queue_limit)
        self._thread: Optional[threading.Thread] = None
        self._serving = False
        self._closed = False
        #: drain mode (POST /drain): reject NEW requests typed while
        #: in-flight work (streams included) finishes — the session-
        #: sticky front moves new sessions to live replicas
        self._draining = False

    # -- lifecycle ----------------------------------------------------------
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "InferenceServer":
        self._serving = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dl4j-tpu-http")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._serving = True
        self._httpd.serve_forever()

    def drain(self) -> dict:
        """Enter drain mode: the listener stays up (in-flight streams
        keep their connection), but every NEW request is refused with a
        typed 503 until shutdown. Idempotent. Returns the drain state
        rollout tooling polls."""
        from deeplearning4j_tpu.obs import flight as _flight

        if not self._draining:
            self._draining = True
            _flight.record("drain_start",
                           port=self.port,
                           queue_depth=self.queue_depth())
        out = {"draining": True, "queue_depth": self.queue_depth()}
        if self.generation is not None:
            out["generation_inflight"] = self.generation.inflight()
        return out

    def queue_depth(self) -> int:
        depth = self.batcher.queue_depth() if self.batcher is not None \
            else 0
        if self.router is not None:
            depth += self.router.queue_depth()
        return depth

    def _check_draining(self) -> None:
        if self._draining:
            err = ServerDrainingError(
                "replica is draining; retry against another replica")
            err.retry_after_s = 1.0
            raise err

    def shutdown(self) -> None:
        """Stop the listener, then drain the batcher (in-flight requests
        finish; the bounded queue is served, not dropped). Idempotent —
        a supervisor's double-shutdown (or shutdown of a server whose
        serve loop never ran) must not hang or double-close."""
        if self._serving:  # BaseServer.shutdown deadlocks if the serve
            self._httpd.shutdown()  # loop never ran
            self._serving = False
        if not self._closed:
            self._closed = True
            self._httpd.server_close()
        if self.batcher is not None:
            self.batcher.shutdown(drain=True)
        if self.generation is not None:
            self.generation.shutdown(drain=True)
        if self.router is not None:
            self.router.shutdown()
        self.alerts.unwatch()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- request plumbing (called from handler threads) ----------------------
    def predict(self, x: np.ndarray, mask=None,
                timeout_s: Optional[float] = None,
                trace: Optional[bool] = None, model: Optional[str] = None,
                tenant: Optional[str] = None):
        """Returns ``(outputs, model_version)`` — the version of the
        snapshot that actually computed them (stamped in the dispatch,
        so a concurrent hot reload cannot mislabel the response).
        ``trace=True`` forces a stage timeline onto this request even
        when batcher-level tracing is off; read it from
        :meth:`predict_request`. ``model`` routes through the registry
        router (required when the server has no single-model engine);
        ``tenant`` is the quota identity."""
        out, version, _ = self.predict_request(x, mask, timeout_s, trace,
                                               model=model, tenant=tenant)
        return out, version

    def predict_request(self, x: np.ndarray, mask=None,
                        timeout_s: Optional[float] = None,
                        trace: Optional[bool] = None,
                        model: Optional[str] = None,
                        tenant: Optional[str] = None):
        """Like :meth:`predict` but also returns the completed
        :class:`~serving.batcher.InferenceRequest` (its ``trace`` holds
        the stage timeline when tracing was on)."""
        timeout = self.default_timeout_s if timeout_s is None else timeout_s
        self._check_draining()
        if model is not None or self.batcher is None:
            if self.router is None:
                raise ValueError(
                    f"request names model {model!r} but the server has no "
                    "registry router (start with router=/--registry-dir)")
            if model is None:
                raise ValueError(
                    "registry-routed server: the request must name its "
                    'model (POST /models/<name>/predict or a "model" '
                    "payload key)")
            req = self.router.submit(model, x, mask, timeout=timeout,
                                     tenant=tenant or "default",
                                     trace=trace)
            out = req.result(timeout=timeout)
            return out, req.model_version, req
        req = self.batcher.submit(x, mask, timeout=timeout, trace=trace)
        out = req.result(timeout=timeout)
        version = req.model_version
        return out, (self.engine.model_version if version is None
                     else version), req


def _make_handler(server: InferenceServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # TCP_NODELAY: headers and body flush as separate segments, and
        # with Nagle on, the body segment stalls behind the peer's
        # delayed ACK — a flat ~40ms on every response on some kernels
        disable_nagle_algorithm = True

        # quiet by default: per-request stderr lines are noise at load
        def log_message(self, fmt, *args):  # noqa: N802
            pass

        # -- helpers --------------------------------------------------------
        def _send(self, code: int, body: bytes,
                  ctype: str = "application/json",
                  headers: Optional[dict] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, obj: dict,
                       headers: Optional[dict] = None) -> None:
            self._send(code, json.dumps(obj).encode(), headers=headers)

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length", 0) or 0)
            return self.rfile.read(n) if n else b""

        def _tenant(self, payload: Optional[dict] = None) -> str:
            t = self.headers.get("X-Tenant")
            if not t and payload:
                t = payload.get("tenant")
            return str(t) if t else "default"

        def _retry_after(self, e: BaseException) -> dict:
            """503s carry a Retry-After derived from the rejecting
            surface's queue depth × recent per-dispatch time, so
            clients back off instead of hammering."""
            import math as _math

            hint = getattr(e, "retry_after_s", None)
            if hint is None:
                hint = 1.0
            return {"Retry-After": str(max(int(_math.ceil(hint)), 1))}

        def _error(self, e: BaseException) -> None:
            from deeplearning4j_tpu.serving.registry import (
                CanaryRolledBackError,
                UnknownModelError,
            )

            name = type(e).__name__
            headers = None
            if isinstance(e, ServerOverloadedError):
                code = 503
                headers = self._retry_after(e)
            elif isinstance(e, RequestDeadlineExceeded):
                code = 504
            elif isinstance(e, CanaryRolledBackError):
                # the canary version rolled back under this request —
                # retryable, the active version is serving
                code = 503
                headers = self._retry_after(e)
            elif isinstance(e, ServerShutdownError):
                code = 503
                headers = self._retry_after(e)
            elif isinstance(e, UnknownModelError):
                code = 404
            elif isinstance(e, (ValueError, KeyError, TypeError)):
                code = 400
            else:
                code = 500
            body = {"error": name, "message": str(e)}
            tenant = getattr(e, "tenant", None)
            if tenant is not None:
                body["tenant"] = tenant
            self._send_json(code, body, headers=headers)

        # -- routes ---------------------------------------------------------
        def do_GET(self):  # noqa: N802
            from urllib.parse import urlparse

            from deeplearning4j_tpu.obs.exporter import (
                PROMETHEUS_CTYPE,
                wants_prometheus,
            )

            try:
                url = urlparse(self.path)
                if url.path.startswith("/models/"):
                    self._get_model_route(url)
                    return
                if url.path == "/healthz":
                    if server.engine is not None:
                        info = server.engine.describe()
                        info["snapshot_version"] = info.get("version")
                    else:
                        info = server.router.describe()
                    info["uptime_s"] = round(
                        time.time() - server.metrics.started_at, 3)
                    info["draining"] = server._draining
                    if server.generation is not None:
                        info["generation"] = server.generation.describe()
                    server.alerts.maybe_tick()
                    info["verdict"] = server.alerts.verdict().to_dict()
                    self._send_json(200, {"status": "ok", **info})
                elif url.path == "/alerts":
                    from deeplearning4j_tpu.obs.exporter import (
                        alerts_response,
                    )

                    code, body, ctype = alerts_response(
                        server.alerts, self.headers.get("Accept", ""),
                        url.query)
                    self._send(code, body, ctype)
                elif url.path == "/metrics":
                    depth = (server.batcher.queue_depth()
                             if server.batcher is not None else 0)
                    if server.router is not None:
                        depth += server.router.queue_depth()
                    if wants_prometheus(self.headers.get("Accept", ""),
                                        url.query):
                        self._send(200, server.metrics.prometheus_text(
                            queue_depth=depth).encode(), PROMETHEUS_CTYPE)
                    else:
                        body = server.metrics.snapshot(queue_depth=depth)
                        if server.generation is not None:
                            body["generation"] = \
                                server.generation.metrics.snapshot()
                        self._send_json(200, body)
                elif url.path == "/trace":
                    from urllib.parse import parse_qs

                    last = parse_qs(url.query).get("last", [None])[0]
                    body = server.traces.snapshot(
                        last=None if last is None else int(last))
                    body["pad_waste"] = {
                        str(k): v
                        for k, v in sorted(
                            server.metrics.pad_waste().items())}
                    self._send_json(200, body)
                elif url.path == "/debug/flight":
                    from deeplearning4j_tpu.obs.exporter import (
                        debug_flight_response,
                    )

                    self._send_json(*debug_flight_response(url.query))
                elif url.path == "/debug/profile":
                    from deeplearning4j_tpu.obs.exporter import (
                        debug_profile_response,
                    )

                    self._send_json(*debug_profile_response(url.query))
                else:
                    self._send_json(404, {"error": "NotFound",
                                          "message": self.path})
            except BaseException as e:  # never kill the connection thread
                self._error(e)

        def _model_route(self, path: str):
            """``/models/<name>/<action>`` → (name, action); None when
            the path does not parse (404)."""
            parts = path.split("/")
            if len(parts) != 4 or parts[1] != "models" or not parts[2]:
                return None
            return parts[2], parts[3]

        def _get_model_route(self, url) -> None:
            route = self._model_route(url.path)
            if route is None or server.router is None:
                self._send_json(404, {"error": "NotFound",
                                      "message": self.path})
                return
            name, action = route
            if action == "healthz":
                info = server.router.healthz(name)
                info["uptime_s"] = round(
                    time.time() - server.metrics.started_at, 3)
                server.alerts.maybe_tick()
                info["verdict"] = server.alerts.verdict().to_dict()
                code = 200 if info.get("active_version") is not None else 503
                self._send_json(code, {"status": "ok" if code == 200
                                       else "no_active_version", **info})
            else:
                self._send_json(404, {"error": "NotFound",
                                      "message": self.path})

        def do_POST(self):  # noqa: N802
            try:
                route = self._model_route(self.path)
                if route is not None:
                    name, action = route
                    if server.router is None:
                        self._send_json(409, {
                            "error": "NoRegistryRouter",
                            "message": "server started without a registry "
                                       "router (cli serve --registry-dir)"})
                    elif action == "predict":
                        self._predict_json(model=name)
                    elif action == "predict_npy":
                        self._predict_npy(model=name)
                    elif action == "generate":
                        self._generate(model=name)
                    else:
                        self._send_json(404, {"error": "NotFound",
                                              "message": self.path})
                elif self.path == "/predict":
                    self._predict_json()
                elif self.path == "/predict_npy":
                    self._predict_npy()
                elif self.path == "/generate":
                    self._generate()
                elif self.path == "/reload":
                    self._reload()
                elif self.path == "/drain":
                    self._send_json(200, server.drain())
                else:
                    self._send_json(404, {"error": "NotFound",
                                          "message": self.path})
            except BaseException as e:  # noqa: BLE001 — mapped to the typed HTTP error response
                self._error(e)

        def _predict_json(self, model: Optional[str] = None) -> None:
            try:
                payload = json.loads(self._body() or b"{}")
                x = np.asarray(payload["inputs"], np.float32)
            except (ValueError, KeyError, TypeError) as e:
                raise ValueError(f"bad /predict payload: {e}") from e
            if x.ndim == 1:
                x = x[None, :]  # single example convenience
            mask = payload.get("mask")
            if mask is not None:
                mask = np.asarray(mask, np.float32)
            timeout_ms = payload.get("timeout_ms")
            want_trace = bool(payload.get("trace", False))
            model = model or payload.get("model")
            out, version, req = server.predict_request(
                x, mask,
                timeout_s=None if timeout_ms is None
                else float(timeout_ms) / 1e3,
                # None keeps the batcher default; True forces a
                # timeline even when server-level tracing is off
                trace=True if want_trace else None,
                model=model, tenant=self._tenant(payload))
            body = {"outputs": np.asarray(out).tolist(),
                    "model_version": version}
            if model is not None:
                body["model"] = model
            if want_trace and req.trace is not None:
                body["trace"] = req.trace.timeline()
            self._send_json(200, body)

        def _generate(self, model: Optional[str] = None) -> None:
            """Continuous-batching generation. Submit errors (overload,
            window overflow, shutdown) raise BEFORE any header is sent
            and map to their typed transport codes; once a stream has
            started, a mid-decode failure becomes a terminal
            ``{"error": ...}`` chunk (the status line is already on the
            wire)."""
            # drain mode refuses NEW streams before any header is on
            # the wire; streams already decoding keep their connection
            server._check_draining()
            gen = server.generation
            submit = None if gen is None else gen.submit
            if model is not None:
                try:
                    # the active engine (timeout defaults + 409 checks);
                    # submission routes through the router so canary
                    # versions get their generation-traffic slice and
                    # every completion feeds the per-version gate
                    gen = server.router.generation_for(model)
                    submit = (lambda *a, **kw:
                              server.router.generation_submit(model, *a,
                                                              **kw))
                except (TypeError, ValueError) as e:
                    # no incremental-decode path / gen_slots=0: the
                    # model cannot generate — a route conflict, not a
                    # malformed request
                    self._send_json(409, {"error": "NoGenerationEngine",
                                          "message": str(e)})
                    return
            if gen is None:
                self._send_json(409, {
                    "error": "NoGenerationEngine",
                    "message": "server started without a generation "
                               "engine (cli serve --gen-slots N)"})
                return
            try:
                payload = json.loads(self._body() or b"{}")
                prompt = np.asarray(payload["prompt"], np.int32).reshape(-1)
            except (ValueError, KeyError, TypeError) as e:
                raise ValueError(f"bad /generate payload: {e}") from e
            timeout_ms = payload.get("timeout_ms")
            timeout_s = (None if timeout_ms is None
                         else float(timeout_ms) / 1e3)
            want_trace = payload.get("trace")
            req = submit(
                prompt,
                max_new=int(payload.get("max_new", 20)),
                temperature=float(payload.get("temperature", 0.0)),
                top_k=int(payload.get("top_k", 0)),
                top_p=float(payload.get("top_p", 0.0)),
                seed=int(payload.get("seed", 0)),
                timeout=timeout_s,
                trace=None if want_trace is None else bool(want_trace))
            wait_s = (gen.default_timeout_s
                      if timeout_s is None else timeout_s)
            if not payload.get("stream", True):
                out = req.result(timeout=wait_s)
                body = {"tokens": [int(t) for t in req.tokens],
                        "sequence": out.tolist(),
                        "prompt_len": int(prompt.size)}
                if want_trace and req.trace is not None:
                    body["trace"] = req.trace.timeline()
                self._send_json(200, body)
                return
            # chunked newline-delimited JSON: tokens land on the wire
            # as the decode loop emits them
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(obj: dict) -> None:
                data = (json.dumps(obj) + "\n").encode()
                self.wfile.write(f"{len(data):x}\r\n".encode()
                                 + data + b"\r\n")
                self.wfile.flush()

            try:
                for tok in req.stream(timeout=wait_s):
                    chunk({"token": int(tok)})
                summary = {"done": True,
                           "tokens": [int(t) for t in req.tokens],
                           "prompt_len": int(prompt.size)}
                if want_trace and req.trace is not None:
                    summary["trace"] = req.trace.timeline()
                chunk(summary)
            except BaseException as e:  # noqa: BLE001 — terminal chunk; see below
                # the status line is on the wire; a decode failure
                # becomes a terminal chunk. If writing THAT fails too
                # (client went away mid-stream), swallow it — letting
                # it propagate would re-enter do_POST's _error(),
                # which injects a second status line into the chunked
                # body on a half-writable socket.
                try:
                    chunk({"error": type(e).__name__, "message": str(e)})
                except OSError:
                    return
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

        def _predict_npy(self, model: Optional[str] = None) -> None:
            body = self._body()
            try:
                x = np.load(io.BytesIO(body), allow_pickle=False)
            except (ValueError, EOFError, OSError) as e:
                # empty/truncated bodies raise EOFError/OSError from
                # np.load — all are the client's malformed input (400)
                raise ValueError(f"bad /predict_npy body: {e}") from e
            out, _ = server.predict(np.asarray(x, np.float32), model=model,
                                    tenant=self._tenant())
            buf = io.BytesIO()
            np.save(buf, np.asarray(out), allow_pickle=False)
            self._send(200, buf.getvalue(), ctype="application/x-npy")

        def _reload(self) -> None:
            if server.engine is None:
                self._send_json(409, {
                    "error": "NoSingleModelEngine",
                    "message": "registry-routed server: versions deploy "
                               "through the registry (publish → canary → "
                               "promote), not /reload"})
                return
            body = self._body()
            payload = json.loads(body) if body else {}
            try:
                result = server.engine.reload(
                    source=payload.get("path"),
                    force=bool(payload.get("force", False)))
            except FileNotFoundError as e:
                self._send_json(409, {"error": "FileNotFoundError",
                                      "message": str(e)})
                return
            if result.get("reloaded") and server.generation is not None:
                # cached prefix KV was computed by the OLD params — a
                # hit after the swap would resurrect them bit-exactly
                result["prefix_entries_cleared"] = \
                    server.generation.clear_prefix_cache(reason="reload")
            self._send_json(200, result)

    return Handler
