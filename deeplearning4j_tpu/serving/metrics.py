"""Serving metrics, rebased onto the unified observability registry
(obs/metrics.MetricsRegistry) — counters, per-bucket hits, latency
quantiles from a fixed-size ring buffer.

The public surface is unchanged from the original serving-only
implementation (``record_*`` methods, attribute-style counter reads,
``snapshot()`` with the same JSON keys for the ``/metrics`` endpoint).
What changed underneath: every value now lives in a
:class:`MetricsRegistry`, so (1) ``prometheus_text()`` exposes the whole
family in Prometheus text format for scrapers, and (2) an engine handed
the process-wide default registry (``cli.py serve`` does this) shares
ONE metrics surface with training — the 1605.08695 train-and-serve
pairing applied to monitoring. By default each instance owns a private
registry, so independent engines (tests run dozens) never double-count.

The ring buffer bounds memory under sustained traffic (millions of
requests must not grow a list); quantiles are computed over the last
``ring_size`` completed requests, which is the window that matters for
a live /metrics endpoint. Everything here is plain Python under
fine-grained locks — the costs are nanoseconds against a device
dispatch.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from deeplearning4j_tpu.obs.metrics import Histogram, MetricsRegistry


class ServingMetrics:
    def __init__(self, ring_size: int = 2048,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        reg = self.registry
        self._requests = reg.counter(
            "serving_requests_total", "requests accepted into the queue")
        self._examples = reg.counter(
            "serving_examples_total", "rows across accepted requests")
        self._rejects = reg.counter(
            "serving_rejects_total", "ServerOverloadedError rejections")
        self._deadline = reg.counter(
            "serving_deadline_exceeded_total", "requests past their deadline")
        self._errors = reg.counter(
            "serving_errors_total", "dispatch failures propagated to callers")
        self._dispatches = reg.counter(
            "serving_dispatches_total", "device batches launched")
        self._reloads = reg.counter(
            "serving_reloads_total", "model hot reloads")
        self._latency = reg.histogram(
            "serving_latency_seconds", "request latency (ring-buffer window)",
            ring_size=ring_size)
        self.started_at = time.time()
        reg.gauge("serving_uptime_seconds", "seconds since metrics start",
                  fn=lambda: time.time() - self.started_at)
        # real-rows-per-dispatch ring: the observed mix an adaptive
        # bucket tuner learns from (bounded, like the latency ring)
        self._rows_window: deque = deque(maxlen=ring_size)
        self._rows_lock = threading.Lock()
        reg.gauge(
            "serving_latency_p99_ms",
            "p99 request latency over the ring window, milliseconds "
            "(0 before any request) — the latency-SLO alert input",
            fn=lambda: round((self.latency_quantile(0.99) or 0.0) * 1e3, 3))

    # -- recording ----------------------------------------------------------
    def record_request(self, rows: int) -> None:
        self._requests.inc()
        self._examples.inc(int(rows))

    def record_reject(self) -> None:
        self._rejects.inc()

    def record_deadline(self) -> None:
        self._deadline.inc()

    def record_error(self) -> None:
        self._errors.inc()

    def record_dispatch(self, bucket: int,
                        real_rows: Optional[int] = None) -> None:
        """One device batch launched at ``bucket`` padded rows;
        ``real_rows`` (when the caller knows it — the engine does)
        splits the bucket's rows into real vs padding so the per-bucket
        pad-waste ratio is a first-class metric instead of a number the
        dispatch path computed and threw away."""
        self._dispatches.inc()
        lbl = {"bucket": str(int(bucket))}
        self.registry.counter(
            "serving_bucket_hits_total", "dispatches per bucket size",
            labels=lbl).inc()
        if real_rows is not None:
            real = min(max(int(real_rows), 0), int(bucket))
            with self._rows_lock:
                self._rows_window.append(real)
            self.registry.counter(
                "serving_real_samples_total",
                "real (request) rows dispatched, per bucket",
                labels=lbl).inc(real)
            self.registry.counter(
                "serving_padded_samples_total",
                "padding rows dispatched (bucket quantization waste), "
                "per bucket", labels=lbl).inc(int(bucket) - real)

    def record_reload(self) -> None:
        self._reloads.inc()

    def record_latency(self, seconds: float) -> None:
        self._latency.observe(float(seconds))

    # -- attribute-style reads (original public surface) ---------------------
    @property
    def requests(self) -> int:
        return int(self._requests.value())

    @property
    def examples(self) -> int:
        return int(self._examples.value())

    @property
    def rejects(self) -> int:
        return int(self._rejects.value())

    @property
    def deadline_exceeded(self) -> int:
        return int(self._deadline.value())

    @property
    def errors(self) -> int:
        return int(self._errors.value())

    @property
    def dispatches(self) -> int:
        return int(self._dispatches.value())

    @property
    def reloads(self) -> int:
        return int(self._reloads.value())

    @property
    def bucket_hits(self) -> Dict[int, int]:
        fam = self.registry.family_values("serving_bucket_hits_total")
        return {int(label.split("=", 1)[1]): int(v)
                for label, v in fam.items()}

    def pad_waste(self) -> Dict[int, dict]:
        """bucket → {real, padded, waste_ratio}: cumulative rows split
        into request rows vs bucket-quantization padding. waste_ratio is
        padding over total dispatched rows — the fraction of device work
        burned on padding at that bucket (the signal that says WHICH
        bucket list to retune)."""
        real = self.registry.family_values("serving_real_samples_total")
        padded = self.registry.family_values("serving_padded_samples_total")
        out: Dict[int, dict] = {}
        for label in set(real) | set(padded):
            bucket = int(label.split("=", 1)[1])
            r = int(real.get(label, 0))
            p = int(padded.get(label, 0))
            out[bucket] = {
                "real": r, "padded": p,
                "waste_ratio": round(p / (r + p), 4) if (r + p) else 0.0,
            }
        return out

    def dispatch_rows_window(self) -> List[int]:
        """Real rows per dispatch over the last ``ring_size`` device
        batches — the observed mix :func:`~.buckets.propose_buckets`
        turns into a learned bucket list."""
        with self._rows_lock:
            return list(self._rows_window)

    # -- reading ------------------------------------------------------------
    def latency_quantile(self, q: float) -> Optional[float]:
        """q in [0, 1] over the ring window; None before any request."""
        return self._latency.quantile(q)

    def snapshot(self, queue_depth: Optional[int] = None) -> dict:
        """One JSON-ready dict for the /metrics endpoint (keys unchanged
        from the pre-registry implementation)."""
        window = self._latency.window()
        n = len(window)
        out = {
            "requests": self.requests,
            "examples": self.examples,
            "rejects": self.rejects,
            "deadline_exceeded": self.deadline_exceeded,
            "errors": self.errors,
            "dispatches": self.dispatches,
            "reloads": self.reloads,
            "bucket_hits": {str(k): v
                            for k, v in sorted(self.bucket_hits.items())},
            "pad_waste": {str(k): v
                          for k, v in sorted(self.pad_waste().items())},
            "uptime_s": round(time.time() - self.started_at, 3),
            "latency_window": n,
        }
        for name, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
            out[f"latency_{name}_ms"] = (
                None if n == 0
                else round(window[min(int(q * n), n - 1)] * 1e3, 3))
        if queue_depth is not None:
            out["queue_depth"] = int(queue_depth)
            self.registry.gauge("serving_queue_depth",
                                "pending requests in the batcher queue"
                                ).set(int(queue_depth))
        return out

    def prometheus_text(self, queue_depth: Optional[int] = None) -> str:
        """Prometheus text exposition of the backing registry."""
        if queue_depth is not None:
            self.registry.gauge("serving_queue_depth",
                                "pending requests in the batcher queue"
                                ).set(int(queue_depth))
        return self.registry.prometheus_text()


class GenerationMetrics:
    """Metrics surface for the continuous-batching generation engine
    (serving/generate.py) — same registry discipline as
    :class:`ServingMetrics`: every value lives in a
    :class:`MetricsRegistry` (private by default; hand it the
    process-wide default registry to share one Prometheus surface with
    training and /predict serving).

    The headline gauges the ISSUE names: ``generation_tokens_per_sec``
    (scrape-to-scrape rate of the token counter),
    ``generation_active_slots`` / ``generation_slots`` (occupancy), and
    the prefill/decode wall-time split (two monotonic seconds counters —
    the ratio is the split)."""

    def __init__(self, ring_size: int = 2048,
                 registry: Optional[MetricsRegistry] = None):
        from deeplearning4j_tpu.obs.cost import value_rate_fn

        self.registry = registry if registry is not None else MetricsRegistry()
        reg = self.registry
        self._requests = reg.counter(
            "generation_requests_total",
            "generation requests accepted into the queue")
        self._rejects = reg.counter(
            "generation_rejects_total",
            "generation requests rejected (queue full / invalid window)")
        self._deadline = reg.counter(
            "generation_deadline_total",
            "generation requests past their deadline (queued or mid-decode)")
        self._errors = reg.counter(
            "generation_errors_total",
            "generation failures propagated to callers")
        self._tokens = reg.counter(
            "generation_tokens_total", "tokens generated across requests")
        self._prefills = reg.counter(
            "generation_prefills_total", "prompt prefills (slot claims)")
        self._decode_steps = reg.counter(
            "generation_decode_steps_total",
            "batched decode dispatches (one per token for ALL slots)")
        self._prefill_s = reg.counter(
            "generation_prefill_seconds_total",
            "wall seconds spent in prompt prefill")
        self._decode_s = reg.counter(
            "generation_decode_seconds_total",
            "wall seconds spent in batched decode steps")
        self._latency = reg.histogram(
            "generation_request_seconds",
            "end-to-end request latency (ring-buffer window)",
            ring_size=ring_size)
        self._slots = reg.gauge(
            "generation_slots", "decode slots in the engine slab")
        self._active = reg.gauge(
            "generation_active_slots", "slots currently decoding")
        reg.gauge("generation_tokens_per_sec",
                  "generated tokens/sec (scrape-to-scrape rate)",
                  fn=value_rate_fn(lambda: self._tokens.value()))
        # speculative decoding: proposed vs accepted draft tokens (the
        # acceptance ratio is the speedup knob's health signal)
        self._draft_proposed = reg.counter(
            "generation_draft_proposed_total",
            "draft tokens proposed to verify dispatches")
        self._draft_accepted = reg.counter(
            "generation_draft_accepted_total",
            "draft tokens accepted by verify dispatches")
        # shared-prefix KV cache: lookup/hit/evict counters + resident
        # bytes. The hit-rate gauge is created LAZILY once lookups cross
        # a floor (see record_prefix_lookup) so the `prefix_hit_rate_low`
        # alert stays inert on engines without prefix traffic — the
        # evaluator's no-data-is-no-verdict contract does the rest.
        self._prefix_lookups = reg.counter(
            "generation_prefix_lookups_total",
            "prefix-cache lookups (one per admitted request when enabled)")
        self._prefix_hits = reg.counter(
            "generation_prefix_hits_total",
            "prefix-cache hits (prefill replaced by a KV block copy)")
        self._prefix_evicts = reg.counter(
            "generation_prefix_evictions_total",
            "prefix-cache entries evicted (lru / poisoned / cleared)")
        self._prefix_bytes = reg.gauge(
            "generation_prefix_cache_bytes",
            "resident bytes held by the shared-prefix KV cache")
        self._flops_avoided = reg.counter(
            "generation_prefill_flops_avoided_total",
            "analytic prefill FLOPs avoided by prefix-cache hits")
        self._hit_rate_gauge = None
        #: lookups before the hit-rate gauge materializes (and the
        #: prefix_hit_rate_low rule can fire)
        self.prefix_gauge_floor = 8
        self.started_at = time.time()

    # -- recording ----------------------------------------------------------
    def set_slots(self, n: int) -> None:
        self._slots.set(int(n))

    def set_active_slots(self, n: int) -> None:
        self._active.set(int(n))

    def record_request(self) -> None:
        self._requests.inc()

    def record_reject(self) -> None:
        self._rejects.inc()

    def record_deadline(self) -> None:
        self._deadline.inc()

    def record_error(self) -> None:
        self._errors.inc()

    def record_prefill(self, seconds: float) -> None:
        self._prefills.inc()
        self._prefill_s.inc(float(seconds))

    def record_decode_step(self, seconds: float, tokens: int) -> None:
        self._decode_steps.inc()
        self._decode_s.inc(float(seconds))
        if tokens:
            self._tokens.inc(int(tokens))

    def record_first_token(self) -> None:
        self._tokens.inc()

    def record_finish(self, latency_seconds: float) -> None:
        self._latency.observe(float(latency_seconds))

    def record_draft(self, proposed: int, accepted: int) -> None:
        if proposed:
            self._draft_proposed.inc(int(proposed))
        if accepted:
            self._draft_accepted.inc(int(accepted))

    def _update_hit_rate(self) -> None:
        lookups = int(self._prefix_lookups.value())
        if lookups < self.prefix_gauge_floor:
            return
        if self._hit_rate_gauge is None:
            self._hit_rate_gauge = self.registry.gauge(
                "generation_prefix_hit_rate",
                "prefix-cache hits / lookups (created after the lookup "
                "floor so the low-hit-rate alert never fires on idle "
                "or prefix-less engines)")
        self._hit_rate_gauge.set(
            int(self._prefix_hits.value()) / max(lookups, 1))

    def record_prefix_lookup(self) -> None:
        self._prefix_lookups.inc()
        self._update_hit_rate()

    def record_prefix_hit(self, flops_avoided: int = 0) -> None:
        self._prefix_hits.inc()
        if flops_avoided:
            self._flops_avoided.inc(int(flops_avoided))
        self._update_hit_rate()

    def record_prefix_evict(self, n: int = 1) -> None:
        self._prefix_evicts.inc(int(n))

    def set_prefix_bytes(self, n: int) -> None:
        self._prefix_bytes.set(int(n))

    # -- reading ------------------------------------------------------------
    @property
    def tokens(self) -> int:
        return int(self._tokens.value())

    @property
    def requests(self) -> int:
        return int(self._requests.value())

    @property
    def rejects(self) -> int:
        return int(self._rejects.value())

    @property
    def deadline_exceeded(self) -> int:
        return int(self._deadline.value())

    def snapshot(self) -> dict:
        """JSON-ready dict merged into the server's /metrics body."""
        window = self._latency.window()
        n = len(window)
        prefill_s = self._prefill_s.value()
        decode_s = self._decode_s.value()
        out = {
            "requests": self.requests,
            "rejects": self.rejects,
            "deadline_exceeded": self.deadline_exceeded,
            "errors": int(self._errors.value()),
            "tokens": self.tokens,
            "prefills": int(self._prefills.value()),
            "decode_steps": int(self._decode_steps.value()),
            "prefill_seconds": round(prefill_s, 4),
            "decode_seconds": round(decode_s, 4),
            "prefill_fraction": (
                round(prefill_s / (prefill_s + decode_s), 4)
                if (prefill_s + decode_s) > 0 else None),
            "slots": int(self._slots.value()),
            "active_slots": int(self._active.value()),
            "draft_proposed": int(self._draft_proposed.value()),
            "draft_accepted": int(self._draft_accepted.value()),
            "draft_acceptance": (
                round(self._draft_accepted.value()
                      / self._draft_proposed.value(), 4)
                if self._draft_proposed.value() > 0 else None),
            "prefix_lookups": int(self._prefix_lookups.value()),
            "prefix_hits": int(self._prefix_hits.value()),
            "prefix_evictions": int(self._prefix_evicts.value()),
            "prefix_cache_bytes": int(self._prefix_bytes.value()),
            "prefill_flops_avoided": int(self._flops_avoided.value()),
            "latency_window": n,
        }
        for name, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
            out[f"latency_{name}_ms"] = (
                None if n == 0
                else round(window[min(int(q * n), n - 1)] * 1e3, 3))
        return out


# re-exported for API continuity: callers that sized the ring via the
# original module keep working
__all__ = ["ServingMetrics", "GenerationMetrics", "Histogram"]
