"""Thread-safe serving metrics: counters, per-bucket hits, latency
quantiles from a fixed-size ring buffer.

The ring buffer bounds memory under sustained traffic (millions of
requests must not grow a list); quantiles are computed over the last
``ring_size`` completed requests, which is the window that matters for
a live /metrics endpoint. Everything here is plain Python under one
lock — the costs are nanoseconds against a device dispatch.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class ServingMetrics:
    def __init__(self, ring_size: int = 2048):
        self._lock = threading.Lock()
        self._ring_size = int(ring_size)
        self._lat = [0.0] * self._ring_size  # seconds, ring buffer
        self._lat_n = 0  # total ever recorded (write head = n % size)
        self.requests = 0          # requests accepted into the queue
        self.examples = 0          # rows across accepted requests
        self.rejects = 0           # ServerOverloadedError rejections
        self.deadline_exceeded = 0
        self.errors = 0            # dispatch failures propagated to callers
        self.dispatches = 0        # device batches launched
        self.reloads = 0
        self.bucket_hits: Dict[int, int] = {}  # dispatched bucket size → count
        self.started_at = time.time()

    # -- recording ----------------------------------------------------------
    def record_request(self, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.examples += int(rows)

    def record_reject(self) -> None:
        with self._lock:
            self.rejects += 1

    def record_deadline(self) -> None:
        with self._lock:
            self.deadline_exceeded += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def record_dispatch(self, bucket: int) -> None:
        with self._lock:
            self.dispatches += 1
            self.bucket_hits[int(bucket)] = (
                self.bucket_hits.get(int(bucket), 0) + 1)

    def record_reload(self) -> None:
        with self._lock:
            self.reloads += 1

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            self._lat[self._lat_n % self._ring_size] = float(seconds)
            self._lat_n += 1

    # -- reading ------------------------------------------------------------
    def latency_quantile(self, q: float) -> Optional[float]:
        """q in [0, 1] over the ring window; None before any request."""
        with self._lock:
            n = min(self._lat_n, self._ring_size)
            if n == 0:
                return None
            window = sorted(self._lat[:n])
        idx = min(int(q * n), n - 1)
        return window[idx]

    def snapshot(self, queue_depth: Optional[int] = None) -> dict:
        """One JSON-ready dict for the /metrics endpoint."""
        with self._lock:
            n = min(self._lat_n, self._ring_size)
            window = sorted(self._lat[:n])
            out = {
                "requests": self.requests,
                "examples": self.examples,
                "rejects": self.rejects,
                "deadline_exceeded": self.deadline_exceeded,
                "errors": self.errors,
                "dispatches": self.dispatches,
                "reloads": self.reloads,
                "bucket_hits": {str(k): v
                                for k, v in sorted(self.bucket_hits.items())},
                "uptime_s": round(time.time() - self.started_at, 3),
                "latency_window": n,
            }
        for name, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
            out[f"latency_{name}_ms"] = (
                None if n == 0
                else round(window[min(int(q * n), n - 1)] * 1e3, 3))
        if queue_depth is not None:
            out["queue_depth"] = int(queue_depth)
        return out
