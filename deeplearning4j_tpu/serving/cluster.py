"""Multi-replica serving coordination over the registry WAL.

Two servers sharing a registry directory already agree on *deployment
state* (the fsync'd ``journal.jsonl`` is the source of truth and
:meth:`~.registry.ModelRegistry.refresh` folds in peers' appends), but
until this module they made *independent control decisions*: each
replica ran its own canary gate over its own slice of traffic, so a
regression one replica observed did not protect users routed to the
other, two replicas could promote/rollback the same window in
opposite directions, and per-tenant quotas multiplied by the replica
count. This is the coordination layer that turns N processes into one
tier — the 1605.08695 framing of fault handling at the system
boundary: every piece of coordination state must survive any single
process dying at any instant, so all of it lives in one append-only
fsync'd journal (``cluster.jsonl``, next to the registry's), written
through :mod:`~deeplearning4j_tpu.chaos.fslayer` so torn/ENOSPC
semantics stay typed and drill-able.

Journal record kinds (whole JSON lines, O_APPEND — the append order IS
the serialization point for ties):

- ``heartbeat``    — replica id, monotonically increasing per-replica
                     seq, wall ``ts`` from the (injectable) clock, and
                     the replica's per-tenant in-flight counts (the
                     quota borrow protocol's input).
- ``lease_claim``  — (model, replica, epoch): a bid for the model's
                     canary-controller lease. The holder is the claim
                     with the HIGHEST epoch; among claims at the same
                     epoch the FIRST APPENDED wins (split-brain
                     concurrent claims resolve deterministically from
                     the journal, with no coordinator). A valid claim
                     must use ``current epoch + 1`` — epochs are the
                     fencing tokens.
- ``lease_release``— the holder stepping down cleanly (drain); the
                     epoch is NOT reset, so the next claim still
                     fences out the ex-holder.
- ``gate``         — one replica's per-(model, version) serving
                     counters (the ``registry_version_*`` families):
                     requests/errors/latency sums for /predict and
                     /generate plus the running score. Every replica
                     folds peers' latest gate records before its gate
                     tick, so the controller's trip/promote decision
                     sees CLUSTER-wide traffic — a regression observed
                     by any replica trips rollback everywhere.

**Lease / epoch state machine.** Exactly one replica owns each canary
window: the lease holder is the only replica allowed to journal
trip/promote decisions into the model registry. Ownership is claimed
with :meth:`ClusterCoordinator.ensure_lease` (claim epoch+1 when the
lease is free or the holder's heartbeat is stale past
``lease_ttl_s``), and every decision is guarded by
:meth:`~ClusterCoordinator.fence`: re-read the journal, and if a
higher-epoch claim exists the decision raises a typed
:class:`StaleEpochError` — a paused-and-resumed ex-holder (GC pause,
SIGSTOP, clock skew) can never silently merge a stale decision; the
refusal is recorded as a ``stale_epoch_refused`` flight event.

**Quota borrow protocol.** With a cluster-wide tenant quota G, each
replica may admit tenant t while its own in-flight count stays under
``max(ceil(G / n_alive), G - peers' reported in-flight for t)`` —
idle peers' unused share is borrowed automatically, and under
saturation every replica converges to the fair-share floor. Budgets
rebalance on every heartbeat fold; a replica-count change records a
``quota_rebalance`` flight event.

The coordinator never calls back into the router and takes only its
own witnessed lock, so it can safely be invoked under a managed
model's lock (the router does).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.obs.lockwitness import witnessed_rlock
from deeplearning4j_tpu.serving.registry import RegistryError

CLUSTER_JOURNAL_NAME = "cluster.jsonl"


class ClusterError(RegistryError):
    """Base of the typed cluster-coordination failures."""


class StaleEpochError(ClusterError):
    """A replica tried to commit a canary-controller decision (trip /
    promote / release) with a lease epoch that is no longer current —
    another replica stole the lease while this one was paused, skewed,
    or partitioned. The decision is REFUSED, never silently merged;
    the current holder's decision is the only one that lands."""


class _MergedStats:
    """Cluster-wide per-version serving counters: this replica's live
    :class:`~.registry._VersionStats` plus every peer's latest
    journaled gate record. Implements the stats protocol the canary
    gate rules (obs/slo.canary_gate_rules) read, so the controller's
    gate tick sees the whole tier's traffic."""

    __slots__ = ("requests", "errors", "latency_sum", "score",
                 "gen_requests", "gen_errors", "gen_latency_sum")

    def __init__(self, local, peers: List[dict]):
        self.requests = local.requests
        self.errors = local.errors
        self.latency_sum = local.latency_sum
        self.gen_requests = local.gen_requests
        self.gen_errors = local.gen_errors
        self.gen_latency_sum = local.gen_latency_sum
        # scores merge as a sample-weighted mean (each contribution
        # carries how many observations produced it)
        score_sum = 0.0
        score_n = 0
        local_n = getattr(local, "_n_scores", 0)
        if local.score is not None and local_n:
            score_sum += local.score * local_n
            score_n += local_n
        for p in peers:
            self.requests += int(p.get("requests", 0))
            self.errors += int(p.get("errors", 0))
            self.latency_sum += float(p.get("latency_sum", 0.0))
            self.gen_requests += int(p.get("gen_requests", 0))
            self.gen_errors += int(p.get("gen_errors", 0))
            self.gen_latency_sum += float(p.get("gen_latency_sum", 0.0))
            ps, pn = p.get("score"), int(p.get("n_scores", 0))
            if ps is not None and pn:
                score_sum += float(ps) * pn
                score_n += pn
        self.score = score_sum / score_n if score_n else None

    def mean_latency(self) -> Optional[float]:
        return self.latency_sum / self.requests if self.requests else None

    def mean_gen_latency(self) -> Optional[float]:
        return (self.gen_latency_sum / self.gen_requests
                if self.gen_requests else None)


class _RoleView:
    __slots__ = ("stats",)

    def __init__(self, stats: _MergedStats):
        self.stats = stats


class _GateView:
    """Duck-typed stand-in for a managed model that the canary gate
    rules read: ``.active`` / ``.canary`` expose CLUSTER-merged stats
    instead of this replica's local counters. Properties re-read the
    live managed model per access, so each evaluator tick sees the
    current engines and the latest folded peer snapshots."""

    def __init__(self, mm, cluster: "ClusterCoordinator"):
        self._mm = mm
        self._cluster = cluster

    @property
    def active(self) -> Optional[_RoleView]:
        ve = self._mm.active
        if ve is None:
            return None
        return _RoleView(self._cluster.merged_stats(self._mm.name, ve))

    @property
    def canary(self) -> Optional[_RoleView]:
        ve = self._mm.canary
        if ve is None:
            return None
        return _RoleView(self._cluster.merged_stats(self._mm.name, ve))


class ClusterCoordinator:
    """One replica's view of the cluster journal: heartbeats, the
    per-model canary-controller lease, folded peer gate snapshots, and
    tenant budget shares. All durable writes go through the injectable
    FS layer (surface ``cluster_journal``); all reads are incremental
    byte-offset folds with the journals' torn-trailing-line tolerance.

    ``clock`` is the wall clock used for heartbeat timestamps AND for
    judging peer staleness — injectable so chaos drills can skew one
    replica's clock and prove the epoch fencing holds anyway.
    """

    def __init__(self, directory: str, replica_id: str,
                 heartbeat_s: float = 1.0,
                 lease_ttl_s: Optional[float] = None,
                 global_tenant_quota: Optional[int] = None,
                 gate_interval_s: float = 0.25,
                 canary_refresh_s: float = 0.25,
                 clock: Optional[Callable[[], float]] = None,
                 metrics_registry=None):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.journal_path = os.path.join(self.directory,
                                         CLUSTER_JOURNAL_NAME)
        self.replica_id = str(replica_id)
        self.heartbeat_s = float(heartbeat_s)
        #: a holder whose newest heartbeat is older than this is
        #: presumed dead; its lease is stealable (epoch + 1)
        self.lease_ttl_s = (3.0 * self.heartbeat_s if lease_ttl_s is None
                            else float(lease_ttl_s))
        self.global_tenant_quota = (None if global_tenant_quota is None
                                    else max(int(global_tenant_quota), 1))
        #: min seconds between journaled gate snapshots per
        #: (model, version) — urgent writes (observed failures) bypass it
        self.gate_interval_s = float(gate_interval_s)
        #: the registry-refresh cadence the router tightens to while a
        #: canary window is open (cross-replica trip latency is bounded
        #: by it — the satellite fix riding on this PR)
        self.canary_refresh_s = float(canary_refresh_s)
        self._clock = clock if clock is not None else time.time
        self._lock = witnessed_rlock("cluster")
        self._offset = 0
        #: replica id -> newest heartbeat record
        self._replicas: Dict[str, dict] = {}
        #: model -> {"replica": id|None, "epoch": n, "ts": wall}
        self._leases: Dict[str, dict] = {}
        #: (model, version) -> replica id -> newest gate record
        self._gates: Dict[Tuple[str, int], Dict[str, dict]] = {}
        self._lost: set = set()
        self._hb_seq = 0
        self._announced = False
        self._last_gate: Dict[Tuple[str, int], float] = {}
        self._last_alive_count: Optional[int] = None
        self._metrics = metrics_registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- durable journal ------------------------------------------------------
    def _append(self, rec: dict) -> None:
        """Append one record through the FS layer (typed StorageError
        on disk faults, torn mode drill-able). The record is NOT folded
        optimistically: callers refresh() afterwards, so records fold
        in true journal order — the property same-epoch lease ties are
        resolved by."""
        from deeplearning4j_tpu.chaos import fslayer as _fs

        line = json.dumps(rec, sort_keys=True) + "\n"
        _fs.append_line(self.journal_path, line, surface="cluster_journal")

    def refresh(self) -> bool:
        """Fold in journal lines appended since the last fold (one stat
        when nothing changed), then re-judge peer liveness. A trailing
        fragment without its newline (a peer's crash mid-append) is
        left un-consumed — the next writer's torn-tail repair truncates
        it; a corrupt newline-terminated line with records after it is
        external corruption and refuses typed."""
        changed = False
        with self._lock:
            try:
                size = os.path.getsize(self.journal_path)
            except OSError:
                size = 0
            if size < self._offset:
                # the journal shrank under us: a torn tail we had NOT
                # consumed was repaired away, or the journal was reset —
                # refold from scratch (replay is cheap and is the code
                # path crash recovery already trusts)
                self._reset_state()
            if size > self._offset:
                with open(self.journal_path, "rb") as f:
                    f.seek(self._offset)
                    data = f.read(size - self._offset)
                consumed = 0
                for raw in data.split(b"\n")[:-1]:
                    consumed += len(raw) + 1
                    if not raw.strip():
                        continue
                    try:
                        rec = json.loads(raw)
                    except json.JSONDecodeError:
                        raise ClusterError(
                            f"{self.journal_path}: corrupt cluster journal "
                            f"line at byte {self._offset + consumed - len(raw) - 1} "
                            "— not crash truncation (the torn state has no "
                            "newline); refusing to fold")
                    self._fold(rec)
                    changed = True
                self._offset += consumed
            self._judge_liveness()
        return changed

    def _reset_state(self) -> None:
        self._offset = 0
        self._replicas.clear()
        self._leases.clear()
        self._gates.clear()

    def _fold(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "heartbeat":
            rid = str(rec.get("replica"))
            prev = self._replicas.get(rid)
            if prev is None or rec.get("seq", 0) >= prev.get("seq", 0):
                self._replicas[rid] = rec
        elif kind == "lease_claim":
            model = str(rec.get("model"))
            cur = self._leases.get(model)
            epoch = int(rec.get("epoch", 0))
            # highest epoch wins; SAME epoch: first appended wins (this
            # record is later in journal order, so it loses the tie)
            if cur is None or epoch > int(cur["epoch"]):
                self._leases[model] = {"replica": str(rec.get("replica")),
                                       "epoch": epoch,
                                       "ts": float(rec.get("ts", 0.0))}
        elif kind == "lease_release":
            model = str(rec.get("model"))
            cur = self._leases.get(model)
            if (cur is not None
                    and cur["replica"] == str(rec.get("replica"))
                    and int(rec.get("epoch", -1)) == int(cur["epoch"])):
                # the epoch survives the release: the next claim must
                # still use epoch+1, fencing out the released holder
                self._leases[model] = {"replica": None,
                                       "epoch": int(cur["epoch"]),
                                       "ts": float(rec.get("ts", 0.0))}
        elif kind == "gate":
            key = (str(rec.get("model")), int(rec.get("version", 0)))
            self._gates.setdefault(key, {})[str(rec.get("replica"))] = rec

    def _judge_liveness(self) -> None:
        """Peer heartbeat staleness scan (caller holds the lock)."""
        from deeplearning4j_tpu.obs import flight as _flight

        now = self._clock()
        for rid, hb in self._replicas.items():
            if rid == self.replica_id:
                continue
            age = now - float(hb.get("ts", 0.0))
            if age > self.lease_ttl_s and rid not in self._lost:
                self._lost.add(rid)
                _flight.record("replica_lost", replica=rid,
                               observer=self.replica_id,
                               heartbeat_age_s=round(age, 3))
            elif age <= self.lease_ttl_s and rid in self._lost:
                self._lost.discard(rid)
                _flight.record("replica_up", replica=rid,
                               observer=self.replica_id, rejoined=True)
        n_alive = len(self.alive_replicas())
        if (self.global_tenant_quota is not None
                and n_alive != self._last_alive_count):
            if self._last_alive_count is not None:
                _flight.record(
                    "quota_rebalance", replicas=n_alive,
                    observer=self.replica_id,
                    share=self._fair_share(n_alive),
                    global_quota=self.global_tenant_quota)
            self._last_alive_count = n_alive
        if self._metrics is not None:
            self._metrics.gauge(
                "cluster_replicas_alive",
                "replicas with a fresh heartbeat in the cluster journal",
                labels={"replica": self.replica_id}).set(float(n_alive))

    # -- membership ------------------------------------------------------------
    def heartbeat(self, inflight: Optional[Dict[str, int]] = None) -> None:
        """Append this replica's heartbeat (liveness + per-tenant
        in-flight counts for the quota borrow protocol) and fold peers'
        appends. Call it every ``heartbeat_s`` — or :meth:`start` a
        thread that does."""
        from deeplearning4j_tpu.obs import flight as _flight

        with self._lock:
            self._hb_seq += 1
            seq = self._hb_seq
        self._append({"kind": "heartbeat", "replica": self.replica_id,
                      "seq": seq, "ts": self._clock(),
                      "inflight": {str(t): int(n)
                                   for t, n in (inflight or {}).items()
                                   if int(n) > 0}})
        if not self._announced:
            self._announced = True
            _flight.record("replica_up", replica=self.replica_id,
                           observer=self.replica_id, rejoined=False)
        self.refresh()

    def alive_replicas(self) -> List[str]:
        with self._lock:
            return sorted(rid for rid in self._replicas
                          if rid == self.replica_id or rid not in self._lost)

    def start(self, inflight_fn: Optional[Callable[[], Dict[str, int]]]
              = None) -> "ClusterCoordinator":
        """Start the heartbeat thread. ``inflight_fn`` supplies the
        per-tenant in-flight counts each beat (the router's
        ``tenant_inflight``)."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _beat():
            while not self._stop.is_set():
                try:
                    self.heartbeat(inflight_fn() if inflight_fn is not None
                                   else None)
                except ClusterError:
                    raise
                except Exception:  # noqa: BLE001 — a transient disk
                    # fault (typed StorageError) must not kill the
                    # beat; the NEXT beat repairs the torn tail and
                    # peers judge us by heartbeat age, not by one miss
                    pass
                self._stop.wait(self.heartbeat_s)

        self._thread = threading.Thread(
            target=_beat, daemon=True,
            name=f"cluster-heartbeat-{self.replica_id}")
        self._thread.start()
        return self

    def shutdown(self, release_leases: bool = True) -> None:
        """Stop heartbeating; optionally release held leases (the
        clean-drain path — a SIGKILLed replica releases nothing and
        peers steal on staleness instead)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if release_leases:
            with self._lock:
                held = [m for m, l in self._leases.items()
                        if l.get("replica") == self.replica_id]
            for model in held:
                try:
                    self.release(model)
                except RegistryError:
                    pass  # best-effort: staleness handles the rest

    # -- the canary-controller lease -------------------------------------------
    def lease_state(self, model: str) -> Optional[dict]:
        with self._lock:
            cur = self._leases.get(model)
            return None if cur is None else dict(cur)

    def _holder_alive(self, lease: dict) -> bool:
        rid = lease.get("replica")
        if rid is None:
            return False
        if rid == self.replica_id:
            return True
        hb = self._replicas.get(rid)
        newest = max(float(lease.get("ts", 0.0)),
                     0.0 if hb is None else float(hb.get("ts", 0.0)))
        return self._clock() - newest <= self.lease_ttl_s

    def is_owner(self, model: str) -> bool:
        """Does this replica currently hold the model's lease? Read-only
        — never claims."""
        self.refresh()
        with self._lock:
            cur = self._leases.get(model)
            return cur is not None and cur.get("replica") == self.replica_id

    def ensure_lease(self, model: str) -> bool:
        """Own the model's canary-controller lease, claiming (or
        stealing from a stale holder) when possible. Returns True when
        this replica holds the lease afterwards. A lost same-epoch race
        (split-brain concurrent claims) returns False — journal append
        order resolved the tie and the first appended claim won."""
        from deeplearning4j_tpu.obs import flight as _flight

        self.refresh()
        with self._lock:
            cur = self._leases.get(model)
            if cur is not None and cur.get("replica") == self.replica_id:
                return True
            if cur is not None and cur.get("replica") is not None \
                    and self._holder_alive(cur):
                return False  # a live peer holds it
            prev_holder = None if cur is None else cur.get("replica")
            epoch = (0 if cur is None else int(cur["epoch"])) + 1
        self._append({"kind": "lease_claim", "model": str(model),
                      "replica": self.replica_id, "epoch": epoch,
                      "ts": self._clock()})
        self.refresh()
        with self._lock:
            cur = self._leases.get(model)
            won = (cur is not None
                   and cur.get("replica") == self.replica_id
                   and int(cur["epoch"]) == epoch)
        if won:
            if prev_holder is not None and prev_holder != self.replica_id:
                _flight.record("lease_steal", model=str(model),
                               replica=self.replica_id, epoch=epoch,
                               stolen_from=prev_holder)
            else:
                _flight.record("lease_acquire", model=str(model),
                               replica=self.replica_id, epoch=epoch)
        return won

    def fence(self, model: str) -> int:
        """The epoch fence every controller decision passes through
        IMMEDIATELY before it lands in the model registry: re-read the
        journal; if this replica no longer holds the lease (a peer
        stole it at a higher epoch while we were paused / skewed /
        partitioned) the decision raises a typed
        :class:`StaleEpochError` — recorded as ``stale_epoch_refused``
        — and is never merged. Returns the held epoch on success. The
        ``cluster.decision`` chaos seam fires first, so drills inject
        the pause exactly between "decided" and "fenced"."""
        from deeplearning4j_tpu.chaos import hooks as _chaos
        from deeplearning4j_tpu.obs import flight as _flight

        _chaos.fire("cluster.decision", model=str(model),
                    replica=self.replica_id)
        self.refresh()
        with self._lock:
            cur = self._leases.get(model)
            if cur is not None and cur.get("replica") == self.replica_id:
                return int(cur["epoch"])
            holder = None if cur is None else cur.get("replica")
            epoch = None if cur is None else int(cur["epoch"])
        _flight.record("stale_epoch_refused", model=str(model),
                       replica=self.replica_id, holder=holder,
                       epoch=epoch)
        raise StaleEpochError(
            f"replica {self.replica_id!r} does not hold the {model!r} "
            f"canary-controller lease (holder {holder!r} at epoch "
            f"{epoch}); stale decision refused — the current holder's "
            "verdict is the only one that lands")

    def release(self, model: str) -> None:
        from deeplearning4j_tpu.obs import flight as _flight

        epoch = self.fence(model)  # releasing a lease we lost is stale too
        self._append({"kind": "lease_release", "model": str(model),
                      "replica": self.replica_id, "epoch": epoch,
                      "ts": self._clock()})
        self.refresh()
        _flight.record("lease_release", model=str(model),
                       replica=self.replica_id, epoch=epoch)

    # -- cross-replica gate aggregation -----------------------------------------
    def journal_gate(self, model: str, version: int, role: str, stats,
                     urgent: bool = False) -> bool:
        """Journal this replica's per-version counters for peers'
        folds. Throttled per (model, version) to ``gate_interval_s``;
        ``urgent=True`` (an observed dispatch failure — ground truth
        the controller must see NOW) bypasses the throttle."""
        key = (str(model), int(version))
        now = time.monotonic()
        with self._lock:
            last = self._last_gate.get(key)
            if not urgent and last is not None \
                    and now - last < self.gate_interval_s:
                return False
            self._last_gate[key] = now
        self._append({"kind": "gate", "replica": self.replica_id,
                      "model": str(model), "version": int(version),
                      "role": str(role),
                      "requests": int(stats.requests),
                      "errors": int(stats.errors),
                      "latency_sum": float(stats.latency_sum),
                      "gen_requests": int(stats.gen_requests),
                      "gen_errors": int(stats.gen_errors),
                      "gen_latency_sum": float(stats.gen_latency_sum),
                      "score": None if stats.score is None
                      else float(stats.score),
                      "n_scores": int(getattr(stats, "_n_scores", 0)),
                      "ts": self._clock()})
        self.refresh()
        return True

    def _peer_gates(self, model: str, version: int) -> List[dict]:
        with self._lock:
            by_replica = self._gates.get((str(model), int(version)), {})
            return [dict(rec) for rid, rec in by_replica.items()
                    if rid != self.replica_id]

    def merged_stats(self, model: str, ve) -> _MergedStats:
        """Cluster-wide stats for a live versioned engine: local live
        counters + every peer's latest journaled gate record."""
        return _MergedStats(ve.stats, self._peer_gates(model, ve.version))

    def peer_failures(self, model: str, version: int) -> int:
        """Dispatch failures peers journaled for (model, version) —
        ground truth for the controller: any nonzero count trips."""
        return sum(int(p.get("errors", 0)) + int(p.get("gen_errors", 0))
                   for p in self._peer_gates(model, version))

    def gate_view(self, mm) -> _GateView:
        """The duck-typed managed-model proxy the canary gate rules
        evaluate over in cluster mode — same rules, merged inputs."""
        return _GateView(mm, self)

    # -- cluster-wide tenant quotas ----------------------------------------------
    def _fair_share(self, n_alive: int) -> int:
        g = self.global_tenant_quota
        return max(-(-g // max(n_alive, 1)), 1)  # ceil(G / N)

    def tenant_budget(self, tenant: str) -> Optional[int]:
        """This replica's admission budget for ``tenant`` under the
        cluster-wide quota: borrow peers' unused share when their
        heartbeats report the tenant idle, fall back to the fair-share
        floor when they are saturating (or their reports are stale —
        a lost replica's last report stops counting against us)."""
        if self.global_tenant_quota is None:
            return None
        with self._lock:
            alive = [rid for rid in self._replicas
                     if rid == self.replica_id or rid not in self._lost]
            if self.replica_id not in alive:
                alive.append(self.replica_id)
            peer_inflight = sum(
                int(self._replicas[rid].get("inflight", {})
                    .get(str(tenant), 0))
                for rid in alive if rid != self.replica_id)
            return max(self._fair_share(len(alive)),
                       self.global_tenant_quota - peer_inflight)

    # -- introspection -------------------------------------------------------------
    def describe(self) -> dict:
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "alive": self.alive_replicas(),
                "lost": sorted(self._lost),
                "leases": {m: dict(l) for m, l in self._leases.items()},
                "heartbeat_s": self.heartbeat_s,
                "lease_ttl_s": self.lease_ttl_s,
                "global_tenant_quota": self.global_tenant_quota,
            }


class ClusterFront:
    """Health-routing load-balancer front over N replicas — the PR 17
    round-robin test harness promoted to a product surface.

    A *replica* is registered as two callables: ``submit(*a, **kw)``
    (the replica's request entry point — a router/batcher ``submit`` or
    an HTTP adapter) and ``healthz()`` (its verdict ``/healthz``
    payload: a :class:`~deeplearning4j_tpu.obs.alerts.HealthVerdict`,
    a dict with a ``"status"`` key, or anything that raises when the
    replica is unreachable). Routing is round-robin over the *admitted*
    set only.

    Ejection/re-admission is streak-based hysteresis on
    :meth:`check_health` polls: ``eject_after`` consecutive
    critical/unreachable verdicts ejects (``replica_eject`` flight
    event, traffic stops immediately), ``readmit_after`` consecutive
    healthy/degraded verdicts re-admits (``replica_readmit``) — one bad
    scrape never ejects, one good one never re-admits, the same
    flap-suppression shape as the alert engine's pending→firing
    machine. ``submit`` additionally fails over within a single call:
    an admitted replica answering with overload/shutdown/draining (or
    a connection error) passes the request to the next admitted
    replica, one full pass, then the last typed error propagates.

    The front never ejects the LAST admitted replica via failover; only
    ``check_health`` can empty the pool (at which point ``route``
    raises a typed :class:`ClusterError` — degraded-but-serving beats
    serving nothing, but a tier that is provably all-critical must say
    so)."""

    def __init__(self, eject_after: int = 2, readmit_after: int = 2,
                 clock: Callable[[], float] = time.monotonic):
        from deeplearning4j_tpu.obs.lockwitness import witnessed_lock

        self.eject_after = max(int(eject_after), 1)
        self.readmit_after = max(int(readmit_after), 1)
        self._clock = clock
        self._lock = witnessed_lock("cluster.front")
        self._replicas: "OrderedDict[str, dict]" = OrderedDict()
        self._rr = 0

    def add_replica(self, name: str, submit: Callable,
                    healthz: Callable[[], object]) -> None:
        with self._lock:
            self._replicas[str(name)] = {
                "submit": submit, "healthz": healthz, "admitted": True,
                "bad_streak": 0, "good_streak": 0, "status": "unknown",
                "since": self._clock(),
            }

    def remove_replica(self, name: str) -> bool:
        with self._lock:
            return self._replicas.pop(str(name), None) is not None

    def admitted(self) -> List[str]:
        with self._lock:
            return [n for n, r in self._replicas.items() if r["admitted"]]

    def _rotation(self) -> List[Tuple[str, Callable]]:
        """Admitted (name, submit) pairs starting at the round-robin
        cursor; advances the cursor by one."""
        with self._lock:
            adm = [(n, r["submit"]) for n, r in self._replicas.items()
                   if r["admitted"]]
            if not adm:
                raise ClusterError(
                    "no admitted replicas: every registered replica is "
                    "ejected (or none were added); check_health must "
                    "see a healthy verdict before traffic can flow")
            start = self._rr % len(adm)
            self._rr += 1
            return adm[start:] + adm[:start]

    def route(self) -> str:
        """Name of the replica the next request would go to."""
        return self._rotation()[0][0]

    def submit(self, *args, **kwargs):
        """Submit through the front: round-robin plus single-pass
        failover on capacity/reachability errors. Application errors
        (bad input, deadline already spent) propagate from the first
        replica — failing those over would just burn the tier."""
        from deeplearning4j_tpu.serving.batcher import (
            ServerOverloadedError,
            ServerShutdownError,
        )

        last_err: Optional[Exception] = None
        for _name, submit in self._rotation():
            try:
                return submit(*args, **kwargs)
            except (ServerOverloadedError, ServerShutdownError,
                    ConnectionError, OSError) as e:
                last_err = e
        assert last_err is not None
        raise last_err

    @staticmethod
    def _status_of(payload) -> str:
        status = getattr(payload, "status", None)
        if status is None and isinstance(payload, dict):
            status = payload.get("status")
        return str(status) if status else "unknown"

    def check_health(self) -> Dict[str, str]:
        """Poll every replica's ``healthz`` once and run the
        eject/readmit streak machine. Returns name → verdict status
        (``unreachable`` when the poll raised). Call this from the
        serving tier's housekeeping cadence (the loadgen cluster plan
        pumps it per tick)."""
        from deeplearning4j_tpu.obs import flight as _flight

        with self._lock:
            targets = [(n, r["healthz"]) for n, r in self._replicas.items()]
        out: Dict[str, str] = {}
        for name, healthz in targets:
            try:
                status = self._status_of(healthz())
            except Exception:  # noqa: BLE001 — unreachable IS the signal
                status = "unreachable"
            out[name] = status
            bad = status in ("critical", "unreachable")
            event = None
            with self._lock:
                r = self._replicas.get(name)
                if r is None:
                    continue
                r["status"] = status
                if bad:
                    r["bad_streak"] += 1
                    r["good_streak"] = 0
                    if r["admitted"] and r["bad_streak"] >= self.eject_after:
                        r["admitted"] = False
                        r["since"] = self._clock()
                        event = ("replica_eject", r["bad_streak"])
                else:
                    r["good_streak"] += 1
                    r["bad_streak"] = 0
                    if (not r["admitted"]
                            and r["good_streak"] >= self.readmit_after):
                        r["admitted"] = True
                        r["since"] = self._clock()
                        event = ("replica_readmit", r["good_streak"])
            if event is not None:
                _flight.record(event[0], replica=name, status=status,
                               streak=event[1])
        return out

    def describe(self) -> dict:
        with self._lock:
            return {
                "eject_after": self.eject_after,
                "readmit_after": self.readmit_after,
                "replicas": {
                    n: {"admitted": r["admitted"], "status": r["status"],
                        "bad_streak": r["bad_streak"],
                        "good_streak": r["good_streak"]}
                    for n, r in self._replicas.items()},
            }
