"""Per-request serving traces: where did this request's latency go?

Aggregate quantiles (serving/metrics.py) say THAT p99 spiked; a
per-request timeline says WHY: queued behind a full batch, padded to a
wasteful bucket, stuck behind a slow dispatch, or slow to complete. Each
request carries a :class:`RequestTrace` — a list of monotonic-clock
marks at every stage boundary of its life:

    enqueue → batch_assembled → dispatch_start → forward_done
            → sliced → respond

The derived timeline reports the INTERVALS between consecutive marks
(``queue``, ``assembly``, ``forward``, ``slice``, ``respond``), which by
construction sum exactly to the end-to-end latency — no double-counted
or missing time. Stage marks are two machine instructions plus a
``time.monotonic()`` call; tracing every request costs well under the
bench's 5% p99 budget.

The batcher worker and the engine run on different abstraction levels
(the engine doesn't see requests, the batcher doesn't see buckets), so
bucket/padding facts flow through a **dispatch context**: a
thread-local slot the dispatcher opens around each ``infer`` call and
the engine fills from inside (:class:`DispatchInfo`). Single-threaded
per batcher worker by construction, and thread-local keeps concurrent
batchers (tests run many) from crosstalking.

Completed timelines are sampled into a bounded :class:`TraceBuffer`
(newest-wins ring) that ``GET /trace`` serves — the recent-requests
window a latency investigation actually needs, with bounded memory under
sustained traffic.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

#: interval names, keyed by the mark that CLOSES the interval.
#: Two families share the table: /predict requests (enqueue →
#: batch_assembled → dispatch_start → forward_done → sliced → respond)
#: and /generate requests (enqueue → slot_claimed → prefill_done →
#: decode_done → respond) — the generation engine marks slot claim,
#: prompt prefill and the whole token-decode span per request.
STAGE_NAMES = {
    "batch_assembled": "queue",
    "dispatch_start": "assembly",
    "forward_done": "forward",
    "sliced": "slice",
    "respond": "respond",
    "slot_claimed": "queue",
    "prefill_done": "prefill",
    "decode_done": "decode",
}


class RequestTrace:
    """Stage marks + metadata for one request. Created at submit time;
    marked by the batcher worker and the dispatcher; serialized once at
    completion."""

    __slots__ = ("marks", "meta")

    def __init__(self):
        self.marks: List[tuple] = [("enqueue", time.monotonic())]
        self.meta: Dict[str, object] = {}

    def mark(self, name: str, at: Optional[float] = None) -> None:
        self.marks.append((name, time.monotonic() if at is None else at))

    def note(self, **fields) -> None:
        self.meta.update(fields)

    def timeline(self) -> dict:
        """JSON-ready timeline: per-interval durations (ms) between
        consecutive marks — they sum exactly to ``total_ms`` — plus the
        dispatch metadata (bucket, rows, pad waste, model version)."""
        t0 = self.marks[0][1]
        stages = []
        prev = t0
        for name, t in self.marks[1:]:
            stages.append({
                "stage": STAGE_NAMES.get(name, name),
                "ms": round((t - prev) * 1e3, 4),
                "at_ms": round((t - t0) * 1e3, 4),
            })
            prev = t
        out = {
            "stages": stages,
            "total_ms": round((prev - t0) * 1e3, 4),
            "enqueued_unix": time.time() - (time.monotonic() - t0),
        }
        out.update(self.meta)
        return out


# --------------------------------------------------------------------------
# dispatch context (batcher worker ↔ engine)
# --------------------------------------------------------------------------
class DispatchInfo:
    """What the engine learned while serving one dispatch: the bucket it
    padded to, real vs padded rows, sequence padding, and the absolute
    times of the forward/slice boundaries."""

    __slots__ = ("bucket", "rows_real", "rows_padded", "seq_real",
                 "seq_padded", "t_forward_done", "t_sliced")

    def __init__(self):
        self.bucket: Optional[int] = None
        self.rows_real: Optional[int] = None
        self.rows_padded: Optional[int] = None
        self.seq_real: Optional[int] = None
        self.seq_padded: Optional[int] = None
        self.t_forward_done: Optional[float] = None
        self.t_sliced: Optional[float] = None


_ctx = threading.local()


def begin_dispatch() -> DispatchInfo:
    """Open a fresh dispatch context on this thread (the dispatcher does
    this right before calling ``infer``)."""
    info = DispatchInfo()
    _ctx.info = info
    return info


def current_dispatch() -> Optional[DispatchInfo]:
    """The open context, or None when nobody is tracing this dispatch
    (direct ``engine.infer`` callers, warmup) — filling is skipped."""
    return getattr(_ctx, "info", None)


def end_dispatch() -> Optional[DispatchInfo]:
    info = getattr(_ctx, "info", None)
    _ctx.info = None
    return info


# --------------------------------------------------------------------------
# bounded trace buffer (GET /trace)
# --------------------------------------------------------------------------
class TraceBuffer:
    """Thread-safe newest-wins ring of completed request traces.

    The ring stores :class:`RequestTrace` OBJECTS (a reference append);
    timelines are materialized lazily at :meth:`snapshot` time — the
    batcher's single worker thread must not spend its dispatch loop
    building dicts for a buffer nobody may ever scrape. A trace is
    immutable once its ``respond`` mark lands, so the read side never
    sees a torn timeline."""

    def __init__(self, capacity: int = 256):
        self.capacity = max(int(capacity), 1)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._total = 0

    def add(self, trace) -> None:
        """``trace``: a completed RequestTrace (or an already-built
        timeline dict)."""
        with self._lock:
            self._total += 1
            self._ring.append(trace)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    def snapshot(self, last: Optional[int] = None) -> dict:
        with self._lock:
            traces = list(self._ring)
            total = self._total
        if last is not None:
            traces = traces[-int(last):]
        return {"capacity": self.capacity, "recorded_total": total,
                "traces": [t.timeline() if isinstance(t, RequestTrace)
                           else t for t in traces]}
