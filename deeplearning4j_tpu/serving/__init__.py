"""Production inference serving subsystem.

TensorFlow's production story (arXiv 1605.08695) pairs the training
runtime with a serving layer: shape-managed batching, bounded queues,
live model reload. This package is that half for deeplearning4j_tpu —
the training stack produces crash-safe checkpoints
(``train.faults.save_checkpoint``) and this layer serves them:

- :mod:`buckets` — shape-bucket policy: every coalesced batch pads up to
  a pre-compiled bucket so steady-state serving never triggers a fresh
  XLA compile (arXiv 1810.09868: ahead-of-time-compiled fixed-shape
  programs are the unit of TPU execution).
- :mod:`batcher` — deadline-based dynamic batcher with bounded-queue
  backpressure (typed :class:`ServerOverloadedError` instead of
  unbounded blocking) and clean drain-on-shutdown.
- :mod:`engine` — model engine: jitted sharded forward, compile-count
  hook, ``warmup()``, atomic hot-swap reload from
  ``faults.latest_valid_checkpoint``.
- :mod:`server` — stdlib HTTP front-end (JSON + raw-npy predict,
  /healthz with the SLO verdict, /alerts, /reload, /metrics, /trace,
  /debug/flight with ?since_seq incremental polling, /debug/profile).
- :mod:`metrics` — thread-safe serving counters + latency quantiles +
  per-bucket pad-waste ratios.
- :mod:`rtrace` — per-request stage timelines (enqueue → batch →
  dispatch → slice → respond) and the bounded /trace buffer.
- :mod:`generate` — continuous-batching autoregressive decode engine:
  a slotted fixed-shape KV-cache/carry slab where requests join and
  leave the ONE in-flight jitted decode step at token granularity,
  with in-graph sampling and streamed responses (``POST /generate``).
- :mod:`sharded` — mesh-sharded serving: tensor-parallel inference and
  generation on a 2-D (batch, model) :class:`ServingMesh` via pure-auto
  GSPMD placement policies (parallel/serving_mesh.py), with
  reshard-on-load from any checkpoint topology and a typed solo
  fallback when the mesh degrades mid-serve.
- :mod:`registry` — the safe train→serve bridge: a crash-safe
  :class:`ModelRegistry` of named models with versioned,
  validation-gated snapshots, and the :class:`ModelRouter` serving
  them multiplexed (canary routing with auto-rollback, per-tenant
  queue quotas, LRU cold-model eviction/rewarm).
- :mod:`cluster` — the multi-replica tier: heartbeat/lease/epoch
  coordination over the registry's fsync'd journal
  (:class:`ClusterCoordinator`) — exactly one canary controller per
  window (epoch-fenced, stale decisions refused typed
  :class:`StaleEpochError`), cross-replica gate-counter aggregation so
  a regression any replica sees rolls back everywhere, and cluster-
  wide tenant-quota budget shares.
"""

from deeplearning4j_tpu.serving.batcher import (
    DynamicBatcher,
    InferenceRequest,
    RequestDeadlineExceeded,
    ServerOverloadedError,
    ServerShutdownError,
    ServingError,
)
from deeplearning4j_tpu.serving.buckets import BucketPolicy
from deeplearning4j_tpu.serving.cluster import (
    ClusterCoordinator,
    ClusterError,
    ClusterFront,
    StaleEpochError,
)
from deeplearning4j_tpu.serving.engine import InferenceEngine
from deeplearning4j_tpu.serving.generate import (
    DecodeStalledError,
    GenerationEngine,
    GenerationMemoryError,
    GenerationRequest,
)
from deeplearning4j_tpu.serving.metrics import GenerationMetrics, ServingMetrics
from deeplearning4j_tpu.serving.registry import (
    CanaryRolledBackError,
    ModelRegistry,
    ModelRouter,
    RegistryError,
    SnapshotValidationError,
    TenantQuotaExceededError,
    UnknownModelError,
)
from deeplearning4j_tpu.serving.rtrace import RequestTrace, TraceBuffer
from deeplearning4j_tpu.serving.sharded import (
    ShardedInferenceEngine,
    ShardedMeshError,
    sharded_generation_engine,
)
from deeplearning4j_tpu.serving.server import (
    InferenceServer,
    ServerDrainingError,
)

__all__ = [
    "BucketPolicy",
    "CanaryRolledBackError",
    "ClusterCoordinator",
    "ClusterError",
    "ClusterFront",
    "DecodeStalledError",
    "DynamicBatcher",
    "GenerationEngine",
    "GenerationMemoryError",
    "GenerationMetrics",
    "GenerationRequest",
    "InferenceEngine",
    "InferenceRequest",
    "InferenceServer",
    "ModelRegistry",
    "ModelRouter",
    "RegistryError",
    "RequestDeadlineExceeded",
    "RequestTrace",
    "ServerDrainingError",
    "ServerOverloadedError",
    "ServerShutdownError",
    "ServingError",
    "ServingMetrics",
    "ShardedInferenceEngine",
    "ShardedMeshError",
    "SnapshotValidationError",
    "StaleEpochError",
    "TenantQuotaExceededError",
    "sharded_generation_engine",
    "TraceBuffer",
    "UnknownModelError",
]
