"""Model registry + canary router: the safe train→serve bridge.

The stack already has both halves of a continuous deployment loop —
crash-safe checkpoints on the training side (train/faults.py) and
atomic zero-recompile hot reload on the serving side (serving/engine.py)
— but until now no safe bridge between them: a long ``fit()`` could not
ship snapshots to live traffic without a human, and a bad snapshot
(NaN-poisoned, regressed) that reached ``/reload`` replaced the good one
for 100% of traffic. This module is that bridge, the 1605.08695
train-and-serve pairing taken to its conclusion:

- :class:`ModelRegistry` — a crash-safe store of named models with
  versioned snapshots. Durability mirrors ``tune/store.py`` exactly:
  an append-only fsync'd ``journal.jsonl`` is the source of truth (a
  SIGKILL can lose at most the in-flight line; a torn TRAILING line is
  dropped on replay, a torn middle line refuses), and ``registry.json``
  is an atomically-replaced (tmp + ``os.replace``) snapshot for humans
  and tooling — a crash between journal append and snapshot replace
  loses nothing, the restart replays the journal. Published snapshots
  are COPIED into the registry (``snapshots/<model>/v####.zip``) so a
  trainer's keep-last-k pruning can never delete a version that is
  still serving.

- **Validation-gated publish** — every :meth:`ModelRegistry.publish`
  carries a held-out validation score. A non-finite score (the
  NaN-poisoned snapshot) or a score regressed beyond
  ``regression_tolerance`` against the best validated version is
  REFUSED with a typed :class:`SnapshotValidationError` — journaled as
  ``rejected``, recorded as a ``publish_refused`` flight event, and
  never eligible for activation or canary traffic.

- :class:`ModelRouter` — the multi-model serving front-end the HTTP
  server mounts: routes requests by model name across multiple warmed
  engines (each model keeps its own :class:`InferenceEngine` + batcher,
  so the 1810.09868 fixed-shape zero-recompile discipline holds per
  model), enforces per-tenant queue quotas (typed
  :class:`TenantQuotaExceededError` — one noisy tenant gets 503s, the
  others are untouched), evicts cold models LRU (``model_evict`` /
  ``model_rewarm`` flight events), and runs the **canary state
  machine**:

  ``publish → validate → canary_start → promote | regression_trip →
  rollback``

  A newly validated version never takes 100% of traffic: the router
  builds and warms a SEPARATE engine for it, routes ``canary_fraction``
  of the model's requests there for a bounded ``canary_window_s``, and
  watches per-version error/latency/score counters. A clean window
  auto-promotes (the canary engine becomes the active one — already
  warm, zero recompiles, and the old active batcher drains so in-flight
  old-version requests all complete, PR 3's no-mixing guarantee
  extended to versioned routing). Any canary dispatch failure, a
  latency blow-up, or a regressed score trips ``regression_trip`` →
  ``rollback``: outstanding canary requests are failed typed
  first-wins BEFORE their results could reach a caller, the canary
  engine is retired, and the active version keeps serving untouched.
  Every transition lands in the journal AND the flight recorder, so
  ``cli flight-dump`` renders the whole deployment timeline.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import warnings
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.obs.lockwitness import (
    witnessed_lock,
    witnessed_rlock,
)
from deeplearning4j_tpu.serving.batcher import (
    DynamicBatcher,
    RequestDeadlineExceeded,
    ServerOverloadedError,
    ServingError,
    make_dispatcher,
)
from deeplearning4j_tpu.serving.buckets import BucketPolicy
from deeplearning4j_tpu.serving.metrics import ServingMetrics

JOURNAL_NAME = "journal.jsonl"
SNAPSHOT_NAME = "registry.json"
SNAPSHOTS_SUBDIR = "snapshots"
SCHEMA_VERSION = 1


class RegistryError(RuntimeError):
    """Base of the typed registry failures."""


class SnapshotValidationError(RegistryError):
    """A published snapshot was refused by the validation gate
    (non-finite held-out score, or regressed beyond the tolerance
    against the best validated version). The snapshot is journaled as
    ``rejected`` and can never be activated or canaried."""


class UnknownModelError(RegistryError, KeyError):
    """Request named a model the registry does not hold (HTTP 404)."""

    def __str__(self):  # KeyError.__str__ repr-quotes; keep it readable
        return self.args[0] if self.args else ""


class TenantQuotaExceededError(ServerOverloadedError):
    """One tenant exceeded its per-tenant queue quota — 503 for THAT
    tenant only; other tenants' admission is untouched (a global
    :class:`ServerOverloadedError` would let one noisy tenant starve
    everyone)."""

    def __init__(self, message: str, tenant: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.tenant = tenant
        if retry_after_s is not None:
            self.retry_after_s = retry_after_s


class CanaryRolledBackError(ServingError):
    """The request was routed to a canary version that regressed and
    rolled back before the result could be returned. Retryable — the
    active version is serving (HTTP 503)."""


def _now() -> float:
    return time.time()


# --------------------------------------------------------------------------
# the crash-safe registry store
# --------------------------------------------------------------------------
class ModelRegistry:
    """Named models → versioned snapshots, durable across SIGKILL.

    Thread-safe (one RLock) and multi-process friendly: a trainer
    publishing and a server canarying can share one registry directory —
    both append whole fsync'd lines to the journal (O_APPEND), and
    :meth:`refresh` folds in lines another process appended. The journal
    is the source of truth; ``registry.json`` is a convenience snapshot
    rewritten atomically after every append.
    """

    def __init__(self, directory: str, regression_tolerance: float = 0.0,
                 higher_is_better: bool = False,
                 keep_last: Optional[int] = None,
                 refresh_min_interval_s: float = 0.0):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.journal_path = os.path.join(self.directory, JOURNAL_NAME)
        self.snapshot_path = os.path.join(self.directory, SNAPSHOT_NAME)
        #: a new score may be worse than the best validated one by this
        #: relative fraction before the publish gate refuses it
        self.regression_tolerance = float(regression_tolerance)
        self.higher_is_better = bool(higher_is_better)
        #: snapshots retained per model beyond the referenced set
        #: (active / canary / newest validated are never pruned)
        self.keep_last = None if keep_last is None else int(keep_last)
        #: min seconds between :meth:`refresh` stat checks (0 = stat on
        #: every call, the original behavior). A deployment with many
        #: co-located readers raises it; the CLUSTER layer bypasses it
        #: (``refresh(force=True)``) while a canary window is open —
        #: cross-replica rollback latency is bounded by this cadence
        self.refresh_min_interval_s = float(refresh_min_interval_s)
        self._next_refresh_check = 0.0  # monotonic deadline
        self._lock = witnessed_rlock("registry.store")
        self._models: Dict[str, dict] = {}
        self._journal_bytes = 0
        from deeplearning4j_tpu.train.faults import sweep_stale_tmp

        # orphaned staging files from a PRIOR crashed atomic write
        # (snapshot copies, registry.json stages) are swept — and
        # counted in a tmp_sweep flight event — on registry-dir open
        sweep_stale_tmp(self.directory, surface="registry",
                        recursive=True)
        self._load()

    # -- journal / snapshot durability --------------------------------------
    def _append(self, record: dict) -> None:
        """Journal first (fsync'd — the WAL), snapshot second (atomic
        replace). A SIGKILL between the two loses nothing: restart
        replays the journal past the stale snapshot. The record is
        folded into in-memory state only AFTER the journal append
        durably lands — a failed append (disk full: typed StorageError
        out of the fs layer) leaves memory and disk agreeing on the
        pre-append state (at worst disk holds a torn trailing line,
        which replay drops)."""
        from deeplearning4j_tpu.chaos import fslayer as _fs

        with self._lock:
            line = json.dumps(record, sort_keys=True) + "\n"
            _fs.append_line(self.journal_path, line,
                            surface="registry_journal")
            self._fold(record)
            # track the bytes WE have folded, not the file size: the
            # file may already contain another process's un-folded
            # lines (O_APPEND interleaving), and absorbing them into
            # the counter here would make refresh() skip them forever
            self._journal_bytes += len(line.encode())
            try:
                self._write_snapshot()
            except _fs.StorageError as e:
                # the journal (the WAL) committed; registry.json is a
                # convenience mirror — a failed rewrite degrades, never
                # un-publishes (the next successful append refreshes it)
                warnings.warn(f"registry snapshot write failed "
                              f"(journal is authoritative): {e}",
                              stacklevel=2)

    def _write_snapshot(self) -> None:
        from deeplearning4j_tpu.chaos import fslayer as _fs

        body = {"schema_version": SCHEMA_VERSION, "written_at": _now(),
                "models": self._models}
        _fs.write_atomic(self.snapshot_path,
                         json.dumps(body, indent=1, sort_keys=True),
                         surface="registry_snapshot")

    def _replay(self) -> List[dict]:
        """Journal records in append order — the tune/store.py torn-line
        semantics: a torn FINAL line (what a SIGKILL mid-append leaves)
        is dropped with a warning, a torn line with valid records after
        it is external corruption and refuses."""
        if not os.path.exists(self.journal_path):
            return []
        out: List[dict] = []
        torn_at: Optional[int] = None
        with open(self.journal_path) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    torn_at = i
                    continue
                if torn_at is not None:
                    raise RegistryError(
                        f"{self.journal_path}:{torn_at + 1}: corrupt journal "
                        "line with valid records after it — not crash "
                        "truncation; refusing to replay")
                out.append(rec)
        if torn_at is not None:
            warnings.warn(
                f"{self.journal_path}: dropping torn trailing line "
                f"{torn_at + 1} (crash mid-append)", stacklevel=2)
        return out

    def _load(self) -> None:
        with self._lock:
            self._models = {}
            records = self._replay()
            if records:
                for rec in records:
                    self._fold(rec)
            elif os.path.exists(self.snapshot_path):
                # journal gone but a snapshot survives (hand-seeded or
                # archived registry): adopt it as the starting state
                with open(self.snapshot_path) as f:
                    self._models = json.load(f).get("models", {})
            self._journal_bytes = (os.path.getsize(self.journal_path)
                                   if os.path.exists(self.journal_path)
                                   else 0)

    def refresh(self, force: bool = False) -> bool:
        """Fold in journal lines another process appended since the last
        load (the serving router polls this to notice a trainer's
        publishes). Returns True when state changed. Cheap when nothing
        changed: one stat — and, with ``refresh_min_interval_s`` set,
        not even that until the throttle window elapses. ``force=True``
        bypasses the throttle (the cluster layer's canary-window
        tightening)."""
        with self._lock:
            now = time.monotonic()
            if not force and now < self._next_refresh_check:
                return False
            self._next_refresh_check = now + self.refresh_min_interval_s
            size = (os.path.getsize(self.journal_path)
                    if os.path.exists(self.journal_path) else 0)
            if size == self._journal_bytes:
                return False
            # full re-replay: the journal is small (one line per
            # deployment event, not per request) and replay is the one
            # code path crash-recovery already trusts
            self._load()
            return True

    # -- folding (journal record → state machine) ----------------------------
    def _model(self, name: str) -> dict:
        m = self._models.get(name)
        if m is None:
            m = {"name": name, "active_version": None, "canary": None,
                 "next_version": 1, "bucket_policy": None, "versions": {}}
            self._models[name] = m
        return m

    def _fold(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "model":
            m = self._model(rec["name"])
            if rec.get("bucket_policy") is not None:
                m["bucket_policy"] = rec["bucket_policy"]
            return
        m = self._model(rec["name"])
        v = str(rec["version"]) if "version" in rec else None
        if kind == "publish":
            m["versions"][v] = {
                "version": int(rec["version"]),
                "path": rec["path"],
                "fingerprint": rec.get("fingerprint"),
                "source": rec.get("source"),
                "published_at": rec.get("ts"),
                "iteration": rec.get("iteration"),
                "validation": None,
                "status": "published",
            }
            m["next_version"] = max(m["next_version"],
                                    int(rec["version"]) + 1)
        elif kind == "validated":
            vr = m["versions"].get(v)
            if vr is not None:
                vr["validation"] = {"ok": True, "score": rec.get("score"),
                                    "baseline": rec.get("baseline")}
                vr["status"] = "validated"
        elif kind == "rejected":
            vr = m["versions"].get(v)
            if vr is not None:
                vr["validation"] = {"ok": False, "score": rec.get("score"),
                                    "reason": rec.get("reason")}
                vr["status"] = "rejected"
        elif kind == "activate" or kind == "promote":
            old = m.get("active_version")
            if old is not None and str(old) in m["versions"] \
                    and int(old) != int(rec["version"]):
                m["versions"][str(old)]["status"] = "retired"
            m["active_version"] = int(rec["version"])
            if v in m["versions"]:
                m["versions"][v]["status"] = "active"
            if m.get("canary") and int(m["canary"]["version"]) == int(
                    rec["version"]):
                m["canary"] = None
        elif kind == "canary_start":
            m["canary"] = {"version": int(rec["version"]),
                           "fraction": rec.get("fraction"),
                           "window_s": rec.get("window_s"),
                           "started_at": rec.get("ts")}
            if v in m["versions"]:
                m["versions"][v]["status"] = "canary"
        elif kind == "rollback":
            if m.get("canary") and int(m["canary"]["version"]) == int(
                    rec["version"]):
                m["canary"] = None
            if v in m["versions"]:
                m["versions"][v]["status"] = "rolled_back"
        elif kind == "prune":
            m["versions"].pop(v, None)

    # -- reads ---------------------------------------------------------------
    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def get(self, name: str) -> dict:
        with self._lock:
            m = self._models.get(name)
            if m is None:
                raise UnknownModelError(
                    f"model {name!r} is not in the registry "
                    f"(have: {sorted(self._models)})")
            return json.loads(json.dumps(m))  # defensive deep copy

    def describe(self) -> dict:
        with self._lock:
            return {"directory": self.directory,
                    "models": json.loads(json.dumps(self._models))}

    def resolve(self, name: str) -> dict:
        """The ACTIVE version record for ``name`` — what a restarted
        server serves. Raises typed when the model has no activated
        (validated) version yet."""
        m = self.get(name)
        av = m.get("active_version")
        if av is None:
            raise UnknownModelError(
                f"model {name!r} has no active version (publish + "
                "validation must succeed at least once)")
        return m["versions"][str(av)]

    def candidate(self, name: str) -> Optional[dict]:
        """Newest VALIDATED version newer than the active one (the one a
        router should canary), or None."""
        with self._lock:
            m = self._models.get(name)
            if m is None:
                return None
            av = m.get("active_version") or 0
            cands = [vr for vr in m["versions"].values()
                     if vr["version"] > av and vr["status"] == "validated"]
            return (dict(max(cands, key=lambda vr: vr["version"]))
                    if cands else None)

    def canary_state(self, name: str) -> Optional[dict]:
        with self._lock:
            m = self._models.get(name)
            return None if m is None else (
                None if m.get("canary") is None else dict(m["canary"]))

    def best_score(self, name: str) -> Optional[float]:
        """Best validated score across the model's versions (direction
        aware) — the baseline the publish regression gate compares new
        snapshots against."""
        with self._lock:
            m = self._models.get(name)
            if m is None:
                return None
            scores = [vr["validation"]["score"]
                      for vr in m["versions"].values()
                      if vr.get("validation") and vr["validation"]["ok"]
                      and vr["validation"].get("score") is not None
                      and vr["status"] != "rolled_back"]
            if not scores:
                return None
            return max(scores) if self.higher_is_better else min(scores)

    def bucket_policy(self, name: str) -> Optional[BucketPolicy]:
        with self._lock:
            m = self._models.get(name)
            bp = None if m is None else m.get("bucket_policy")
        if bp is None:
            return None
        return BucketPolicy(batch_buckets=bp.get("batch_buckets"),
                            max_batch=bp.get("max_batch"),
                            seq_buckets=bp.get("seq_buckets"))

    # -- writes --------------------------------------------------------------
    def define_model(self, name: str,
                     bucket_policy: Optional[dict] = None) -> None:
        """Idempotently declare a model (optionally with its serving
        bucket policy: ``{"batch_buckets": [...], "max_batch": n,
        "seq_buckets": [...]}``)."""
        with self._lock:
            existing = self._models.get(name)
            if existing is not None and (
                    bucket_policy is None
                    or existing.get("bucket_policy") == bucket_policy):
                return
            self._append({"kind": "model", "name": name, "ts": _now(),
                          "bucket_policy": bucket_policy})

    def _snapshot_dest(self, name: str, version: int) -> str:
        d = os.path.join(self.directory, SNAPSHOTS_SUBDIR, name)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"v{version:04d}.zip")

    def publish(self, name: str, source: str, score: Optional[float] = None,
                iteration: Optional[int] = None,
                allow_unvalidated: bool = False) -> dict:
        """Publish a checkpoint as the next version of ``name``.

        ``source`` is a checkpoint zip or directory; it resolves through
        the serving checkpoint-fallback path (a truncated newest zip
        falls back to its newest valid sibling, with a
        ``checkpoint_fallback`` flight event naming the skipped path and
        error class), then the file is COPIED into the registry
        atomically — the registry owns its snapshots, a trainer's
        retention pruning cannot unpublish one.

        ``score`` is the held-out validation verdict. The gate refuses
        (typed :class:`SnapshotValidationError`, journaled ``rejected``,
        ``publish_refused`` flight event) when the score is non-finite
        or regressed beyond ``regression_tolerance`` against the best
        validated version. ``allow_unvalidated=True`` skips the gate
        (score may be None) — the version lands as ``published`` /
        ``validated``-without-score and the serving-side canary gate is
        the only line of defense; use it for score-free models, never to
        silence a refusal.

        The first validated version of a model auto-activates (there is
        no baseline to canary against); later ones wait for a router to
        canary them.
        """
        from deeplearning4j_tpu.chaos import fslayer as _fs
        from deeplearning4j_tpu.chaos import hooks as _chaos
        from deeplearning4j_tpu.obs import flight as _flight
        from deeplearning4j_tpu.serving.engine import (
            resolve_checkpoint_source,
        )
        from deeplearning4j_tpu.train.faults import (
            atomic_tmp_path,
            checkpoint_fingerprint,
        )

        path = resolve_checkpoint_source(source)
        # chaos seam: the held-out validation verdict (mode 'value'
        # overrides the score — the NaN-poisoned-snapshot drill)
        _score_spec = _chaos.fire("registry.validation_score", model=name)
        if _score_spec is not None and _score_spec.mode == "value":
            score = _score_spec.value
        # stage the copy OUTSIDE the lock: a multi-GB checkpoint copy
        # must not block every registry read (and, through refresh(),
        # every co-located serving submission) for its duration — only
        # the version assignment and the rename need the lock. Disk-full
        # here (fs layer, injectable) is a typed StorageError with the
        # staging file cleaned and the live registry untouched.
        stage_dir = os.path.join(self.directory, SNAPSHOTS_SUBDIR, name)
        os.makedirs(stage_dir, exist_ok=True)
        tmp = atomic_tmp_path(os.path.join(stage_dir, "incoming.zip"))
        try:
            _fs.copy_file(path, tmp, surface="registry_publish")
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        with self._lock:
            # read the next version WITHOUT creating the model entry:
            # in-memory state must only change when the WAL append
            # commits (a first-publish whose append fails must not
            # leave a phantom model that a restart would not replay)
            existing = self._models.get(name)
            version = (int(existing["next_version"])
                       if existing is not None else 1)
            dest = self._snapshot_dest(name, version)
            try:
                _fs.replace(tmp, dest, surface="registry_publish")
            finally:
                if os.path.exists(tmp):
                    os.remove(tmp)
            fp = checkpoint_fingerprint(dest)
            baseline = self.best_score(name)
            try:
                self._append({"kind": "publish", "name": name,
                              "version": version, "path": dest,
                              "fingerprint": list(fp), "source": str(path),
                              "iteration": iteration, "ts": _now()})
            except _fs.StorageError:
                # the WAL append failed: nothing was folded, so the
                # copied snapshot would be an orphan the journal never
                # names — remove it and surface the typed error (the
                # previously active version keeps serving)
                try:
                    os.remove(dest)
                except OSError:
                    pass
                raise
            m = self._models[name]  # created by the committed fold
            _flight.record("publish", model=name, version=version,
                           source=str(path),
                           score=None if score is None else float(score))
            refusal = self._gate(name, score, baseline, allow_unvalidated)
            if refusal is not None:
                self._append({"kind": "rejected", "name": name,
                              "version": version, "reason": refusal,
                              "score": None if score is None
                              else float(score), "ts": _now()})
                _flight.record("publish_refused", model=name,
                               version=version, reason=refusal,
                               score=None if score is None
                               else float(score))
                # a rejected snapshot can never be activated — keeping
                # its bytes would grow the registry by one checkpoint
                # per refused publish (a long fit whose baseline was a
                # lucky early epoch refuses every later one)
                try:
                    os.remove(dest)
                except OSError:
                    pass
                raise SnapshotValidationError(
                    f"{name} v{version}: {refusal} — snapshot refused, "
                    "never activated (the live version keeps serving)")
            self._append({"kind": "validated", "name": name,
                          "version": version,
                          "score": None if score is None else float(score),
                          "baseline": baseline, "ts": _now()})
            _flight.record("validated", model=name, version=version,
                           score=None if score is None else float(score),
                           baseline=baseline)
            if m.get("active_version") is None:
                self.activate(name, version)
            self._prune(name)
            return dict(m["versions"][str(version)])

    def _gate(self, name: str, score: Optional[float],
              baseline: Optional[float], allow_unvalidated: bool
              ) -> Optional[str]:
        """The validation verdict: None = pass, else the refusal reason."""
        if score is None:
            return (None if allow_unvalidated
                    else "no validation score supplied (pass score=..., or "
                         "allow_unvalidated=True for score-free models)")
        score = float(score)
        if not math.isfinite(score):
            return f"non-finite validation score ({score})"
        if allow_unvalidated or baseline is None:
            return None
        tol = self.regression_tolerance * max(abs(baseline), 1e-12)
        if self.higher_is_better:
            regressed = score < baseline - tol
        else:
            regressed = score > baseline + tol
        if regressed:
            return (f"validation score {score:.6g} regressed vs best "
                    f"validated {baseline:.6g} "
                    f"(tolerance {self.regression_tolerance:g})")
        return None

    def activate(self, name: str, version: int) -> None:
        """Make ``version`` the active one (the first-version bootstrap
        and the explicit-operator override; routed promotion goes
        through :meth:`promote`)."""
        with self._lock:
            vr = self.get(name)["versions"].get(str(int(version)))
            if vr is None:
                raise RegistryError(f"{name} has no version {version}")
            if vr["status"] == "rejected":
                raise SnapshotValidationError(
                    f"{name} v{version} was refused by validation; "
                    "it cannot be activated")
            self._append({"kind": "activate", "name": name,
                          "version": int(version), "ts": _now()})

    def start_canary(self, name: str, version: int, fraction: float,
                     window_s: float) -> None:
        with self._lock:
            self._append({"kind": "canary_start", "name": name,
                          "version": int(version),
                          "fraction": float(fraction),
                          "window_s": float(window_s), "ts": _now()})

    def promote(self, name: str, version: int) -> None:
        with self._lock:
            self._append({"kind": "promote", "name": name,
                          "version": int(version), "ts": _now()})

    def rollback(self, name: str, version: int, reason: str) -> None:
        with self._lock:
            self._append({"kind": "rollback", "name": name,
                          "version": int(version), "reason": str(reason),
                          "ts": _now()})

    def _prune(self, name: str) -> None:
        """keep-last-k snapshot retention: never the active, canary, or
        newest-validated version; journal history is kept (cheap)."""
        if self.keep_last is None:
            return
        m = self._models[name]
        keep = {m.get("active_version")}
        if m.get("canary"):
            keep.add(m["canary"]["version"])
        cand = self.candidate(name)
        if cand is not None:
            keep.add(cand["version"])
        versions = sorted(int(v) for v in m["versions"])
        disposable = [v for v in versions if v not in keep]
        for v in disposable[:max(len(disposable) - self.keep_last, 0)]:
            vr = m["versions"][str(v)]
            try:
                if os.path.exists(vr["path"]):
                    os.remove(vr["path"])
            except OSError:
                continue
            self._append({"kind": "prune", "name": name, "version": v,
                          "ts": _now()})


# --------------------------------------------------------------------------
# per-version serving state (engine + batcher + counters)
# --------------------------------------------------------------------------
class _VersionStats:
    """Per-version serving counters — the canary metric gate's inputs.
    Mirrored into the shared metrics registry as labeled families.
    Generation traffic keeps its own error/latency columns: a decode
    request holds a slot for hundreds of tokens, so folding its wall
    time into the /predict mean would poison the latency comparison —
    the gate compares generation to generation."""

    __slots__ = ("requests", "errors", "latency_sum", "score", "_n_scores",
                 "gen_requests", "gen_errors", "gen_latency_sum")

    def __init__(self):
        self.requests = 0
        self.errors = 0
        self.latency_sum = 0.0
        self.score: Optional[float] = None
        self._n_scores = 0
        self.gen_requests = 0
        self.gen_errors = 0
        self.gen_latency_sum = 0.0

    def mean_latency(self) -> Optional[float]:
        return self.latency_sum / self.requests if self.requests else None

    def mean_gen_latency(self) -> Optional[float]:
        return (self.gen_latency_sum / self.gen_requests
                if self.gen_requests else None)

    def observe_score(self, value: float) -> None:
        # running mean: scores arrive from probes / external evaluators
        self._n_scores += 1
        prev = self.score if self.score is not None else 0.0
        self.score = prev + (float(value) - prev) / self._n_scores


class _VersionedEngine:
    """One live (engine, batcher) pair pinned to one registry version.
    Requests submitted here are computed entirely by this version —
    per-version batchers are what make "a batch is one version" true by
    construction, even while a canary runs next to the active."""

    def __init__(self, router: "ModelRouter", name: str, vrec: dict,
                 role: str):
        self.router = router
        self.name = name
        self.version = int(vrec["version"])
        self.record = dict(vrec)
        self.role = role  # "active" | "canary"
        self.dead = False
        self.stats = _VersionStats()
        from deeplearning4j_tpu.serving.engine import InferenceEngine

        policy = router.registry.bucket_policy(name)
        kwargs = dict(metrics=router.metrics)
        if policy is not None:
            kwargs["buckets"] = policy
        engine_cls = InferenceEngine
        if router.mesh is not None:
            kwargs["mesh"] = router.mesh
            if getattr(router.mesh, "n_model", 1) > 1:
                # a 2-D (batch, model) ServingMesh serves every version
                # — active and canary alike — tensor-parallel; the
                # canary state machine neither knows nor cares (a
                # sharded candidate's dispatch failure trips the same
                # rollback as any other)
                from deeplearning4j_tpu.serving.sharded import (
                    ShardedInferenceEngine,
                )

                engine_cls = ShardedInferenceEngine
        self.engine = engine_cls.from_checkpoint(vrec["path"], **kwargs)
        shape = self.engine.example_shape()
        if shape is not None:
            # warm BEFORE any traffic: canary traffic must never absorb
            # the new version's compiles (PR 3's reload discipline)
            self.engine.warmup(shape)
        self.batcher = DynamicBatcher(
            make_dispatcher(self._infer, metrics=router.metrics,
                            traces=router.traces),
            batch_limit=router.batch_limit,
            max_wait_ms=router.max_wait_ms,
            queue_limit=router.queue_limit, metrics=router.metrics,
            trace_requests=router.trace_requests)

    def _infer(self, x, mask=None):
        from deeplearning4j_tpu.chaos import hooks as _chaos

        t0 = time.monotonic()
        try:
            # chaos seam with deployment identity: drills target exactly
            # the canary's dispatches via match={"role": "canary"}
            _chaos.fire("registry.version_dispatch", model=self.name,
                        version=self.version, role=self.role)
            out, _snap_version = self.engine.infer_versioned(x, mask)
        except BaseException as e:
            self.stats.errors += 1
            self.router._counter("registry_version_errors_total",
                                 self.name, self.version).inc()
            if self.role == "canary":
                # ANY canary dispatch failure trips the rollback — the
                # bad version must not get a second chance at traffic
                self.router._trip(self.name, self,
                                  f"dispatch failure: {type(e).__name__}")
            raise
        if self.dead:
            # rolled back while this batch was in flight: fail instead
            # of finish, so no result computed by the bad version
            # reaches a caller after regression_trip
            raise CanaryRolledBackError(
                f"{self.name} v{self.version} rolled back mid-dispatch")
        dt = time.monotonic() - t0
        self.stats.requests += 1
        self.stats.latency_sum += dt
        self.router._counter("registry_version_requests_total",
                             self.name, self.version).inc()
        self.router._counter("registry_version_latency_seconds_total",
                             self.name, self.version).inc(dt)
        if self.role == "canary":
            self.router._evaluate_canary(self.name)
        # requests carry the REGISTRY version (the deployment-level
        # identity), not the engine's internal snapshot generation
        return out, self.version

    def retire(self, drain: bool) -> None:
        """Shut the batcher down off-thread: retire() is called from
        batcher worker threads (a canary completion promoting, a canary
        dispatch failure tripping) and DynamicBatcher.shutdown joins the
        worker — a same-thread join would deadlock."""
        self.dead = True
        threading.Thread(target=self.batcher.shutdown,
                         kwargs={"drain": drain}, daemon=True,
                         name=f"retire-{self.name}-v{self.version}").start()


class _ManagedModel:
    """Router-side live state of one registry model: the active
    versioned engine, an optional canary one, canary bookkeeping, and
    the per-tenant outstanding-request ledgers."""

    def __init__(self, name: str):
        self.name = name
        self.lock = witnessed_rlock("router.model")
        self.active: Optional[_VersionedEngine] = None
        self.canary: Optional[_VersionedEngine] = None
        self.canary_started: Optional[float] = None  # monotonic
        self.canary_counter = 0
        self.canary_inflight: deque = deque()
        #: cluster mode: this replica observed the canary fail but does
        #: NOT hold the controller lease — local canary routing stops
        #: (no more traffic to a version we saw fail) while the lease
        #: holder's cluster-wide verdict is pending in the journal
        self.canary_suspended = False
        self.generation = None  # lazy GenerationEngine
        #: canary-version GenerationEngine (built lazily at the first
        #: /generate while a canary window is open) — canary_fraction of
        #: generation traffic decodes on the candidate weights so its
        #: errors/latency feed the metric gate (the PR 11 residue:
        #: generation-only regressions must still trip auto-rollback)
        self.canary_generation = None
        self.canary_gen_failed = False  # build failed once: don't retry
        #: a build+warm is in flight OFF the lock (exactly one builder;
        #: traffic keeps routing to the active version meanwhile)
        self.canary_gen_building = False
        #: per-window AlertEvaluator holding the canary gate's rules
        #: (obs/slo.canary_gate_rules) — built at canary start, torn
        #: down on trip/promote/evict; the gate decisions live in the
        #: rules' signals, the engine owns the state machine + forensics
        self.canary_alerts = None
        self.gen_counter = 0
        self.last_used = time.monotonic()
        #: set by LRU eviction. Engines are retired but the references
        #: stay valid, so a thread that grabbed this object before the
        #: eviction fails typed (ServerShutdownError from the drained
        #: batcher) or re-admits — never an AttributeError on None
        self.evicted = False


class ModelRouter:
    """Multi-model request router over a :class:`ModelRegistry`.

    One router per serving process. Models are admitted lazily (first
    request builds + warms the engine — a ``model_rewarm`` flight event
    marks the stall) and evicted LRU beyond ``max_live_models``
    (``model_evict``). The canary state machine runs inside the request
    path: submissions adopt newly validated versions, completions feed
    the metric gate, and the gate promotes or rolls back.

    ``score_probe`` (optional, ``engine → float``, same direction as the
    registry's scores) re-runs the held-out validation against the
    canary's LIVE engine at canary start — the score leg of the gate
    without any external feeder. External evaluators can also post
    scores via :meth:`record_score`.
    """

    def __init__(self, registry: ModelRegistry,
                 batch_limit: int = 32, max_wait_ms: float = 5.0,
                 queue_limit: int = 256, max_live_models: int = 4,
                 tenant_quota: Optional[int] = None,
                 canary_fraction: float = 0.1,
                 canary_window_s: float = 30.0,
                 canary_min_requests: int = 1,
                 latency_trip_mult: float = 5.0,
                 latency_trip_min_samples: int = 8,
                 score_trip_tolerance: float = 0.0,
                 score_probe: Optional[Callable] = None,
                 refresh_s: float = 2.0, mesh=None,
                 gen_slots: int = 0, gen_max_length: Optional[int] = None,
                 gen_spec_decode_k: int = 1, gen_draft_mode: str = "ngram",
                 gen_prefix_cache_mb: float = 0.0,
                 metrics: Optional[ServingMetrics] = None,
                 trace_requests: bool = True, traces=None,
                 cluster=None):
        self.registry = registry
        #: optional serving/cluster.py ClusterCoordinator. When set,
        #: the canary state machine becomes cluster-wide: gate ticks
        #: read CLUSTER-merged per-version stats, only the lease
        #: holder commits trip/promote decisions (epoch-fenced — a
        #: stale ex-holder's decision raises typed StaleEpochError),
        #: and tenant quotas become budget shares of the global quota
        self.cluster = cluster
        self.batch_limit = int(batch_limit)
        self.max_wait_ms = float(max_wait_ms)
        self.queue_limit = int(queue_limit)
        self.max_live_models = max(int(max_live_models), 1)
        self.tenant_quota = (None if tenant_quota is None
                             else max(int(tenant_quota), 1))
        self.canary_fraction = min(max(float(canary_fraction), 0.0), 1.0)
        self.canary_window_s = float(canary_window_s)
        self.canary_min_requests = max(int(canary_min_requests), 1)
        self.latency_trip_mult = float(latency_trip_mult)
        self.latency_trip_min_samples = max(int(latency_trip_min_samples), 1)
        self.score_trip_tolerance = float(score_trip_tolerance)
        self.score_probe = score_probe
        self.refresh_s = float(refresh_s)
        self.mesh = mesh
        self.gen_slots = int(gen_slots)
        self.gen_max_length = gen_max_length
        self.gen_spec_decode_k = int(gen_spec_decode_k)
        self.gen_draft_mode = str(gen_draft_mode)
        self.gen_prefix_cache_mb = float(gen_prefix_cache_mb)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.trace_requests = bool(trace_requests)
        self.traces = traces
        self._live: "OrderedDict[str, _ManagedModel]" = OrderedDict()
        self._lock = witnessed_rlock("router")
        self._tenants: Dict[str, deque] = {}
        self._tenant_lock = witnessed_lock("router.tenants")
        #: per-tenant quota overrides (demotions): tenant → max
        #: in-flight, applied as a MIN over the configured/cluster
        #: quota in :meth:`_admit_tenant`. Written by
        #: :meth:`demote_tenant` / :meth:`restore_tenant` (the
        #: adaptive-capacity TenantDemoter's knob).
        self.tenant_tiers: Dict[str, int] = {}
        self._last_refresh = time.monotonic()
        self._shutdown = False

    # -- metrics helpers -----------------------------------------------------
    def _counter(self, family: str, name: str, version: int):
        return self.metrics.registry.counter(
            family, "per-version deployment counters",
            labels={"model": name, "version": str(int(version))})

    # -- admission -----------------------------------------------------------
    def _maybe_refresh(self) -> None:
        now = time.monotonic()
        interval = self.refresh_s
        canary_open = False
        if self.cluster is not None:
            with self._lock:
                canary_open = any(mm.canary is not None
                                  for mm in self._live.values())
            if canary_open:
                # tighten the poll while a window is open: a peer's
                # rollback must reach THIS replica within the bench's
                # cross-replica latency bound
                interval = min(interval, self.cluster.canary_refresh_s)
        if now - self._last_refresh < interval:
            return
        self._last_refresh = now
        changed = self.registry.refresh(force=canary_open)
        if self.cluster is not None:
            self.cluster.refresh()
            self._sync_cluster(changed)

    def managed(self, name: str) -> _ManagedModel:
        """The live managed model, admitting (and LRU-evicting) as
        needed. Raises :class:`UnknownModelError` for names the registry
        does not hold. The engine BUILD (checkpoint restore + XLA
        warmup, seconds on a cold model) runs outside the router-wide
        lock so one model's rewarm never stalls traffic to the others;
        a lost build race simply discards the duplicate."""
        from deeplearning4j_tpu.obs import flight as _flight
        from deeplearning4j_tpu.serving.batcher import ServerShutdownError

        with self._lock:
            if self._shutdown:
                raise ServerShutdownError("router is shut down")
            mm = self._live.get(name)
            if mm is not None:
                mm.last_used = time.monotonic()
                self._live.move_to_end(name)
                return mm
            vrec = self.registry.resolve(name)  # typed if unknown/inactive
        t0 = time.monotonic()
        ve = _VersionedEngine(self, name, vrec, role="active")
        with self._lock:
            if self._shutdown:
                ve.retire(drain=False)
                raise ServerShutdownError("router is shut down")
            raced = self._live.get(name)
            if raced is not None:
                ve.retire(drain=False)  # another thread built it first
                return raced
            while len(self._live) >= self.max_live_models:
                evict_name = next(
                    (n for n, m in self._live.items() if m.canary is None),
                    next(iter(self._live)))
                self._evict(evict_name)
            mm = _ManagedModel(name)
            mm.active = ve
            _flight.record("model_rewarm", model=name,
                           version=int(vrec["version"]),
                           wall_ms=round((time.monotonic() - t0) * 1e3, 1))
            self._live[name] = mm
        # a canary that was mid-window when the process died restarts
        # cleanly: the journal kept canary_start, the window restarts
        persisted = self.registry.canary_state(name)
        if persisted is not None:
            with mm.lock:
                cand = self.registry.get(name)["versions"].get(
                    str(persisted["version"]))
                if cand is not None and cand["status"] == "canary":
                    self._start_canary(mm, cand, resumed=True)
        return mm

    def _evict(self, name: str) -> None:
        from deeplearning4j_tpu.obs import flight as _flight

        mm = self._live.pop(name, None)
        if mm is None:
            return
        with mm.lock:
            # retire WITHOUT nulling the references: a thread that
            # grabbed this _ManagedModel before the eviction sees
            # evicted=True (and retries admission) or hits the drained
            # batcher's typed ServerShutdownError — never a None deref
            mm.evicted = True
            if mm.generation is not None:
                gen, mm.generation = mm.generation, None
                threading.Thread(target=gen.shutdown, daemon=True).start()
            if mm.canary_generation is not None:
                cgen, mm.canary_generation = mm.canary_generation, None
                threading.Thread(target=cgen.shutdown,
                                 kwargs={"drain": False},
                                 daemon=True).start()
            if mm.canary is not None:
                # eviction is capacity pressure, not a verdict: the
                # canary record stays in the registry and resumes on
                # rewarm
                mm.canary.retire(drain=True)
                mm.canary = None
            if mm.canary_alerts is not None:
                mm.canary_alerts.shutdown()
                mm.canary_alerts = None
            if mm.active is not None:
                _flight.record("model_evict", model=name,
                               version=mm.active.version)
                mm.active.retire(drain=True)

    # -- capacity surface (the ModelPrewarmer's knobs) -----------------------
    def live_models(self) -> List[str]:
        """Names currently warm, LRU → MRU order."""
        with self._lock:
            return list(self._live)

    def model_idle_s(self, name: str) -> Optional[float]:
        """Seconds since ``name`` last served a request; None when the
        model is not live."""
        with self._lock:
            mm = self._live.get(name)
            return (None if mm is None
                    else max(time.monotonic() - mm.last_used, 0.0))

    def prewarm_model(self, name: str) -> int:
        """Admit (build + warm) ``name`` ahead of predicted load so its
        first real request hits a compiled engine. Returns the active
        version. Typed UnknownModelError when the registry has no such
        model — a forecast must not invent capacity."""
        return self.managed(name).active.version

    def evict_model(self, name: str) -> bool:
        """Release a live model's capacity (predicted-idle eviction).
        Refuses — returns False — while a canary window is open on the
        model (an open verdict outranks a load forecast) or when the
        model is not live. The LRU machinery re-admits on next use."""
        with self._lock:
            mm = self._live.get(name)
            if mm is None or mm.canary is not None:
                return False
            self._evict(name)
            return True

    # -- tenant quotas -------------------------------------------------------
    def tenant_inflight(self) -> Dict[str, int]:
        """Per-tenant in-flight request counts — what this replica's
        cluster heartbeat reports so peers can borrow unused quota."""
        with self._tenant_lock:
            out = {}
            for t, ledger in self._tenants.items():
                n = sum(1 for r in ledger if not r.done())
                if n:
                    out[t] = n
            return out

    def _admit_tenant(self, tenant: str, retry_after: float):
        quota = self.tenant_quota
        if self.cluster is not None:
            # cluster-wide quota: this replica's budget share (fair-
            # share floor + borrow of peers' reported idle capacity)
            budget = self.cluster.tenant_budget(tenant)
            if budget is not None:
                quota = budget if quota is None else min(quota, budget)
        tier = self.tenant_tiers.get(tenant)
        if tier is not None:
            # a demoted tenant's tier binds even when no global quota
            # is configured — demotion must mean something everywhere
            quota = tier if quota is None else min(quota, tier)
        if quota is None:
            return None
        with self._tenant_lock:
            # bound the ledger table: tenant ids come from a
            # client-controlled header, so unique-per-request ids (or
            # natural churn over months) must not grow memory forever
            if len(self._tenants) > 4096:
                self._tenants = {t: d for t, d in self._tenants.items()
                                 if any(not r.done() for r in d)}
            ledger = self._tenants.setdefault(tenant, deque())
            while ledger and ledger[0].done():
                ledger.popleft()
            # opportunistic prune of the middle too (completion order is
            # not FIFO under mixed timeouts)
            if len(ledger) >= quota:
                live = deque(r for r in ledger if not r.done())
                self._tenants[tenant] = ledger = live
            if len(ledger) >= quota:
                from deeplearning4j_tpu.obs import flight as _flight

                self.metrics.registry.counter(
                    "serving_tenant_rejects_total",
                    "per-tenant quota rejections",
                    labels={"tenant": tenant}).inc()
                _flight.record("tenant_reject", tenant=tenant,
                               quota=quota)
                raise TenantQuotaExceededError(
                    f"tenant {tenant!r} has {len(ledger)} requests in "
                    f"flight (quota {quota}); retry with "
                    "backoff — other tenants are unaffected",
                    tenant=tenant, retry_after_s=retry_after)
            return ledger

    def demote_tenant(self, tenant: str, quota: int) -> Optional[int]:
        """Cap ``tenant`` at ``quota`` in-flight requests (a MIN over
        any configured/cluster quota). Returns the previous override
        (None if the tenant was un-demoted). The caller — normally the
        adaptive TenantDemoter — owns recording the controller flight
        event with its triggering verdict."""
        quota = max(int(quota), 1)
        with self._tenant_lock:
            prev = self.tenant_tiers.get(tenant)
            self.tenant_tiers[tenant] = quota
            n = len(self.tenant_tiers)
        self.metrics.registry.gauge(
            "serving_tenants_demoted",
            "tenants currently on a demoted quota tier").set(n)
        return prev

    def restore_tenant(self, tenant: str) -> bool:
        """Lift a tenant's demotion; True if one was in force."""
        with self._tenant_lock:
            had = self.tenant_tiers.pop(tenant, None) is not None
            n = len(self.tenant_tiers)
        self.metrics.registry.gauge(
            "serving_tenants_demoted",
            "tenants currently on a demoted quota tier").set(n)
        return had

    # -- the request path ----------------------------------------------------
    def submit(self, model: str, x, mask=None,
               timeout: Optional[float] = None, tenant: str = "default",
               trace: Optional[bool] = None):
        """Route one request: admit the model, adopt any pending canary,
        pick the version (canary_fraction of traffic to the canary),
        enforce the tenant quota, and submit into that version's
        batcher. Returns the :class:`InferenceRequest` (block on
        ``.result()``; ``.model_version`` is the registry version that
        computed it)."""
        self._maybe_refresh()
        ve = None
        for _ in range(3):
            mm = self.managed(model)
            with mm.lock:
                if mm.evicted:
                    continue  # raced an LRU eviction: re-admit fresh
                self._maybe_adopt(mm)
                self._maybe_promote(mm)
                ve = mm.active
                if mm.canary is not None and self.canary_fraction > 0 \
                        and not mm.canary_suspended:
                    mm.canary_counter += 1
                    every = max(int(round(1.0 / self.canary_fraction)), 1)
                    if mm.canary_counter % every == 0:
                        ve = mm.canary
            break
        if ve is None:
            err = ServerOverloadedError(
                f"model {model!r} kept being evicted under admission "
                "churn; retry")
            err.retry_after_s = 1.0
            raise err
        ledger = self._admit_tenant(tenant, ve.batcher.retry_after_s())
        # per-tenant accepted traffic: the abuse-share signal the
        # TenantDemoter reads (rejects are counted separately above)
        self.metrics.registry.counter(
            "serving_tenant_requests_total",
            "per-tenant accepted requests",
            labels={"tenant": tenant}).inc()
        req = ve.batcher.submit(x, mask, timeout=timeout, trace=trace)
        if ledger is not None:
            with self._tenant_lock:
                ledger.append(req)
        if ve.role == "canary":
            with mm.lock:
                mm.canary_inflight.append(req)
                while mm.canary_inflight and mm.canary_inflight[0].done():
                    mm.canary_inflight.popleft()
        return req

    def predict(self, model: str, x, mask=None,
                timeout: Optional[float] = None, tenant: str = "default",
                trace: Optional[bool] = None):
        """Blocking convenience: ``(outputs, registry_version)``."""
        req = self.submit(model, x, mask, timeout=timeout, tenant=tenant,
                          trace=trace)
        out = req.result(timeout=timeout)
        return out, req.model_version

    def _build_generation(self, base_model, name: str, version: int,
                          role: str, n_slots: Optional[int] = None):
        from deeplearning4j_tpu.serving.generate import GenerationEngine
        from deeplearning4j_tpu.serving.metrics import GenerationMetrics

        gen = GenerationEngine(base_model,
                               n_slots=(self.gen_slots if n_slots is None
                                        else int(n_slots)),
                               max_length=self.gen_max_length,
                               spec_decode_k=self.gen_spec_decode_k,
                               draft_mode=self.gen_draft_mode,
                               prefix_cache_mb=self.gen_prefix_cache_mb,
                               metrics=GenerationMetrics(),
                               traces=self.traces)
        gen.chaos_ctx = {"model": name, "version": int(version),
                         "role": role}
        return gen

    def _managed_for_generation(self, model: str) -> _ManagedModel:
        if self.gen_slots <= 0:
            raise ValueError(
                "router built without generation slots (gen_slots=0)")
        mm = self.managed(model)
        with mm.lock:
            if mm.evicted:
                mm = None
        if mm is None:
            mm = self.managed(model)  # raced an eviction: re-admit
        return mm

    def generation_for(self, model: str):
        """The model's continuous-batching generation engine (lazily
        built over the ACTIVE version's model). Raises TypeError when
        the model has no incremental-decode path, ValueError when the
        router was built with ``gen_slots=0``. Canary-aware generation
        submission goes through :meth:`generation_submit` — this
        accessor always returns the active-version engine."""
        mm = self._managed_for_generation(model)
        with mm.lock:
            return self._ensure_generation(mm)

    def _ensure_generation(self, mm: _ManagedModel):
        # caller holds mm.lock
        if mm.generation is None:
            mm.generation = self._build_generation(
                mm.active.engine.model, mm.name, mm.active.version,
                "active")
        return mm.generation

    def scale_generation_slots(self, model: str, n_slots: int) -> dict:
        """Resize the model's generation slab to ``n_slots`` decode
        slots (the SlotScaler's knob, sized against
        ``generation_memory_report``). The slab's slot count is baked
        into its fixed shapes, so scaling means building and warming a
        FRESH engine — done entirely outside locks (the
        ``_build_canary_generation`` discipline: building under
        ``mm.lock`` would stall the model's traffic for seconds and
        re-close the lock-order cycle the witness flagged), then
        installed under ``mm.lock`` with the old engine drained in the
        background. A lost race (eviction, concurrent scale) discards
        the new engine. Returns ``{slots, previous, changed}``."""
        n_slots = max(int(n_slots), 1)
        mm = self._managed_for_generation(model)
        with mm.lock:
            old = self._ensure_generation(mm)
            if old.n_slots == n_slots:
                return {"slots": n_slots, "previous": n_slots,
                        "changed": False}
            base_model = mm.active.engine.model
            version = mm.active.version
        gen = self._build_generation(base_model, mm.name, version,
                                     "active", n_slots=n_slots)
        gen.warmup()
        stale = None
        with mm.lock:
            if mm.evicted or mm.generation is not old:
                stale = gen  # raced an eviction or another scaler: lose
            else:
                mm.generation = gen
                stale = old
        prev = old.n_slots
        changed = stale is old
        if stale is not None:
            threading.Thread(
                target=stale.shutdown,
                kwargs={"drain": changed},  # drain the replaced engine's
                # in-flight decodes; a discarded NEW engine has none
                daemon=True).start()
        return {"slots": n_slots if changed else prev,
                "previous": prev, "changed": changed}

    def _build_canary_generation(self, mm: _ManagedModel, base_model,
                                 version: int) -> None:
        """Build+warm the canary's generation engine with NO locks
        held, then install it under ``mm.lock`` — the caller set
        ``canary_gen_building`` under the lock, so exactly one builder
        runs. Building under ``mm.lock`` would (a) stall every
        predict/generate for the model behind seconds of slab compiles
        and (b) close a lock-order cycle against the decode worker,
        which holds the engine DEVICE lock when its completion
        observers take ``mm.lock`` — the ABBA pattern the lock witness
        (obs/lockwitness.py) flagged the moment it armed over this
        drill. A model whose candidate cannot decode (arch change)
        records the fact once and serves generation from the active
        version only (the canary then needs /predict traffic to
        promote)."""
        from deeplearning4j_tpu.obs import flight as _flight

        gen = None
        try:
            gen = self._build_generation(base_model, mm.name, version,
                                         "canary")
            gen.warmup()
        except Exception as e:  # noqa: BLE001 — a candidate that
            # cannot even build its decode slab must not take down
            # generation serving; it simply gets no generation
            # traffic (and no generation votes in the gate)
            with mm.lock:
                # poison only the window we were building for: if it
                # already tripped/promoted and a NEW canary opened,
                # this stale failure must not cost the new candidate
                # its generation votes
                if (mm.canary is not None
                        and mm.canary.version == version):
                    mm.canary_gen_failed = True
                mm.canary_gen_building = False
            _flight.record("canary_generation_unavailable",
                           model=mm.name, version=version,
                           error=type(e).__name__,
                           message=str(e)[:200])
            return
        stale = None
        with mm.lock:
            mm.canary_gen_building = False
            if (mm.canary is not None and mm.canary.version == version
                    and mm.canary_generation is None):
                mm.canary_generation = gen
            else:
                # the window closed (trip/promote/evict) while we were
                # warming: discard the engine OUTSIDE the lock
                stale = gen
        if stale is not None:
            stale.shutdown(drain=False, timeout=5.0)

    def generation_submit(self, model: str, prompt_ids, **kwargs):
        """Submit one generation request with canary routing: while a
        canary window is open, ``canary_fraction`` of the model's
        /generate traffic decodes on the candidate version's own
        engine, and EVERY generation completion (either version) feeds
        the per-version ``registry_version_gen_*`` counters the metric
        gate reads — so a snapshot that only regresses under generation
        traffic still trips auto-rollback (the PR 11 residue). Returns
        the :class:`~.generate.GenerationRequest`."""
        mm = self._managed_for_generation(model)
        build_spec = None
        with mm.lock:
            self._maybe_adopt(mm)
            self._maybe_promote(mm)
            gen = self._ensure_generation(mm)
            ve = mm.active
            if mm.canary is not None and self.canary_fraction > 0 \
                    and not mm.canary_suspended:
                cgen = mm.canary_generation
                if (cgen is None and not mm.canary_gen_failed
                        and not mm.canary_gen_building):
                    # first /generate of an open window: claim the
                    # build under the lock, run it AFTER release (see
                    # _build_canary_generation — lock-order + latency)
                    mm.canary_gen_building = True
                    build_spec = (mm.canary.engine.model,
                                  mm.canary.version)
                if cgen is not None:
                    mm.gen_counter += 1
                    every = max(int(round(1.0 / self.canary_fraction)), 1)
                    if mm.gen_counter % every == 0:
                        gen, ve = cgen, mm.canary
        if build_spec is not None:
            # this request still decodes on the active version; the
            # canary starts taking its fraction from the NEXT submit,
            # once the warm engine is installed (the documented
            # lazily-built semantics)
            self._build_canary_generation(mm, *build_spec)
        # the observer rides in through submit so it is installed
        # BEFORE the request is enqueued — a completion racing the
        # submit return (instant canary decode failure, already-expired
        # deadline) must still be counted by the metric gate
        t0 = time.monotonic()
        return gen.submit(prompt_ids,
                          on_done=self._make_gen_observer(model, ve, t0),
                          **kwargs)

    def _make_gen_observer(self, name: str, ve: _VersionedEngine,
                           t0: float):
        from deeplearning4j_tpu.serving.batcher import (
            ServerShutdownError,
        )

        def on_done(req, error):
            dt = time.monotonic() - t0
            if error is None:
                ve.stats.gen_requests += 1
                ve.stats.gen_latency_sum += dt
                self._counter("registry_version_gen_requests_total",
                              name, ve.version).inc()
                self._counter(
                    "registry_version_gen_latency_seconds_total",
                    name, ve.version).inc(dt)
                if ve.role == "canary":
                    # off-thread: on_done runs on the decode worker
                    # UNDER the engine's device lock, and a promotion
                    # here does journal fsyncs + a snapshot rewrite —
                    # disk I/O that must not stall every decode slot.
                    # (The error-path trip below stays inline: it is
                    # terminal for these slots anyway and must be
                    # prompt.)
                    threading.Thread(target=self._evaluate_canary,
                                     args=(name,), daemon=True,
                                     name=f"canary-eval-{name}").start()
                return
            if isinstance(error, (ServerShutdownError,
                                  ServerOverloadedError,
                                  CanaryRolledBackError)):
                return  # admission/lifecycle, not the version's fault
            ve.stats.gen_errors += 1
            self._counter("registry_version_gen_errors_total",
                          name, ve.version).inc()
            if ve.role != "canary" or ve.dead:
                return
            if isinstance(error, RequestDeadlineExceeded):
                # a caller-side deadline is ambiguous (tight client
                # timeout vs slow canary) — count it and let the
                # latency/score legs decide
                self._evaluate_canary(name)
            else:
                # decode failure / watchdog stall on the candidate:
                # the bad version must not get more traffic
                self._trip(name, ve,
                           f"generation dispatch failure: "
                           f"{type(error).__name__}")

        return on_done

    # -- canary state machine ------------------------------------------------
    def _maybe_adopt(self, mm: _ManagedModel) -> None:
        """Start a canary for a newly validated version (the serve-side
        half of the continuous loop: the trainer publishes, the router
        notices here). Adoption is synchronous under ``mm.lock``: the
        ONE request that notices the new version pays the canary
        engine's build+warmup (and concurrent requests for this model
        wait on the lock) — the deliberate trade for a state machine
        with no background thread; canary-ROUTED traffic afterwards
        never absorbs a compile (the engine is warm before the first
        slice of traffic reaches it)."""
        if mm.canary is not None or self._shutdown:
            return
        cand = self.registry.candidate(mm.name)
        if cand is None:
            return
        self._start_canary(mm, cand, resumed=False)

    def _start_canary(self, mm: _ManagedModel, vrec: dict,
                      resumed: bool) -> None:
        from deeplearning4j_tpu.obs import flight as _flight

        try:
            ve = _VersionedEngine(self, mm.name, vrec, role="canary")
        except Exception as e:  # noqa: BLE001 — a snapshot that cannot
            # even build an engine must roll back, not kill serving
            self.registry.rollback(mm.name, int(vrec["version"]),
                                   f"engine build failed: "
                                   f"{type(e).__name__}: {e}")
            _flight.record("regression_trip", model=mm.name,
                           version=int(vrec["version"]),
                           reason=f"engine build failed: {type(e).__name__}")
            _flight.record("rollback", model=mm.name,
                           version=int(vrec["version"]),
                           active_version=mm.active.version)
            return
        mm.canary = ve
        mm.canary_started = time.monotonic()
        mm.canary_counter = 0
        mm.canary_inflight.clear()
        mm.canary_suspended = False
        if self.cluster is not None:
            # bid for the window's controller lease; losing is fine —
            # this replica then serves its canary slice, journals gate
            # snapshots, and the lease holder decides
            self.cluster.ensure_lease(mm.name)
        # the gate as declarative rules in the shared alert engine (ONE
        # evaluation mechanism with the SLO pack): signals close over
        # the live per-version stats and reproduce the PR 11 gate's
        # comparisons and reason strings exactly; the evaluator
        # contributes the state machine, alert_* flight forensics and
        # alert_firing gauges
        from deeplearning4j_tpu.obs.alerts import AlertEvaluator
        from deeplearning4j_tpu.obs.slo import canary_gate_rules

        # cluster mode evaluates the SAME rules over a duck-typed view
        # whose per-version stats are CLUSTER-merged (local live
        # counters + peers' journaled gate snapshots): a regression any
        # replica observes reaches the controller's tick
        gate_subject = (mm if self.cluster is None
                        else self.cluster.gate_view(mm))
        mm.canary_alerts = AlertEvaluator(
            canary_gate_rules(gate_subject,
                              self.registry.higher_is_better,
                              self.latency_trip_mult,
                              self.latency_trip_min_samples,
                              self.score_trip_tolerance),
            registry=self.metrics.registry,
            context={"model": mm.name, "version": ve.version},
            min_tick_interval=0.0)
        if not resumed:
            self.registry.start_canary(mm.name, ve.version,
                                       self.canary_fraction,
                                       self.canary_window_s)
        _flight.record("canary_start", model=mm.name, version=ve.version,
                       fraction=self.canary_fraction,
                       window_s=self.canary_window_s,
                       resumed=bool(resumed))
        if self.score_probe is not None:
            # the held-out validation step re-run against the LIVE
            # canary engine — the score leg of the gate without any
            # external feeder
            try:
                c_score = float(self.score_probe(ve.engine))
                a_score = (mm.active.stats.score
                           if mm.active.stats.score is not None
                           else (self.score_probe(mm.active.engine)
                                 if mm.active is not None else None))
            except Exception as e:  # noqa: BLE001 — a broken probe is a
                # trip, not a crash: refusing to score IS a red flag
                self._trip(mm.name, ve,
                           f"score probe failed: {type(e).__name__}: {e}")
                return
            self.record_score(mm.name, ve.version, c_score)
            if a_score is not None:
                mm.active.stats.observe_score(float(a_score))
            self._evaluate_canary(mm.name)

    def record_score(self, model: str, version: int, value: float) -> None:
        """Post a quality score for a version (probes, external
        evaluators). Feeds the canary score gate; mirrored into the
        shared metrics registry."""
        mm = self._live.get(model)
        if mm is None:
            return
        with mm.lock:
            for ve in (mm.active, mm.canary):
                if ve is not None and ve.version == int(version):
                    ve.stats.observe_score(float(value))
                    self.metrics.registry.gauge(
                        "registry_version_score",
                        "latest quality score per served version",
                        labels={"model": model,
                                "version": str(int(version))}
                    ).set(float(ve.stats.score))
        self._evaluate_canary(model)

    def _evaluate_canary(self, name: str) -> None:
        """The metric gate: called on canary completions, score posts,
        and submissions. One evaluator tick over the window's gate
        rules (score / latency / generation latency, in the original
        evaluation order — obs/slo.canary_gate_rules); the first firing
        rule trips with its rule-rendered reason. Promotes once the
        window has elapsed with enough clean traffic."""
        mm = self._live.get(name)
        if mm is None:
            return
        with mm.lock:
            ve = mm.canary
            if ve is None or ve.dead:
                return
            if self.cluster is not None:
                # fold OUT first: journal this replica's per-version
                # observations so every peer's next tick sees them
                self.cluster.journal_gate(name, ve.version, "canary",
                                          ve.stats)
                if mm.active is not None:
                    self.cluster.journal_gate(name, mm.active.version,
                                              "active", mm.active.stats)
                if not self.cluster.ensure_lease(name):
                    return  # a live peer holds the controller lease
                if mm.canary_suspended:
                    # this replica observed the failure while a peer
                    # held the lease (fence refused its inline trip);
                    # now IT is the controller — the suspended canary
                    # trips immediately
                    self._trip(name, ve,
                               "canary dispatch failures observed "
                               "while a peer held the controller lease")
                    return
                # a dispatch failure a PEER journaled is ground truth
                # (its own inline trip was refused by the fence): the
                # bad version must not get more cluster traffic
                peer_fail = self.cluster.peer_failures(name, ve.version)
                if peer_fail:
                    self._trip(name, ve,
                               f"peer-observed canary dispatch "
                               f"failures ({peer_fail})")
                    return
            ev = mm.canary_alerts
            if ev is not None:
                for st in ev.tick():
                    if st["state"] == "firing":
                        self._trip(name, ve, st["reason"])
                        return
            # promotion: bounded window elapsed, enough canary traffic
            # (predict AND generation requests both count — a model
            # serving only /generate must still be able to promote; in
            # cluster mode the CLUSTER-wide canary traffic counts),
            # nothing tripped
            st = (ve.stats if self.cluster is None
                  else self.cluster.merged_stats(name, ve))
            if (mm.canary_started is not None
                    and time.monotonic() - mm.canary_started
                    >= self.canary_window_s
                    and st.requests + st.gen_requests
                    >= self.canary_min_requests):
                self._promote(mm)

    def _maybe_promote(self, mm: _ManagedModel) -> None:
        """Submission-path promotion poke (completions may have stopped
        exactly at the window edge)."""
        if mm.canary is not None and not mm.canary.dead:
            self._evaluate_canary(mm.name)

    def _promote(self, mm: _ManagedModel) -> None:
        from deeplearning4j_tpu.obs import flight as _flight

        with mm.lock:
            ve, old = mm.canary, mm.active
            if ve is None:
                return
            if self.cluster is not None:
                from deeplearning4j_tpu.serving.cluster import (
                    StaleEpochError,
                )

                try:
                    # the epoch fence: a stale ex-holder (paused,
                    # skewed) must not journal a promote the current
                    # controller did not make
                    self.cluster.fence(mm.name)
                except StaleEpochError:
                    return  # the holder's verdict arrives via the WAL
            mm.canary = None
            mm.canary_started = None
            mm.canary_inflight.clear()
            if mm.canary_alerts is not None:
                mm.canary_alerts.shutdown()
                mm.canary_alerts = None
            mm.active = ve
            ve.role = "active"
            self.registry.promote(mm.name, ve.version)
            _flight.record("promote", model=mm.name, version=ve.version,
                           requests=ve.stats.requests,
                           gen_requests=ve.stats.gen_requests,
                           mean_latency_ms=None
                           if ve.stats.mean_latency() is None
                           else round(ve.stats.mean_latency() * 1e3, 2))
            if old is not None:
                # drain: in-flight old-version requests all complete —
                # the no-mixing/no-dropping guarantee under promotion
                old.retire(drain=True)
            self._adopt_promoted_generation(mm, old)

    def _adopt_promoted_generation(self, mm: _ManagedModel,
                                   old: Optional["_VersionedEngine"]
                                   ) -> None:
        # caller holds mm.lock and has already made mm.active the
        # promoted engine
        if mm.canary_generation is not None:
            # the canary's warmed decode engine IS the promoted
            # version's engine — adopt it (already on the new
            # weights, zero recompiles) and retire the old one
            old_gen, mm.generation = mm.generation, mm.canary_generation
            mm.canary_generation = None
            mm.canary_gen_failed = False
            mm.generation.chaos_ctx["role"] = "active"
            if old_gen is not None:
                threading.Thread(target=old_gen.shutdown,
                                 daemon=True).start()
        else:
            mm.canary_gen_failed = False
            self._sync_generation(mm, old)

    def _sync_generation(self, mm: _ManagedModel,
                         old: Optional[_VersionedEngine]) -> None:
        """Point the model's generation engine at the promoted weights.
        Same architecture → atomic params swap on the bound model object
        (the jitted decode programs read ``params_`` per dispatch, so
        the swap takes effect at the next token, zero recompiles);
        different architecture → retire and rebuild lazily."""
        gen = mm.generation
        if gen is None:
            return
        new_model = mm.active.engine.model
        old_conf = getattr(getattr(gen.backend.model, "conf", None),
                           "to_json", lambda: None)()
        new_conf = getattr(getattr(new_model, "conf", None),
                           "to_json", lambda: None)()
        if old_conf is not None and old_conf == new_conf:
            gen.backend.model.params_ = new_model.params_
            gen.backend.model.state_ = new_model.state_
        else:
            mm.generation = None
            threading.Thread(target=gen.shutdown, daemon=True).start()

    def _trip(self, name: str, ve: _VersionedEngine, reason: str) -> None:
        """Regression trip → rollback. Outstanding canary requests are
        failed typed FIRST (first-wins — a racing completion of the bad
        version becomes a no-op for any request we fail here), then the
        canary engine is retired and the registry records the rollback.
        The active version is untouched throughout."""
        from deeplearning4j_tpu.obs import flight as _flight

        mm = self._live.get(name)
        if mm is None:
            return
        if self.cluster is not None:
            from deeplearning4j_tpu.serving.cluster import StaleEpochError

            try:
                # same fence as promote: only the current lease holder
                # journals a rollback. A non-holder that observed the
                # failure suspends its local canary routing and journals
                # the failure urgently so the holder's next tick trips.
                self.cluster.fence(name)
            except StaleEpochError:
                self._suspend_canary(mm, ve, reason)
                return
        with mm.lock:
            if mm.canary is not ve or ve.dead:
                return  # already tripped / promoted
            ve.dead = True
            mm.canary = None
            mm.canary_started = None
            if mm.canary_alerts is not None:
                mm.canary_alerts.shutdown()
                mm.canary_alerts = None
            if mm.canary_generation is not None:
                # fail the candidate's in-flight generation requests
                # typed and tear its slab down off-thread (shutdown
                # joins the decode worker)
                cgen, mm.canary_generation = mm.canary_generation, None
                threading.Thread(target=cgen.shutdown,
                                 kwargs={"drain": False},
                                 daemon=True).start()
            mm.canary_gen_failed = False
            _flight.record("regression_trip", model=name,
                           version=ve.version, reason=reason,
                           canary_requests=ve.stats.requests,
                           canary_errors=ve.stats.errors,
                           canary_gen_requests=ve.stats.gen_requests,
                           canary_gen_errors=ve.stats.gen_errors)
            err = CanaryRolledBackError(
                f"{name} v{ve.version} rolled back: {reason}; retry — "
                "the active version is serving")
            while mm.canary_inflight:
                req = mm.canary_inflight.popleft()
                req.fail(err)
            self.registry.rollback(name, ve.version, reason)
            _flight.record("rollback", model=name, version=ve.version,
                           active_version=None if mm.active is None
                           else mm.active.version)
            ve.retire(drain=False)

    # -- cluster sync --------------------------------------------------------
    def _suspend_canary(self, mm: _ManagedModel, ve: _VersionedEngine,
                        reason: str) -> None:
        """Non-holder observed a canary failure but the epoch fence
        refused its trip: stop routing local traffic to the candidate
        and journal the evidence urgently. The lease holder's next gate
        tick sees the peer failures and trips the CLUSTER rollback."""
        from deeplearning4j_tpu.obs import flight as _flight

        with mm.lock:
            if mm.canary is not ve or ve.dead or mm.canary_suspended:
                return
            mm.canary_suspended = True
            _flight.record("canary_suspend", model=mm.name,
                           version=ve.version, reason=reason)
        if self.cluster is not None:
            self.cluster.journal_gate(mm.name, ve.version, "canary",
                                      ve.stats, urgent=True)

    def _sync_cluster(self, registry_changed: bool) -> None:
        """Post-refresh reconciliation against the shared registry +
        cluster journal: apply peers' rollback/promote decisions
        locally, adopt canaries peers opened, and give the lease holder
        its gate tick (liveness-driven — no request traffic needed to
        steal a dead holder's lease)."""
        with self._lock:
            mms = list(self._live.values())
        for mm in mms:
            try:
                self._sync_cluster_model(mm)
            except (RegistryError, OSError):
                continue  # transient — next refresh retries

    def _sync_cluster_model(self, mm: _ManagedModel) -> None:
        try:
            reg = self.registry.get(mm.name)
        except UnknownModelError:
            return
        with mm.lock:
            if mm.evicted:
                return
            ve = mm.canary
            if ve is not None and not ve.dead:
                vr = reg.get("versions", {}).get(str(ve.version))
                status = None if vr is None else vr.get("status")
                if status == "rolled_back":
                    # a peer (the lease holder) tripped: tear down the
                    # local candidate without journaling a second
                    # rollback
                    self._apply_remote_rollback(mm, ve)
                elif (status == "active"
                      and reg.get("active_version") == ve.version):
                    self._apply_remote_promote(mm, ve)
            elif ve is None and not self._shutdown:
                cand = reg.get("canary")
                if (cand is not None
                        and mm.active is not None
                        and int(cand["version"]) != mm.active.version):
                    vrec = reg.get("versions", {}).get(
                        str(int(cand["version"])))
                    if vrec is not None \
                            and vrec.get("status") == "canary":
                        # a peer opened a canary window — adopt it so
                        # this replica's traffic share feeds the
                        # cluster gate
                        self._start_canary(mm, vrec, resumed=True)
        if mm.canary is not None:
            # the holder's poll tick: liveness/steal/peer-failure
            # evaluation must not wait for local canary traffic
            self._evaluate_canary(mm.name)

    def _apply_remote_rollback(self, mm: _ManagedModel,
                               ve: _VersionedEngine) -> None:
        """Caller holds mm.lock. Mirror of _trip's teardown minus the
        registry write and rollback event — the holder already
        journaled both; this replica only applies the verdict."""
        from deeplearning4j_tpu.obs import flight as _flight

        ve.dead = True
        mm.canary = None
        mm.canary_started = None
        mm.canary_suspended = False
        if mm.canary_alerts is not None:
            mm.canary_alerts.shutdown()
            mm.canary_alerts = None
        if mm.canary_generation is not None:
            cgen, mm.canary_generation = mm.canary_generation, None
            threading.Thread(target=cgen.shutdown,
                             kwargs={"drain": False},
                             daemon=True).start()
        mm.canary_gen_failed = False
        _flight.record("cluster_rollback_applied", model=mm.name,
                       version=ve.version)
        err = CanaryRolledBackError(
            f"{mm.name} v{ve.version} rolled back cluster-wide; retry "
            "— the active version is serving")
        while mm.canary_inflight:
            req = mm.canary_inflight.popleft()
            req.fail(err)
        ve.retire(drain=False)

    def _apply_remote_promote(self, mm: _ManagedModel,
                              ve: _VersionedEngine) -> None:
        """Caller holds mm.lock. Mirror of _promote minus the registry
        write and promote event (the holder journaled them)."""
        from deeplearning4j_tpu.obs import flight as _flight

        old = mm.active
        mm.canary = None
        mm.canary_started = None
        mm.canary_suspended = False
        mm.canary_inflight.clear()
        if mm.canary_alerts is not None:
            mm.canary_alerts.shutdown()
            mm.canary_alerts = None
        mm.active = ve
        ve.role = "active"
        _flight.record("cluster_promote_applied", model=mm.name,
                       version=ve.version)
        if old is not None:
            old.retire(drain=True)
        self._adopt_promoted_generation(mm, old)

    # -- introspection -------------------------------------------------------
    def healthz(self, name: str) -> dict:
        """Per-model readiness: active/canary versions, warm state,
        compile counts — the keys rollout tooling watches."""
        self._maybe_refresh()
        reg = self.registry.get(name)
        out = {"model": name,
               "active_version": reg.get("active_version"),
               "canary": reg.get("canary"),
               "live": False, "ready": False}
        mm = self._live.get(name)
        if mm is not None and mm.active is not None:
            info = mm.active.engine.describe()
            out.update(live=True, ready=bool(info.get("warm")),
                       warm=info.get("warm"),
                       checkpoint_fingerprint=info.get(
                           "checkpoint_fingerprint"),
                       compile_count=info.get("compile_count"),
                       queue_depth=mm.active.batcher.queue_depth())
            if mm.canary is not None:
                out["canary_live"] = {
                    "version": mm.canary.version,
                    "requests": mm.canary.stats.requests,
                    "errors": mm.canary.stats.errors,
                    "warm": mm.canary.engine.warm,
                }
        return out

    def describe(self) -> dict:
        with self._lock:
            live = {name: {
                "active_version": None if mm.active is None
                else mm.active.version,
                "canary_version": None if mm.canary is None
                else mm.canary.version,
                "queue_depth": 0 if mm.active is None
                else mm.active.batcher.queue_depth(),
            } for name, mm in self._live.items()}
        out = {"models": self.registry.models(), "live": live,
               "max_live_models": self.max_live_models,
               "tenant_quota": self.tenant_quota,
               "canary_fraction": self.canary_fraction,
               "canary_window_s": self.canary_window_s}
        if self.cluster is not None:
            out["cluster"] = self.cluster.describe()
        return out

    def queue_depth(self) -> int:
        with self._lock:
            depth = 0
            for mm in self._live.values():
                for ve in (mm.active, mm.canary):
                    if ve is not None:
                        depth += ve.batcher.queue_depth()
            return depth

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            names = list(self._live)
        for name in names:
            mm = self._live.get(name)
            if mm is None:
                continue
            # detach under mm.lock, tear down OUTSIDE it: shutdown
            # joins engine workers, and a canary completion observer
            # running ON such a worker takes mm.lock
            # (_evaluate_canary/_trip) — joining it while holding the
            # lock would deadlock. Synchronous drains are fine here
            # (shutdown runs on a caller thread, never a worker).
            with mm.lock:
                gen, mm.generation = mm.generation, None
                cgen, mm.canary_generation = mm.canary_generation, None
                canary, mm.canary = mm.canary, None
                active, mm.active = mm.active, None
                if canary is not None:
                    canary.dead = True
                if mm.canary_alerts is not None:
                    mm.canary_alerts.shutdown()
                    mm.canary_alerts = None
            if cgen is not None:
                cgen.shutdown(drain=False)
            if gen is not None:
                gen.shutdown(drain=True)
            if canary is not None:
                canary.batcher.shutdown(drain=True)
            if active is not None:
                active.batcher.shutdown(drain=True)
        with self._lock:
            self._live.clear()
