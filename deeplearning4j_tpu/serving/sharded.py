"""Mesh-sharded serving: tensor-parallel inference + generation on a
2-D (batch, model) ServingMesh.

Both engines here are thin placement layers over the existing serving
stack — **pure-auto GSPMD**, no shard_map, no manual collectives:

- :class:`ShardedInferenceEngine` overrides exactly two seams of
  :class:`InferenceEngine`: snapshot construction (params placed per a
  :class:`ShardingPolicy` instead of replicated) and the raw dispatch
  (batch-sharded input + the ``serving.sharded_dispatch`` chaos seam +
  mesh-loss fallback). Everything else — buckets, warmup, hot reload,
  int8 refusal, registry/canary routing — is inherited unchanged,
  which is the point: the registry's canary machinery promotes and
  rolls back sharded candidates without knowing they are sharded.
- :class:`ShardedGenerationEngine` policy-places the model's params
  *before* the decode backend compiles, then re-places the KV slab
  sharded (slots over "batch", attention heads over "model"). The
  backend's jitted programs read params and slab as *arguments* with
  donation, so the sharded layouts flow through every dispatch and
  steady-state decode never retraces (``trace_counts`` is the
  instrument, same as solo).

Mesh-loss handling: a sharded dispatch that fails (device subset gone,
injected fault) raises a typed :class:`ShardedMeshError` AND arms a
solo fallback — the snapshot's params are gathered onto one surviving
device and every subsequent request serves there (slower, alive). The
``sharded_fallback`` flight event + ``sharded_serving_fallback`` alert
make the degraded mode loud; a canary running sharded trips the normal
rollback on the same failure (ANY canary dispatch error already does).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from deeplearning4j_tpu.parallel import reshard as _reshard
from deeplearning4j_tpu.parallel.serving_mesh import (
    ServingMesh,
    ShardingPolicy,
    ShardingPolicyError,
    policy_for,
    reshard_to_policy,
    validate_policy,
)
from deeplearning4j_tpu.serving.batcher import ServingError
from deeplearning4j_tpu.serving.engine import InferenceEngine, _Snapshot


class ShardedMeshError(ServingError):
    """A sharded dispatch failed mid-serve (device subset lost, runtime
    fault at the mesh seam). The engine has already armed its solo
    fallback when this reaches a caller — retrying the request serves
    degraded instead of failing again."""


class ShardedInferenceEngine(InferenceEngine):
    """:class:`InferenceEngine` whose snapshots live TP-sharded on a
    2-D (batch, model) :class:`ServingMesh`.

    ``mesh`` must be a ServingMesh (the ``n_data`` batch axis drives
    bucket divisibility exactly as before). ``policy`` defaults to the
    model's registry entry (``serving_mesh.policy_for``); validation —
    axis divisibility AND the per-device memory gate — happens at every
    snapshot build, so a reload to an incompatible checkpoint is a
    typed refusal with the old snapshot still serving.
    """

    def __init__(self, model, buckets=None, mesh=None, checkpoint_dir=None,
                 metrics=None, int8_serving: bool = False,
                 policy: Optional[ShardingPolicy] = None,
                 policy_overrides=None):
        if mesh is None or not hasattr(mesh, "n_model"):
            raise ShardingPolicyError(
                "ShardedInferenceEngine needs a ServingMesh (got "
                f"{type(mesh).__name__}); for replicated serving use "
                "InferenceEngine")
        if int8_serving:
            raise ShardingPolicyError(
                "int8_serving composes with replicated snapshots only; "
                "a TP policy would shard per-channel scales — serve "
                "sharded fp32 or solo int8, not both")
        self.policy = (policy if policy is not None
                       else policy_for(model, policy_overrides))
        #: memory-gate report of the LIVE snapshot's placement
        self.shard_report: Optional[dict] = None
        #: (params, state) gathered onto one device after a mesh loss;
        #: None while the mesh serves healthy
        self._solo = None
        super().__init__(model, buckets=buckets, mesh=mesh,
                         checkpoint_dir=checkpoint_dir, metrics=metrics,
                         int8_serving=False)

    # -- construction -------------------------------------------------------
    @classmethod
    def from_checkpoint(cls, source: str, **kwargs):
        """Reshard-on-load: any checkpoint topology → this serving
        mesh. Same resolution/validation/fallback chain as the base
        engine; the reshard event reports N→M with M = the FULL mesh
        device count (a 2x4 mesh is 8 devices, not 2 replicas)."""
        import os

        from deeplearning4j_tpu.serving.engine import (
            resolve_checkpoint_source,
        )
        from deeplearning4j_tpu.train.model_serializer import (
            ModelGuesser,
            ModelSerializer,
        )

        path = resolve_checkpoint_source(source)
        topo = ModelSerializer.checkpoint_meta(path).get("topology") or {}
        n_from = topo.get("n_devices")
        model = ModelGuesser.load_model_guess(path)
        if os.path.isdir(source):
            kwargs.setdefault("checkpoint_dir", source)
        mesh = kwargs.get("mesh")
        n_to = mesh.n_devices if mesh is not None else 1
        with _reshard.reshard_event(n_from, n_to, surface="serving") as st:
            eng = cls(model, **kwargs)
            if eng.reshard_stats is not None:
                st.merge(eng.reshard_stats)
        eng._snap.source = path
        eng._fingerprint = cls._path_fingerprint(path)
        from deeplearning4j_tpu.obs import flight as _flight

        _flight.record("checkpoint_load", path=str(path), surface="serving")
        return eng

    # -- snapshot construction ----------------------------------------------
    def _build_snapshot(self, model, version: int, source) -> _Snapshot:
        from deeplearning4j_tpu.obs import flight as _flight

        conf = getattr(model, "conf", None)
        conf_json = conf.to_json() if hasattr(conf, "to_json") else None
        fn = self._build_fn(model)
        if fn is None:
            raise ShardingPolicyError(
                f"{type(model).__name__} serves through the generic "
                "output path (no functional _forward); tensor-parallel "
                "placement needs params to flow through jit as arguments")
        _flight.record("mesh_build", surface="serving",
                       batch=self.mesh.n_data, model=self.mesh.n_model,
                       n_devices=self.mesh.n_devices,
                       policy=self.policy.name)
        report = validate_policy(model.params_, self.mesh, self.policy,
                                 conf=conf)
        stats = _reshard.TransferStats()
        reshard_to_policy(model, self.mesh, self.policy, stats)
        self.reshard_stats = stats
        self.shard_report = report
        _flight.record("shard_load", surface="serving",
                       policy=self.policy.name, version=int(version),
                       total_bytes=report["total_bytes"],
                       per_device_bytes=report["per_device_bytes"],
                       replicated_bytes=report["replicated_bytes"],
                       device_bytes=int(stats.device_bytes),
                       host_bytes=int(stats.host_bytes))
        # a fresh snapshot serves the full mesh again (a reload is the
        # operator's recovery action after a fallback)
        self._solo = None
        return _Snapshot(model, fn, conf_json, version, source)

    # -- dispatch -----------------------------------------------------------
    @property
    def fallback_active(self) -> bool:
        """True once a mesh loss demoted this engine to one device."""
        return self._solo is not None

    def describe(self) -> dict:
        d = super().describe()
        d["mesh"] = dict(self.mesh.shape)
        d["policy"] = self.policy.describe()
        d["shard_report"] = self.shard_report
        d["fallback_active"] = self.fallback_active
        return d

    def _activate_fallback(self, snap: _Snapshot, reason: str) -> None:
        """Gather the live snapshot onto one device and route every
        later dispatch there. The gather is a device→device copy of
        whatever shards still respond; the first solo dispatch retraces
        (params changed sharding) — loud by design, the retrace event
        sits next to the fallback in the flight recorder."""
        from deeplearning4j_tpu.obs import flight as _flight

        dev = self.mesh.devices_flat()[0]
        sh = jax.sharding.SingleDeviceSharding(dev)
        params = jax.device_put(snap.params, sh)
        state = (jax.device_put(snap.state, sh)
                 if snap.state is not None else None)
        self._solo = (params, state)
        _flight.record("sharded_fallback", surface="serving",
                       reason=reason, batch=self.mesh.n_data,
                       model=self.mesh.n_model,
                       device=str(dev))

    def _forward_raw(self, snap: _Snapshot, xp, mp=None) -> np.ndarray:
        solo = self._solo
        if solo is not None:
            params, state = solo
            return snap.fn(params, state, xp, mp)
        from deeplearning4j_tpu.chaos import hooks as chaos_hooks

        try:
            chaos_hooks.fire("serving.sharded_dispatch",
                             batch=self.mesh.n_data,
                             model=self.mesh.n_model)
            xd = jax.device_put(xp, self.mesh.batch_sharded())
            md = (jax.device_put(mp, self.mesh.batch_sharded())
                  if mp is not None else None)
            return snap.fn(snap.params, snap.state, xd, md)
        except (ShardingPolicyError, TypeError):
            raise
        except Exception as e:  # noqa: BLE001 — any mesh/runtime fault
            self._activate_fallback(snap, reason=type(e).__name__)
            raise ShardedMeshError(
                f"sharded dispatch on mesh {self.mesh.shape} failed "
                f"({type(e).__name__}: {e}); solo fallback armed — "
                "subsequent requests serve on one device") from e


class ShardedGenerationEngine:
    """Factory wrapper: a :class:`GenerationEngine` decoding TP-sharded.

    Construction order matters and is all this class adds: (1) validate
    the mesh divides the model (heads, vocab, feature dim, slots), (2)
    policy-place ``model.params_`` — the backend's jitted decode/prefill
    programs take params per call, so they compile partitioned from the
    first dispatch, (3) build the normal engine, (4) re-place the KV
    slab sharded ``P(None, "batch", "model", None, None)`` — slots over
    the batch axis, attention heads over the model axis — and keep it
    that way across ``backend.reset()`` (decode-failure recovery
    rebuilds the slab; the wrap re-shards it before the next dispatch).

    Use :func:`sharded_generation_engine`; instances ARE
    GenerationEngines (every queue/slot/watchdog/speculation behavior
    inherited by construction, not reimplementation).
    """

    def __new__(cls, *a, **kw):  # pragma: no cover — factory only
        raise TypeError("use sharded_generation_engine(...)")


def _validate_generation_mesh(model, mesh: ServingMesh,
                              n_slots: int) -> None:
    cfg = getattr(model, "cfg", None)
    if cfg is None or not hasattr(cfg, "n_heads"):
        raise ShardingPolicyError(
            f"sharded generation needs a TransformerLM (got "
            f"{type(model).__name__}); recurrent decode backends serve "
            "solo")
    nm, nb = mesh.n_model, mesh.n_data
    checks = [("n_heads", cfg.n_heads, nm), ("d_model", cfg.d_model, nm),
              ("vocab_size", cfg.vocab_size, nm), ("n_slots", n_slots, nb)]
    bad = [f"{name}={val} % {div}" for name, val, div in checks
           if val % div]
    if bad:
        raise ShardingPolicyError(
            f"mesh {mesh.shape} does not divide the model/slab: "
            + ", ".join(bad))


def sharded_generation_engine(model, mesh: ServingMesh,
                              policy: Optional[ShardingPolicy] = None,
                              **kwargs):
    """Build a :class:`GenerationEngine` whose params and KV slab live
    sharded on ``mesh`` (see :class:`ShardedGenerationEngine`).
    Returns the engine with ``serving_mesh``/``shard_policy``/
    ``shard_report`` attached."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.obs import flight as _flight
    from deeplearning4j_tpu.serving.generate import GenerationEngine

    n_slots = int(kwargs.get("n_slots", 8))
    _validate_generation_mesh(model, mesh, n_slots)
    pol = policy if policy is not None else policy_for(model)
    _flight.record("mesh_build", surface="generation",
                   batch=mesh.n_data, model=mesh.n_model,
                   n_devices=mesh.n_devices, policy=pol.name)
    report = validate_policy(model.params_, mesh, pol)
    stats = _reshard.TransferStats()
    reshard_to_policy(model, mesh, pol, stats)
    _flight.record("shard_load", surface="generation", policy=pol.name,
                   total_bytes=report["total_bytes"],
                   per_device_bytes=report["per_device_bytes"],
                   replicated_bytes=report["replicated_bytes"],
                   device_bytes=int(stats.device_bytes),
                   host_bytes=int(stats.host_bytes))
    eng = GenerationEngine(model, **kwargs)
    eng.serving_mesh = mesh
    eng.shard_policy = pol
    eng.shard_report = report
    eng.shard_stats = stats

    slab_sharding = NamedSharding(mesh.mesh,
                                  P(None, "batch", "model", None, None))

    be = eng.backend

    def _place_slab():
        be._kc = jax.device_put(be._kc, slab_sharding)
        be._vc = jax.device_put(be._vc, slab_sharding)
        ld = getattr(be, "draft_layers", 0)
        # draft slabs are L-axis slices of the sharded slab: re-derive
        # so they inherit the placement (zero-size when drafting is off)
        be._dkc = be._kc[:ld] if ld else be._kc[:0]
        be._dvc = be._vc[:ld] if ld else be._vc[:0]

    orig_reset = be.reset

    def reset_sharded():
        orig_reset()
        _place_slab()

    be.reset = reset_sharded
    _place_slab()
    return eng
