"""Shape-bucket policy: fixed-shape programs for dynamic traffic.

A coalescing batcher emits arbitrary batch sizes, and under XLA every
distinct input shape is a distinct compiled program — so naive
coalescing turns organic traffic (1..N rows per request, any mix) into
an endless stream of fresh compiles, each worth seconds of p99 latency
on TPU (arXiv 1810.09868: TPU programs are ahead-of-time-compiled
fixed-shape binaries; there is no partial-shape execution to fall back
on). The fix is the classic serving one (TF Serving's
``BatchingSession`` allowed-batch-sizes): quantize every dispatched
batch up to one of a small set of **buckets**, pad the tail, slice the
results back, and pre-compile every bucket once at startup
(:meth:`BucketPolicy.warmup_shapes` drives that) so the steady state
never compiles again.

Two bucketed axes:

- **batch** (axis 0): powers of two up to ``max_batch`` by default, or
  an explicit user list (e.g. ``[1, 4, 16, 64]``).
- **sequence length** (axis 1, opt-in per model): for recurrent /
  transformer inputs ``(b, T, ...)`` the time dimension is padded up to
  a per-model bucket list too. Sequence padding is only meaningful with
  masking — :meth:`pad_batch` therefore synthesizes (or extends) the
  feature mask so padded steps are dead, which the recurrent layers and
  attention here already honor.

Padding rows are zeros and every row of the result slice belongs to a
real request row — forward passes are row-independent in inference mode
(no cross-batch statistics with ``train=False``), so padding can never
leak into real results; tests assert this bitwise.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def _pow2_buckets(limit: int) -> List[int]:
    out, b = [], 1
    limit = max(int(limit), 1)
    while b < limit:
        out.append(b)
        b *= 2
    out.append(limit)
    return out


class BucketPolicy:
    """Quantizes dispatched batches onto a fixed shape set.

    - ``batch_buckets``: explicit ascending batch sizes, or None for
      powers of two up to ``max_batch``. When BOTH are given,
      ``max_batch`` (the batcher's ``batch_limit``) is unioned into the
      list — the last bucket always covers a full coalesced batch, so a
      loaded dispatch pads by zero instead of growing past the limit
      into a never-warmed shape.
    - ``seq_buckets``: optional ascending sequence-length buckets for
      rank>=3 inputs ``(b, T, ...)``; None disables time padding.
    - Oversized requests (more rows than the top bucket, or longer than
      the top seq bucket) round up to the next power of two beyond the
      list; the grown bucket is remembered so it only ever compiles
      once. The policy never truncates data.
    """

    def __init__(self, batch_buckets: Optional[Sequence[int]] = None,
                 max_batch: Optional[int] = None,
                 seq_buckets: Optional[Sequence[int]] = None):
        if batch_buckets is not None:
            bb = sorted({int(b) for b in batch_buckets})
            if not bb or bb[0] < 1:
                raise ValueError(f"batch_buckets must be positive: {batch_buckets}")
            if max_batch is not None and bb[-1] < int(max_batch):
                bb.append(int(max_batch))
        else:
            bb = _pow2_buckets(32 if max_batch is None else max_batch)
        self.batch_buckets: List[int] = bb
        self.seq_buckets: Optional[List[int]] = (
            None if seq_buckets is None
            else sorted({int(t) for t in seq_buckets}))
        if self.seq_buckets is not None and (
                not self.seq_buckets or self.seq_buckets[0] < 1):
            raise ValueError(f"seq_buckets must be positive: {seq_buckets}")

    def copy(self) -> "BucketPolicy":
        """Independent copy (same class, own bucket lists). The engine
        copies the policy it is given so its mesh filtering and
        oversize-growth never mutate a policy shared with another
        engine."""
        new = self.__class__.__new__(self.__class__)
        new.batch_buckets = list(self.batch_buckets)
        new.seq_buckets = (None if self.seq_buckets is None
                           else list(self.seq_buckets))
        return new

    # -- identity (the naive-coalescing baseline) ---------------------------
    @staticmethod
    def identity() -> "IdentityBucketPolicy":
        """A policy that never pads: every distinct size is its own
        "bucket" (exactly the pre-bucketing behavior — kept as the A/B
        baseline for the serving bench and as an opt-out)."""
        return IdentityBucketPolicy()

    # -- lookups ------------------------------------------------------------
    @staticmethod
    def _round_up(n: int, buckets: List[int]) -> int:
        for b in buckets:
            if n <= b:
                return b
        # oversized: grow by powers of two past the top bucket and
        # remember the new bucket (bounded shape count, compiles once)
        b = buckets[-1]
        while b < n:
            b *= 2
        buckets.append(b)
        return b

    def bucket_for(self, n: int) -> int:
        """Smallest batch bucket >= n."""
        return self._round_up(int(n), self.batch_buckets)

    def seq_bucket_for(self, t: int) -> int:
        """Smallest sequence bucket >= t (t itself when seq bucketing is
        off)."""
        if self.seq_buckets is None:
            return int(t)
        return self._round_up(int(t), self.seq_buckets)

    # -- padding ------------------------------------------------------------
    def pad_batch(self, x: np.ndarray, mask: Optional[np.ndarray] = None
                  ) -> Tuple[np.ndarray, Optional[np.ndarray], int]:
        """Pad ``x`` (and ``mask``) up to the bucketed shape.

        Returns ``(x_padded, mask_padded, n_real_rows)``; the caller
        slices results back to ``n_real_rows``. When sequence bucketing
        applies (rank>=3 input) a mask is synthesized if absent so the
        padded timesteps are masked out; batch-only padding leaves a
        None mask as None (padded rows are sliced away regardless).
        """
        x = np.asarray(x)
        if x.ndim < 1:
            raise ValueError("pad_batch needs a batched array, got a scalar")
        n = x.shape[0]
        nb = self.bucket_for(n)
        pad_seq = self.seq_buckets is not None and x.ndim >= 3
        if pad_seq:
            t = x.shape[1]
            tb = self.seq_bucket_for(t)
            if mask is None:
                # synthesized even at exact fit: mask presence changes the
                # jitted program's signature, so it must be uniform or
                # t==bucket traffic would compile a second program set
                mask = np.ones((n, t), np.float32)
        else:
            tb = x.shape[1] if x.ndim >= 2 else None
        if nb == n and (not pad_seq or tb == x.shape[1]):
            return x, mask, n
        shape = list(x.shape)
        shape[0] = nb
        if pad_seq:
            shape[1] = tb
        xp = np.zeros(shape, x.dtype)
        if pad_seq:
            xp[:n, : x.shape[1]] = x
        else:
            xp[:n] = x
        mp = mask
        if mask is not None:
            mask = np.asarray(mask)
            mshape = list(mask.shape)
            mshape[0] = nb
            if pad_seq and mask.ndim >= 2:
                mshape[1] = tb
            mp = np.zeros(mshape, mask.dtype)
            if pad_seq and mask.ndim >= 2:
                mp[:n, : mask.shape[1]] = mask
            else:
                mp[:n] = mask
        return xp, mp, n

    # -- warmup enumeration -------------------------------------------------
    def warmup_shapes(self, example_shape: Sequence[int]
                      ) -> List[Tuple[Tuple[int, ...], bool]]:
        """Every (input_shape, with_mask) this policy can emit for
        per-example shape ``example_shape`` (no batch dim) — the set
        :meth:`InferenceEngine.warmup` pre-compiles. With seq bucketing
        the time axis (``example_shape[0]``) takes each seq bucket and
        the mask is always present (matching :meth:`pad_batch`)."""
        example_shape = tuple(int(d) for d in example_shape)
        shapes: List[Tuple[Tuple[int, ...], bool]] = []
        seq = self.seq_buckets is not None and len(example_shape) >= 2
        for nb in list(self.batch_buckets):
            if seq:
                for tb in list(self.seq_buckets):
                    shapes.append(((nb, tb) + example_shape[1:], True))
            else:
                shapes.append(((nb,) + example_shape, False))
        return shapes

    def __repr__(self):
        return (f"BucketPolicy(batch={self.batch_buckets}, "
                f"seq={self.seq_buckets})")


def propose_buckets(observed_rows: Sequence[int],
                    max_batch: int) -> List[int]:
    """Learn a batch-bucket list from an observed dispatch-size mix.

    The static default (powers of two up to ``max_batch``) is the right
    *prior*; once real traffic exists, the right buckets are the ones
    that sit just above the mix's mass. Take the 50/90/99th percentiles
    of the observed real-row counts, round each up to a power of two
    (fixed-shape discipline: the shape set must stay small and stable
    under jitter in the mix), union in ``max_batch`` so a full coalesced
    batch still fits, and drop anything over the limit. The result is
    the candidate an adaptive controller hands to
    :meth:`InferenceEngine.retune_buckets` — which pre-compiles every
    shape BEFORE switching, so adopting the proposal costs zero
    steady-state retraces."""
    max_batch = max(int(max_batch), 1)
    rows = sorted(int(r) for r in observed_rows if int(r) > 0)
    if not rows:
        return _pow2_buckets(max_batch)
    picks = set()
    for q in (0.5, 0.9, 0.99):
        v = rows[min(int(q * len(rows)), len(rows) - 1)]
        picks.add(1 << max(v - 1, 0).bit_length())
    picks.add(max_batch)
    return sorted(b for b in picks if b <= max_batch)


def slice_result(y: np.ndarray, n: int, t_orig: Optional[int],
                 t_padded: Optional[int]) -> np.ndarray:
    """Undo bucket padding on a model output: always slice the batch
    axis to ``n``; slice the time axis back to ``t_orig`` when sequence
    padding occurred AND the output still carries that axis (per-step
    outputs ``(b, T, ...)``; time-pooled outputs ``(b, C)`` have no
    padded axis left — masking already kept them correct)."""
    y = np.asarray(y)[:n]
    if (t_orig is not None and t_padded is not None and t_padded != t_orig
            and y.ndim >= 3 and y.shape[1] == t_padded):
        y = y[:, :t_orig]
    return y


class IdentityBucketPolicy(BucketPolicy):
    """Pass-through policy: no padding, every size its own program — the
    naive-coalescing baseline. ``warmup_shapes`` is empty (there is no
    finite shape set to pre-compile, which is exactly the problem)."""

    def __init__(self):
        super().__init__(batch_buckets=[1])

    def bucket_for(self, n: int) -> int:
        return int(n)

    def seq_bucket_for(self, t: int) -> int:
        return int(t)

    def pad_batch(self, x, mask=None):
        x = np.asarray(x)
        return x, mask, x.shape[0]

    def warmup_shapes(self, example_shape):
        return []

    def __repr__(self):
        return "IdentityBucketPolicy()"
