"""Regularization, parameter constraints, and gradient normalization.

Parity targets in the reference:
- l1/l2/weight-decay per layer & per param-type (weights vs biases), applied
  to gradients before the updater and to the score
  (``nn/conf/NeuralNetConfiguration`` builder l1/l2/l1Bias/l2Bias,
  score terms via ``BaseLayer.calcRegularizationScore``).
- Gradient normalization modes applied in the updater "preApply"
  (``nn/updater/BaseMultiLayerUpdater.java:322``,
  ``nn/conf/GradientNormalization.java``).
- Parameter constraints applied after each step
  (``nn/conf/constraint/*`` — MaxNorm, MinMaxNorm, NonNegative, UnitNorm;
  applied at ``optimize/solvers/BaseOptimizer applyConstraints``).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

Array = jax.Array


# ---------------------------------------------------------------------------
# L1 / L2 / weight decay
# ---------------------------------------------------------------------------

class RegularizationConf:
    """Per-layer regularization coefficients (weights vs biases)."""

    def __init__(self, l1: float = 0.0, l2: float = 0.0, l1_bias: float = 0.0,
                 l2_bias: float = 0.0, weight_decay: float = 0.0,
                 weight_decay_bias: float = 0.0):
        self.l1 = float(l1)
        self.l2 = float(l2)
        self.l1_bias = float(l1_bias)
        self.l2_bias = float(l2_bias)
        self.weight_decay = float(weight_decay)
        self.weight_decay_bias = float(weight_decay_bias)

    def coeffs_for(self, param_name: str) -> tuple[float, float, float]:
        """(l1, l2, weight_decay) for a parameter by name ('b*' = bias)."""
        if param_name.startswith("b") or "bias" in param_name.lower():
            return self.l1_bias, self.l2_bias, self.weight_decay_bias
        return self.l1, self.l2, self.weight_decay

    def grad_term(self, param_name: str, param: Array) -> Optional[Array]:
        """dReg/dParam to add to the raw gradient (reference applies l1/l2
        into the gradient before the updater sees it)."""
        l1, l2, wd = self.coeffs_for(param_name)
        term = None
        if l2:
            term = l2 * param
        if l1:
            t = l1 * jnp.sign(param)
            term = t if term is None else term + t
        # weight decay is applied post-lr multiplication in some formulations;
        # reference WeightDecay applies coeff * param into the update. We fold
        # it into the gradient (equivalent for SGD; standard decoupled form is
        # approximated — documented deviation).
        if wd:
            t = wd * param
            term = t if term is None else term + t
        return term

    def score_term(self, param_name: str, param: Array) -> Array:
        # accumulate in >= fp32 (half-precision sums overflow/lose bits) but
        # keep fp64 when the gradient checker runs the net in float64
        l1, l2, _wd = self.coeffs_for(param_name)
        acc = jnp.promote_types(param.dtype, jnp.float32)
        p = param.astype(acc)
        s = jnp.zeros((), acc)
        if l2:
            s = s + 0.5 * l2 * jnp.sum(p**2)
        if l1:
            s = s + l1 * jnp.sum(jnp.abs(p))
        return s

    def to_dict(self):
        return dict(self.__dict__)

    @staticmethod
    def from_dict(d):
        return RegularizationConf(**d)

    def __eq__(self, other):
        return isinstance(other, RegularizationConf) and self.__dict__ == other.__dict__


# ---------------------------------------------------------------------------
# Gradient normalization (reference GradientNormalization enum)
# ---------------------------------------------------------------------------

GRADIENT_NORMALIZATIONS = (
    "none",
    "renormalize_l2_per_layer",
    "renormalize_l2_per_param_type",
    "clip_element_wise_absolute_value",
    "clip_l2_per_layer",
    "clip_l2_per_param_type",
)


def normalize_layer_gradients(
    grads: Dict[str, Array],
    mode: Optional[str],
    threshold: float = 1.0,
    eps: float = 1e-8,
) -> Dict[str, Array]:
    """Apply a gradient-normalization mode to one layer's gradient dict.

    Mirrors ``BaseMultiLayerUpdater.preApply`` (reference
    ``nn/updater/BaseMultiLayerUpdater.java:322``): normalization happens on
    the raw gradients before the updater math.
    """
    if not mode or mode == "none" or not grads:
        return grads
    mode = mode.lower()
    if mode == "renormalize_l2_per_layer":
        sq = sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in grads.values())
        norm = jnp.sqrt(sq + eps)
        return {k: g / norm for k, g in grads.items()}
    if mode == "renormalize_l2_per_param_type":
        return {
            k: g / jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2) + eps)
            for k, g in grads.items()
        }
    if mode == "clip_element_wise_absolute_value":
        return {k: jnp.clip(g, -threshold, threshold) for k, g in grads.items()}
    if mode == "clip_l2_per_layer":
        sq = sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in grads.values())
        norm = jnp.sqrt(sq + eps)
        scale = jnp.where(norm > threshold, threshold / norm, 1.0)
        return {k: g * scale for k, g in grads.items()}
    if mode == "clip_l2_per_param_type":
        out = {}
        for k, g in grads.items():
            norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2) + eps)
            scale = jnp.where(norm > threshold, threshold / norm, 1.0)
            out[k] = g * scale
        return out
    raise ValueError(f"Unknown gradient normalization '{mode}'")


# ---------------------------------------------------------------------------
# Constraints (applied to params after each update)
# ---------------------------------------------------------------------------

class Constraint:
    """Base parameter constraint (reference ``nn/conf/constraint/BaseConstraint``).

    ``dims``: axes over which norms are computed (reference defaults: for a
    dense weight [nIn, nOut] the norm is per output unit → axis 0).
    """

    applies_to = ("W",)  # param names; reference default applies to weights only

    def apply(self, param: Array) -> Array:
        raise NotImplementedError

    def to_dict(self):
        return {"@class": type(self).__name__, **self.__dict__}

    @staticmethod
    def from_dict(d):
        d = dict(d)
        cls = _CONSTRAINTS[d.pop("@class")]
        obj = cls.__new__(cls)
        obj.__dict__.update(d)
        return obj

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__


def _reduce_axes(param: Array) -> tuple:
    # All axes except the last ("per output unit" norms, matching reference
    # dimension conventions for dense [in,out] and conv [kh,kw,in,out]).
    return tuple(range(param.ndim - 1)) if param.ndim > 1 else (0,)


class MaxNormConstraint(Constraint):
    def __init__(self, max_norm: float = 1.0):
        self.max_norm = float(max_norm)

    def apply(self, param):
        axes = _reduce_axes(param)
        norm = jnp.sqrt(jnp.sum(param**2, axis=axes, keepdims=True) + 1e-12)
        scale = jnp.minimum(1.0, self.max_norm / norm)
        return param * scale


class MinMaxNormConstraint(Constraint):
    def __init__(self, min_norm: float = 0.0, max_norm: float = 1.0, rate: float = 1.0):
        self.min_norm = float(min_norm)
        self.max_norm = float(max_norm)
        self.rate = float(rate)

    def apply(self, param):
        axes = _reduce_axes(param)
        norm = jnp.sqrt(jnp.sum(param**2, axis=axes, keepdims=True) + 1e-12)
        clipped = jnp.clip(norm, self.min_norm, self.max_norm)
        target = self.rate * clipped + (1 - self.rate) * norm
        return param * (target / norm)


class NonNegativeConstraint(Constraint):
    def __init__(self):
        pass

    def apply(self, param):
        return jnp.maximum(param, 0.0)


class UnitNormConstraint(Constraint):
    def __init__(self):
        pass

    def apply(self, param):
        axes = _reduce_axes(param)
        norm = jnp.sqrt(jnp.sum(param**2, axis=axes, keepdims=True) + 1e-12)
        return param / norm


_CONSTRAINTS = {
    c.__name__: c
    for c in [MaxNormConstraint, MinMaxNormConstraint, NonNegativeConstraint, UnitNormConstraint]
}
