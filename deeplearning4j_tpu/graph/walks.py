"""Random-walk sequence generators (reference
``graph/iterator/RandomWalkIterator.java`` and
``WeightedRandomWalkIterator.java``): fixed-length walks from every
vertex, uniform or weight-proportional next-step choice; NoEdgeHandling
SELF_LOOP_ON_DISCONNECTED semantics."""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph


class RandomWalkIterator:
    def __init__(self, graph: Graph, walk_length: int, seed: int = 42,
                 walks_per_vertex: int = 1):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.seed = seed
        self.walks_per_vertex = int(walks_per_vertex)

    def _next_step(self, rng, v: int) -> int:
        nbrs = self.graph.get_connected_vertices(v)
        if not nbrs:
            return v  # self-loop on disconnected vertex
        return nbrs[rng.integers(0, len(nbrs))]

    def __iter__(self) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed)
        order = np.arange(self.graph.num_vertices())
        for _ in range(self.walks_per_vertex):
            rng.shuffle(order)
            for start in order:
                walk = [int(start)]
                v = int(start)
                for _ in range(self.walk_length - 1):
                    v = self._next_step(rng, v)
                    walk.append(v)
                yield np.asarray(walk, np.int32)


class WeightedRandomWalkIterator(RandomWalkIterator):
    def _next_step(self, rng, v: int) -> int:
        nbrs = self.graph.get_connected_vertices(v)
        if not nbrs:
            return v
        w = np.asarray(self.graph.get_edge_weights(v), np.float64)
        p = w / w.sum() if w.sum() > 0 else None
        return int(rng.choice(nbrs, p=p))
