"""Graph-embedding serialization (reference ``GraphVectorSerializer``,
``deeplearning4j-graph/.../models/loader/GraphVectorSerializer.java:21``):
tab-delimited text — one line per vertex, ``index\\tv0\\tv1...`` — and a
static query object on load."""

from __future__ import annotations

from typing import List

import numpy as np

DELIM = "\t"


class StaticGraphVectors:
    """Query surface over a loaded vertex-vector matrix (the reference's
    in-memory ``GraphVectors`` returned by ``loadTxtVectors``)."""

    def __init__(self, matrix: np.ndarray):
        self.matrix = np.asarray(matrix, np.float32)

    def num_vertices(self) -> int:
        return self.matrix.shape[0]

    def get_vertex_vector(self, v: int) -> np.ndarray:
        return self.matrix[v]

    def similarity(self, a: int, b: int) -> float:
        va, vb = self.matrix[a], self.matrix[b]
        na, nb = np.linalg.norm(va), np.linalg.norm(vb)
        if na == 0 or nb == 0:
            return 0.0
        return float(va @ vb / (na * nb))

    def vertices_nearest(self, v: int, n: int = 10) -> List[int]:
        m = self.matrix
        norms = np.linalg.norm(m, axis=1)
        norms[norms == 0] = 1e-9
        sims = (m @ m[v]) / (norms * max(float(norms[v]), 1e-9))
        sims[v] = -np.inf
        return [int(i) for i in np.argsort(-sims)[:n]]


class GraphVectorSerializer:
    @staticmethod
    def write_graph_vectors(model, path: str) -> None:
        """``model`` is anything with num_vertices()/get_vertex_vector()
        (DeepWalk, Node2Vec, StaticGraphVectors)."""
        with open(path, "w", encoding="utf-8") as f:
            for i in range(model.num_vertices()):
                vec = np.asarray(model.get_vertex_vector(i), np.float64)
                f.write(str(i) + DELIM
                        + DELIM.join(repr(float(x)) for x in vec) + "\n")

    @staticmethod
    def load_txt_vectors(path: str) -> StaticGraphVectors:
        rows = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split(DELIM)
                if len(parts) < 2:
                    continue
                rows.append(np.asarray(parts[1:], np.float64))
        if not rows:
            raise ValueError(f"no vectors in {path}")
        return StaticGraphVectors(np.stack(rows))

    # reference-parity names
    writeGraphVectors = write_graph_vectors
    loadTxtVectors = load_txt_vectors
