"""Graph embeddings — rebuild of deeplearning4j-graph (SURVEY.md §2.7:
in-memory graph, random-walk iterators, DeepWalk with hierarchical
softmax via GraphHuffman; 2,283 LoC reference)."""

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walks import (
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk, GraphVectors
from deeplearning4j_tpu.graph.node2vec import BiasedRandomWalkIterator, Node2Vec
from deeplearning4j_tpu.graph.loader import GraphLoader
from deeplearning4j_tpu.graph.serializer import (
    GraphVectorSerializer,
    StaticGraphVectors,
)

__all__ = [
    "Graph", "RandomWalkIterator", "WeightedRandomWalkIterator",
    "DeepWalk", "GraphVectors", "Node2Vec", "BiasedRandomWalkIterator",
    "GraphVectorSerializer", "StaticGraphVectors", "GraphLoader",
]
