"""Node2Vec (reference ``models/node2vec/Node2Vec.java``): DeepWalk with
2nd-order biased random walks — return parameter ``p`` (likelihood of
revisiting the previous node) and in-out parameter ``q`` (BFS-like q<1 vs
DFS-like q>1), per Grover & Leskovec 2016. Training reuses the batched
skip-gram kernels via SequenceVectors, exactly like DeepWalk."""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.graph.deepwalk import DeepWalk, GraphVectors, _degree_vocab
from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walks import RandomWalkIterator
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors


class BiasedRandomWalkIterator(RandomWalkIterator):
    """node2vec 2nd-order walk: unnormalized next-step weight is 1/p to
    return to the previous node, 1 for a neighbour of the previous node,
    1/q otherwise."""

    def __init__(self, graph: Graph, walk_length: int, p: float = 1.0,
                 q: float = 1.0, seed: int = 42, walks_per_vertex: int = 1):
        super().__init__(graph, walk_length, seed, walks_per_vertex)
        self.p = float(p)
        self.q = float(q)
        # neighbour sets for O(1) membership checks
        self._nbr_sets = [
            set(graph.get_connected_vertices(v))
            for v in range(graph.num_vertices())
        ]

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        order = np.arange(self.graph.num_vertices())
        for _ in range(self.walks_per_vertex):
            rng.shuffle(order)
            for start in order:
                walk = [int(start)]
                prev = None
                v = int(start)
                for _ in range(self.walk_length - 1):
                    nbrs = self.graph.get_connected_vertices(v)
                    if not nbrs:
                        walk.append(v)  # self-loop on disconnected
                        continue
                    if prev is None:
                        nxt = nbrs[rng.integers(0, len(nbrs))]
                    else:
                        w = np.empty(len(nbrs))
                        prev_nbrs = self._nbr_sets[prev]
                        for i, u in enumerate(nbrs):
                            if u == prev:
                                w[i] = 1.0 / self.p
                            elif u in prev_nbrs:
                                w[i] = 1.0
                            else:
                                w[i] = 1.0 / self.q
                        nxt = int(rng.choice(nbrs, p=w / w.sum()))
                    walk.append(nxt)
                    prev, v = v, nxt
                yield np.asarray(walk, np.int32)


class Node2Vec(DeepWalk):
    """DeepWalk with the biased walk generator injected — the training
    setup is DeepWalk's, unchanged (node2vec's published configuration
    uses negative sampling, so the Builder defaults differ)."""

    class Builder(DeepWalk.Builder):
        def __init__(self):
            super().__init__()
            self._p = 1.0
            self._q = 1.0
            self._negative = 5       # node2vec's published setting is NS
            self._use_hs = False

        def p(self, v: float):
            self._p = float(v)
            return self

        def q(self, v: float):
            self._q = float(v)
            return self

        def build(self) -> "Node2Vec":
            return Node2Vec(self)

    @staticmethod
    def builder():
        return Node2Vec.Builder()

    def fit(self, graph: Graph, walk_iterator=None) -> "Node2Vec":
        b = self._b
        if walk_iterator is None:
            walk_iterator = BiasedRandomWalkIterator(
                graph, b._walk_length, p=b._p, q=b._q, seed=b._seed,
                walks_per_vertex=b._walks_per_vertex,
            )
        super().fit(graph, walk_iterator=walk_iterator)
        return self
