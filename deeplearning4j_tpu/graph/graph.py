"""In-memory graph (reference ``graph/graph/Graph.java`` implementing
``api/IGraph.java``): vertices 0..N-1, directed or undirected weighted
edges, adjacency lists."""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class Graph:
    def __init__(self, num_vertices: int, allow_multiple_edges: bool = False):
        self.num_vertices_ = int(num_vertices)
        self.allow_multiple_edges = allow_multiple_edges
        self._adj: List[List[Tuple[int, float]]] = [
            [] for _ in range(num_vertices)
        ]

    def num_vertices(self) -> int:
        return self.num_vertices_

    def add_edge(self, a: int, b: int, weight: float = 1.0,
                 directed: bool = False) -> None:
        if not (0 <= a < self.num_vertices_ and 0 <= b < self.num_vertices_):
            raise ValueError(f"edge ({a},{b}) out of range")
        if not self.allow_multiple_edges and any(v == b for v, _ in self._adj[a]):
            return
        self._adj[a].append((b, float(weight)))
        if not directed:
            self._adj[b].append((a, float(weight)))

    def get_connected_vertices(self, v: int) -> List[int]:
        return [u for u, _ in self._adj[v]]

    def get_edge_weights(self, v: int) -> List[float]:
        return [w for _, w in self._adj[v]]

    def degree(self, v: int) -> int:
        return len(self._adj[v])
