"""Graph file loading (reference ``graph/data/GraphLoader.java``:
edge-list / weighted-edge-list / adjacency-list text formats, with the
delimiter and directed/undirected options)."""

from __future__ import annotations

from deeplearning4j_tpu.graph.graph import Graph


class GraphLoader:
    @staticmethod
    def load_undirected_graph_edge_list_file(path: str, num_vertices: int,
                                             delim: str = ",") -> Graph:
        """Lines ``a<delim>b`` add an undirected edge (reference
        ``loadUndirectedGraphEdgeListFile``)."""
        g = Graph(num_vertices)
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                a, b = line.split(delim)[:2]
                g.add_edge(int(a), int(b), directed=False)
        return g

    @staticmethod
    def load_weighted_edge_list_file(path: str, num_vertices: int,
                                     delim: str = ",",
                                     directed: bool = False) -> Graph:
        """Lines ``a<delim>b<delim>weight`` (reference
        ``loadWeightedEdgeListFile``)."""
        g = Graph(num_vertices)
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                a, b, w = line.split(delim)[:3]
                g.add_edge(int(a), int(b), weight=float(w),
                           directed=directed)
        return g

    @staticmethod
    def load_adjacency_list_file(path: str, num_vertices: int,
                                 delim: str = ",") -> Graph:
        """Each line ``v<delim>n1<delim>n2...`` lists vertex v's (directed)
        neighbours (the reference's adjacency-list processor shape)."""
        g = Graph(num_vertices)
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                parts = [p for p in line.strip().split(delim) if p != ""]
                if not parts or parts[0].startswith("#"):
                    continue
                v = int(parts[0])
                for n in parts[1:]:
                    g.add_edge(v, int(n), directed=True)
        return g

    # reference-parity names
    loadUndirectedGraphEdgeListFile = load_undirected_graph_edge_list_file
    loadWeightedEdgeListFile = load_weighted_edge_list_file
    loadAdjacencyListFile = load_adjacency_list_file
