"""DeepWalk (reference ``graph/models/deepwalk/DeepWalk.java``): truncated
random walks fed to skip-gram with hierarchical softmax
(``GraphHuffman.java`` builds codes over vertex degree).

TPU-native: walks are just integer sequences, so training reuses the
batched skip-gram kernel via SequenceVectors directly — the reference's
``GraphVectorLookupTable`` + per-pair HS updates collapse into the same
jitted scatter step Word2Vec uses (SURVEY.md §9: DeepWalk reuses the
skip-gram learner).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walks import RandomWalkIterator
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabWord


def _degree_vocab(graph: Graph) -> AbstractCache:
    """Vertex vocab with degree as 'frequency' (the reference's
    GraphHuffman weights codes by degree); index i = vertex i."""
    cache = AbstractCache()
    for v in range(graph.num_vertices()):
        vw = VocabWord(str(v), max(graph.degree(v), 1))
        cache.add_token(vw)
    # identity indexing: vertex id == row id (walks index rows directly)
    cache._by_index = [cache._by_word[str(v)] for v in range(graph.num_vertices())]
    for i, vw in enumerate(cache._by_index):
        vw.index = i
    return cache


class GraphVectors:
    """Query surface (reference ``GraphVectors``/``GraphVectorsImpl``)."""

    def __init__(self, sv: SequenceVectors, graph: Graph):
        self.sv = sv
        self.graph = graph

    def num_vertices(self) -> int:
        return self.graph.num_vertices()

    def get_vertex_vector(self, v: int) -> np.ndarray:
        return self.sv.vector(v)

    def similarity(self, a: int, b: int) -> float:
        return self.sv.similarity_by_index(a, b)

    def vertices_nearest(self, v: int, n: int = 10) -> List[int]:
        return self.sv.nearest_by_index(v, n)


class DeepWalk(GraphVectors):
    class Builder:
        def __init__(self):
            self._vector_size = 100
            self._window = 5
            self._walk_length = 40
            self._walks_per_vertex = 10
            self._lr = 0.025
            self._seed = 42
            self._epochs = 1
            self._negative = 0  # reference uses HS only
            self._use_hs = True
            self._batch_size = 512

        def vector_size(self, n):
            self._vector_size = int(n)
            return self

        def window_size(self, n):
            self._window = int(n)
            return self

        def walk_length(self, n):
            self._walk_length = int(n)
            return self

        def walks_per_vertex(self, n):
            self._walks_per_vertex = int(n)
            return self

        def learning_rate(self, x):
            self._lr = float(x)
            return self

        def seed(self, n):
            self._seed = int(n)
            return self

        def epochs(self, n):
            self._epochs = int(n)
            return self

        def negative_sample(self, n):
            """Optional NS instead of/alongside HS (new capability; the
            reference is HS-only)."""
            self._negative = int(n)
            return self

        def use_hierarchic_softmax(self, b):
            self._use_hs = bool(b)
            return self

        def batch_size(self, n):
            self._batch_size = int(n)
            return self

        def build(self) -> "DeepWalk":
            return DeepWalk(self)

    @staticmethod
    def builder():
        return DeepWalk.Builder()

    def __init__(self, b: "DeepWalk.Builder"):
        self._b = b
        self.sv: Optional[SequenceVectors] = None
        self.graph: Optional[Graph] = None

    def fit(self, graph: Graph,
            walk_iterator: Optional[RandomWalkIterator] = None) -> "DeepWalk":
        b = self._b
        self.graph = graph
        vocab = _degree_vocab(graph)
        self.sv = SequenceVectors(
            vocab,
            layer_size=b._vector_size,
            window=b._window,
            negative=b._negative,
            use_hierarchic_softmax=b._use_hs,
            learning_rate=b._lr,
            min_learning_rate=1e-4,
            epochs=b._epochs,
            batch_size=b._batch_size,
            seed=b._seed,
            elements_algorithm="skipgram",
        )
        it = walk_iterator if walk_iterator is not None else RandomWalkIterator(
            graph, b._walk_length, seed=b._seed,
            walks_per_vertex=b._walks_per_vertex,
        )
        walks = list(it)
        self.sv.fit_sequences(walks)
        return self
