"""Sklearn-style estimator adapters — the idiomatic analog of the
reference's ``dl4j-spark-ml`` pipeline wrappers
(``spark/dl4j-spark-ml/src/main/scala/org/deeplearning4j/spark/ml/impl/
SparkDl4jNetwork.scala``: an Estimator whose ``fit`` trains the network
and returns a Model exposing ``transform``/``predict``).

Spark ML is JVM pipeline infrastructure; the Python ecosystem's
equivalent contract is scikit-learn's estimator API, implemented here by
duck typing (``fit`` / ``predict`` / ``predict_proba`` / ``score`` /
``get_params`` / ``set_params`` / ``partial_fit``) — no sklearn import
required, but the classes drop into sklearn Pipelines, GridSearchCV and
cross_val_score unchanged because those only use the protocol.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import numpy as np

try:  # optional: inherit sklearn's bases so modern Pipeline/GridSearch
    # machinery (__sklearn_tags__, clone) recognizes these natively
    from sklearn.base import BaseEstimator as _SkBase
    from sklearn.base import ClassifierMixin as _SkClassifier
    from sklearn.base import RegressorMixin as _SkRegressor
except ImportError:  # pure duck-typed protocol without sklearn
    _SkBase = object

    class _SkClassifier:  # type: ignore[no-redef]
        pass

    class _SkRegressor:  # type: ignore[no-redef]
        pass


class _BaseNetEstimator(_SkBase):
    def __init__(self, conf: Union[Callable, "object"], epochs: int = 10,
                 batch_size: int = 32, shuffle: bool = True,
                 seed: int = 0):
        self.conf = conf
        self.epochs = int(epochs)
        self.batch_size = int(batch_size)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.net_ = None

    # -- sklearn protocol --------------------------------------------------
    def get_params(self, deep: bool = True) -> dict:
        params = {"conf": self.conf, "epochs": self.epochs,
                  "batch_size": self.batch_size, "shuffle": self.shuffle,
                  "seed": self.seed}
        if deep and hasattr(self.conf, "get_params"):
            # conf-factory hyperparameters (tune.space.ConfFactory or any
            # object with get_params/with_params) surface as conf__<name>,
            # so sklearn clone/GridSearchCV and the tuner bridge can
            # search the NETWORK's hyperparameters, not just the loop's
            for k, v in self.conf.get_params().items():
                if callable(v):
                    continue  # the factory fn itself is not a hyperparameter
                params[f"conf__{k}"] = v
        return params

    def set_params(self, **params) -> "_BaseNetEstimator":
        shallow = {"conf", "epochs", "batch_size", "shuffle", "seed"}
        conf_updates = {}
        for k, v in params.items():
            if k.startswith("conf__"):
                if not hasattr(self.conf, "with_params"):
                    raise ValueError(
                        f"Parameter {k!r} needs conf to be a factory with "
                        "with_params() (e.g. tune.ConfFactory); got "
                        f"{type(self.conf).__name__}")
                conf_updates[k[len("conf__"):]] = v
            elif k in shallow:
                setattr(self, k, v)
            else:
                raise ValueError(
                    f"Invalid parameter {k!r} for {type(self).__name__}")
        if conf_updates:
            # copy-on-write: sklearn clones share the factory object, so
            # a grid point must never mutate a sibling clone's conf
            self.conf = self.conf.with_params(**conf_updates)
        return self

    # -- shared machinery --------------------------------------------------
    def _build(self):
        from deeplearning4j_tpu.nn.conf.builders import (
            MultiLayerConfiguration,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = self.conf() if callable(self.conf) else self.conf
        if not isinstance(conf, MultiLayerConfiguration):
            raise TypeError(
                "conf must be a MultiLayerConfiguration or a zero-arg "
                f"callable returning one, got {type(conf).__name__}")
        return MultiLayerNetwork(conf).init()

    def _epoch_batches(self, X, Y, rng):
        n = X.shape[0]
        order = rng.permutation(n) if self.shuffle else np.arange(n)
        for s in range(0, n, self.batch_size):
            idx = order[s:s + self.batch_size]
            yield X[idx], Y[idx]

    def _fit_loop(self, X, Y, epochs):
        rng = np.random.default_rng(self.seed)
        for _ in range(epochs):
            for xb, yb in self._epoch_batches(X, Y, rng):
                self.net_.fit(xb, yb)
        return self

    def _check_fitted(self):
        if self.net_ is None:
            raise RuntimeError(
                f"{type(self).__name__} is not fitted yet; call fit()")


class NeuralNetClassifier(_SkClassifier, _BaseNetEstimator):
    """Classifier over a MultiLayerNetwork configuration.

    ``conf``: a built MultiLayerConfiguration (its output layer width
    must equal the number of classes) or a zero-arg callable returning
    one (lets GridSearchCV clones build fresh networks). ``fit``
    one-hot-encodes integer/string labels and records ``classes_``.
    """

    def fit(self, X, y) -> "NeuralNetClassifier":
        X = np.asarray(X, np.float32)
        y = np.asarray(y)
        self.classes_, y_idx = np.unique(y, return_inverse=True)
        Y = np.eye(len(self.classes_), dtype=np.float32)[y_idx]
        self.net_ = self._build()
        return self._fit_loop(X, Y, self.epochs)

    def partial_fit(self, X, y, classes=None) -> "NeuralNetClassifier":
        """Incremental fit (one epoch over the given data). ``classes``
        is required on the first call (sklearn's partial_fit contract)."""
        X = np.asarray(X, np.float32)
        y = np.asarray(y)
        if self.net_ is None:
            if classes is None:
                raise ValueError(
                    "classes= is required on the first partial_fit call")
            # sorted-unique normalization: searchsorted (below) assumes a
            # sorted classes_ array, so an unsorted classes= would
            # silently map labels to the wrong one-hot columns
            self.classes_ = np.unique(np.asarray(classes))
            self.net_ = self._build()
        idx = np.searchsorted(self.classes_, y)
        known = (idx < len(self.classes_))
        known &= self.classes_[np.minimum(idx, len(self.classes_) - 1)] == y
        if not np.all(known):
            raise ValueError(
                f"y contains labels not in classes=: "
                f"{np.unique(y[~known]).tolist()}")
        Y = np.eye(len(self.classes_), dtype=np.float32)[idx]
        return self._fit_loop(X, Y, 1)

    def predict_proba(self, X) -> np.ndarray:
        self._check_fitted()
        return np.asarray(self.net_.output(np.asarray(X, np.float32)))

    def predict(self, X) -> np.ndarray:
        proba = self.predict_proba(X)  # checks fitted first
        return self.classes_[np.argmax(proba, axis=1)]

    def score(self, X, y) -> float:
        """Mean accuracy (sklearn classifier convention)."""
        return float(np.mean(self.predict(X) == np.asarray(y)))


class NeuralNetRegressor(_SkRegressor, _BaseNetEstimator):
    """Regressor over a MultiLayerNetwork configuration (identity/linear
    output layer with an mse-style loss)."""

    def fit(self, X, y) -> "NeuralNetRegressor":
        X = np.asarray(X, np.float32)
        Y = np.asarray(y, np.float32)
        if Y.ndim == 1:
            Y = Y[:, None]
        self._y_1d = np.asarray(y).ndim == 1
        self.net_ = self._build()
        return self._fit_loop(X, Y, self.epochs)

    def predict(self, X) -> np.ndarray:
        self._check_fitted()
        out = np.asarray(self.net_.output(np.asarray(X, np.float32)))
        return out[:, 0] if self._y_1d else out

    def score(self, X, y) -> float:
        """R² coefficient of determination (sklearn regressor
        convention)."""
        y = np.asarray(y, np.float32)
        pred = self.predict(X)
        ss_res = float(np.sum((y - pred) ** 2))
        ss_tot = float(np.sum((y - np.mean(y)) ** 2))
        return 1.0 - ss_res / ss_tot if ss_tot else 0.0
