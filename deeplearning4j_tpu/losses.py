"""Loss function catalog.

Parity with the reference's ``LossFunctions.LossFunction`` enum and the
``ILossFunction`` implementations consumed by output layers (reference:
``nd4j`` loss functions as used by
``deeplearning4j-nn/.../nn/conf/layers/OutputLayer.java`` and
``nn/layers/BaseOutputLayer``).

Design: each loss is a pure function ``loss(labels, preout, activation, mask)
-> per-example score vector``; gradients come from jax autodiff on the whole
train step, so there is no hand-written ``computeGradient`` as in the
reference. Softmax/sigmoid cross-entropies are computed from logits
(numerically stable log-sum-exp form) — the activation is folded into the
loss when it is the canonical pairing, mirroring what the reference does
analytically in ``LossMCXENT.computeGradient`` (softmax-cancellation).

Masking: ``mask`` has shape (batch,) or broadcastable to the per-element
score; masked elements contribute zero and the mean divides by mask sum
(reference per-output masking semantics, ``nn/api/Layer.java:288``).
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import activations as _act

Array = jax.Array
EPS = 1e-7


def _apply_activation(preout: Array, activation: Optional[str]) -> Array:
    return _act.get(activation)(preout)


def _reduce_elementwise(per_elem: Array, mask: Optional[Array]) -> Array:
    """Sum per-element scores over feature axes → per-example vector."""
    if mask is not None:
        per_elem = per_elem * mask
    axes = tuple(range(1, per_elem.ndim))
    return jnp.sum(per_elem, axis=axes) if axes else per_elem


def mse(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    # Reference LossMSE: mean over output features of squared error.
    n = labels.shape[-1]
    return _reduce_elementwise((out - labels) ** 2, mask) / n


def l2(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    return _reduce_elementwise((out - labels) ** 2, mask)


def mae(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    n = labels.shape[-1]
    return _reduce_elementwise(jnp.abs(out - labels), mask) / n


def l1(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    return _reduce_elementwise(jnp.abs(out - labels), mask)


def mape(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    n = labels.shape[-1]
    per = jnp.abs((labels - out) / jnp.where(jnp.abs(labels) < EPS, EPS, labels)) * 100.0
    return _reduce_elementwise(per, mask) / n


def msle(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    n = labels.shape[-1]
    per = (jnp.log1p(jnp.maximum(out, -1 + EPS)) - jnp.log1p(jnp.maximum(labels, -1 + EPS))) ** 2
    return _reduce_elementwise(per, mask) / n


def xent(labels, preout, activation="sigmoid", mask=None) -> Array:
    """Binary cross-entropy. Stable from logits when activation == sigmoid."""
    if activation in ("sigmoid", None):
        # log(1+exp(-|x|)) formulation
        per = jnp.maximum(preout, 0) - preout * labels + jnp.log1p(jnp.exp(-jnp.abs(preout)))
    else:
        out = jnp.clip(_apply_activation(preout, activation), EPS, 1 - EPS)
        per = -(labels * jnp.log(out) + (1 - labels) * jnp.log(1 - out))
    return _reduce_elementwise(per, mask)


def mcxent(labels, preout, activation="softmax", mask=None) -> Array:
    """Multi-class cross-entropy with one-hot (or soft) labels."""
    if activation in ("softmax", None):
        logp = jax.nn.log_softmax(preout, axis=-1)
    else:
        logp = jnp.log(jnp.clip(_apply_activation(preout, activation), EPS, 1.0))
    return _reduce_elementwise(-labels * logp, mask)


def sparse_mcxent(labels, preout, activation="softmax", mask=None) -> Array:
    """MCXENT with integer class-index labels (reference SPARSE_MCXENT)."""
    labels = labels.astype(jnp.int32)
    if labels.ndim == preout.ndim:  # (batch,1) style
        labels = labels.squeeze(-1)
    if activation in ("softmax", None):
        logp = jax.nn.log_softmax(preout, axis=-1)
    else:
        logp = jnp.log(jnp.clip(_apply_activation(preout, activation), EPS, 1.0))
    per = -jnp.take_along_axis(logp, labels[..., None], axis=-1).squeeze(-1)
    if mask is not None:
        m = mask
        while m.ndim > per.ndim:
            m = m.squeeze(-1)
        per = per * m
    axes = tuple(range(1, per.ndim))
    return jnp.sum(per, axis=axes) if axes else per


def negativeloglikelihood(labels, preout, activation="softmax", mask=None) -> Array:
    # Reference LossNegativeLogLikelihood == MCXENT for one-hot labels.
    return mcxent(labels, preout, activation, mask)


def kl_divergence(labels, preout, activation="softmax", mask=None) -> Array:
    out = jnp.clip(_apply_activation(preout, activation), EPS, 1.0)
    lab = jnp.clip(labels, EPS, 1.0)
    return _reduce_elementwise(labels * (jnp.log(lab) - jnp.log(out)), mask)


def cosine_proximity(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    if mask is not None:
        out = out * mask
        labels = labels * mask
    dot = jnp.sum(out * labels, axis=-1)
    no = jnp.sqrt(jnp.sum(out * out, axis=-1) + EPS)
    nl = jnp.sqrt(jnp.sum(labels * labels, axis=-1) + EPS)
    per = -(dot / (no * nl))
    axes = tuple(range(1, per.ndim))
    return jnp.sum(per, axis=axes) if axes else per


def hinge(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    # labels in {-1, +1} (reference LossHinge)
    return _reduce_elementwise(jnp.maximum(0.0, 1.0 - labels * out), mask)


def squared_hinge(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    return _reduce_elementwise(jnp.maximum(0.0, 1.0 - labels * out) ** 2, mask)


def poisson(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    out = jnp.maximum(out, EPS)
    return _reduce_elementwise(out - labels * jnp.log(out), mask)


def reconstruction_crossentropy(labels, preout, activation="sigmoid", mask=None) -> Array:
    out = jnp.clip(_apply_activation(preout, activation), EPS, 1 - EPS)
    per = -(labels * jnp.log(out) + (1 - labels) * jnp.log(1 - out))
    return _reduce_elementwise(per, mask)


def wasserstein(labels, preout, activation=None, mask=None) -> Array:
    out = _apply_activation(preout, activation)
    return _reduce_elementwise(labels * out, mask)


_REGISTRY: dict[str, Callable] = {
    "mse": mse,
    "squared_loss": mse,
    "l2": l2,
    "mae": mae,
    "mean_absolute_error": mae,
    "l1": l1,
    "mape": mape,
    "mean_absolute_percentage_error": mape,
    "msle": msle,
    "mean_squared_logarithmic_error": msle,
    "xent": xent,
    "mcxent": mcxent,
    "sparse_mcxent": sparse_mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "kl_divergence": kl_divergence,
    "kld": kl_divergence,
    "cosine_proximity": cosine_proximity,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "poisson": poisson,
    "reconstruction_crossentropy": reconstruction_crossentropy,
    "wasserstein": wasserstein,
}

LossLike = Union[str, Callable]


def get(name_or_fn: LossLike) -> Callable:
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown loss '{name_or_fn}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def names() -> list[str]:
    return sorted(_REGISTRY)
