"""AlexNet (reference ``zoo/model/AlexNet.java``: the dual-GPU 2012 net
flattened to one tower — conv11/4 + LRN + pool stem, 5 conv layers, two
4096 dense layers with dropout, softmax)."""

from __future__ import annotations

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    LocalResponseNormalization,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.updaters import Nesterovs


class AlexNet(ZooModel):
    name = "alexnet"

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, **kwargs):
        super().__init__(num_classes=num_classes, **kwargs)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", Nesterovs(1e-2, 0.9)))
            .weight_init("relu")
            .l2(5e-4)
            .list()
            .layer(ConvolutionLayer(n_out=96, kernel_size=11, stride=4,
                                    convolution_mode="same", activation="relu"))
            .layer(LocalResponseNormalization())
            .layer(SubsamplingLayer(kernel_size=3, stride=2))
            .layer(ConvolutionLayer(n_out=256, kernel_size=5, stride=1,
                                    convolution_mode="same", activation="relu",
                                    bias_init=1.0))
            .layer(LocalResponseNormalization())
            .layer(SubsamplingLayer(kernel_size=3, stride=2))
            .layer(ConvolutionLayer(n_out=384, kernel_size=3,
                                    convolution_mode="same", activation="relu"))
            .layer(ConvolutionLayer(n_out=384, kernel_size=3,
                                    convolution_mode="same", activation="relu",
                                    bias_init=1.0))
            .layer(ConvolutionLayer(n_out=256, kernel_size=3,
                                    convolution_mode="same", activation="relu",
                                    bias_init=1.0))
            .layer(SubsamplingLayer(kernel_size=3, stride=2))
            .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5, bias_init=1.0))
            .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5, bias_init=1.0))
            .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )
