"""Zoo prediction-label decoders (reference ``zoo/util/``:
``Labels``/``BaseLabels``/``ClassPrediction`` SPI with
``ImageNetLabels``, ``DarknetLabels``, ``COCOLabels``, ``VOCLabels``).

``decode_predictions(probs, n)`` turns a (batch, classes) probability
array into per-example top-n ``ClassPrediction(number, label,
probability)`` lists. COCO-80 and VOC-20 class lists are embedded; the
1000-class ImageNet/Darknet lists load from
``$DL4J_TPU_CACHE/labels/{imagenet,darknet}_labels.txt`` (one label per
line — this image has zero egress, so the standard files are cache-gated
like the dataset fetchers) and fall back to ``class_%04d`` placeholders
so decoding always works."""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.data.mnist import CACHE_DIR


class ClassPrediction:
    """(reference ``ClassPrediction``)"""

    def __init__(self, number: int, label: str, probability: float):
        self.number = int(number)
        self.label = label
        self.probability = float(probability)

    def __repr__(self):
        return (f"ClassPrediction(number={self.number}, "
                f"label={self.label!r}, probability={self.probability:.4f})")


class BaseLabels:
    """(reference ``BaseLabels``: label lookup + top-n decoding)"""

    def __init__(self, labels: List[str]):
        self._labels = list(labels)

    def get_label(self, n: int) -> str:
        return self._labels[n]

    def num_classes(self) -> int:
        return len(self._labels)

    def decode_predictions(self, predictions: np.ndarray, n: int = 5
                           ) -> List[List[ClassPrediction]]:
        p = np.asarray(predictions)
        if p.ndim == 1:
            p = p[None]
        if p.shape[1] != len(self._labels):
            raise ValueError(
                f"predictions have {p.shape[1]} classes, labels have "
                f"{len(self._labels)}")
        out = []
        for row in p:
            top = np.argsort(-row)[:n]
            out.append([ClassPrediction(int(i), self._labels[int(i)],
                                        float(row[int(i)]))
                        for i in top])
        return out


def _cached_or_placeholder(filename: str, n: int, what: str) -> List[str]:
    path = os.path.join(CACHE_DIR, "labels", filename)
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            labels = [line.strip() for line in f if line.strip()]
        if len(labels) != n:
            raise ValueError(
                f"{path} has {len(labels)} labels, expected {n}")
        return labels
    return [f"{what}_{i:04d}" for i in range(n)]


class ImageNetLabels(BaseLabels):
    """(reference ``ImageNetLabels`` — 1000 ILSVRC classes; real names
    from the cache-gated labels file)"""

    def __init__(self):
        super().__init__(_cached_or_placeholder(
            "imagenet_labels.txt", 1000, "class"))


class DarknetLabels(BaseLabels):
    """(reference ``DarknetLabels`` — Darknet19's 1000-class list)"""

    def __init__(self):
        super().__init__(_cached_or_placeholder(
            "darknet_labels.txt", 1000, "class"))


_COCO_80 = [
    "person", "bicycle", "car", "motorbike", "aeroplane", "bus", "train",
    "truck", "boat", "traffic light", "fire hydrant", "stop sign",
    "parking meter", "bench", "bird", "cat", "dog", "horse", "sheep",
    "cow", "elephant", "bear", "zebra", "giraffe", "backpack", "umbrella",
    "handbag", "tie", "suitcase", "frisbee", "skis", "snowboard",
    "sports ball", "kite", "baseball bat", "baseball glove", "skateboard",
    "surfboard", "tennis racket", "bottle", "wine glass", "cup", "fork",
    "knife", "spoon", "bowl", "banana", "apple", "sandwich", "orange",
    "broccoli", "carrot", "hot dog", "pizza", "donut", "cake", "chair",
    "sofa", "pottedplant", "bed", "diningtable", "toilet", "tvmonitor",
    "laptop", "mouse", "remote", "keyboard", "cell phone", "microwave",
    "oven", "toaster", "sink", "refrigerator", "book", "clock", "vase",
    "scissors", "teddy bear", "hair drier", "toothbrush",
]

_VOC_20 = [
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
]


class COCOLabels(BaseLabels):
    """(reference ``COCOLabels`` — the 80 COCO detection classes in
    Darknet order, as YOLO2 consumes)"""

    def __init__(self):
        super().__init__(list(_COCO_80))


class VOCLabels(BaseLabels):
    """(reference ``VOCLabels`` — the 20 PASCAL VOC classes, as TinyYOLO
    consumes)"""

    def __init__(self):
        super().__init__(list(_VOC_20))
