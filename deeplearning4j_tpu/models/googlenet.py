"""GoogLeNet / Inception-v1 (reference ``zoo/model/GoogLeNet.java``):
stem convs + 9 inception modules (1x1 / 3x3 / 5x5 / pool-proj branches
concatenated) + global average pool + softmax. Aux classifiers omitted
(inference parity; the reference zoo model trains the main head)."""

from __future__ import annotations

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    GlobalPoolingLayer,
    LocalResponseNormalization,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.updaters import Nesterovs

# (1x1, 3x3reduce, 3x3, 5x5reduce, 5x5, poolproj) per inception module
_INCEPTION = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


class GoogLeNet(ZooModel):
    name = "googlenet"

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, **kwargs):
        super().__init__(num_classes=num_classes, **kwargs)
        self.height, self.width, self.channels = height, width, channels

    def _conv(self, gb, name, inp, n_out, kernel, stride=1):
        gb.add_layer(name,
                     ConvolutionLayer(n_out=n_out, kernel_size=kernel,
                                      stride=stride, convolution_mode="same",
                                      activation="relu"), inp)
        return name

    def _inception(self, gb, name, inp, spec):
        c1, r3, c3, r5, c5, pp = spec
        b1 = self._conv(gb, f"{name}_1x1", inp, c1, 1)
        b3r = self._conv(gb, f"{name}_3x3r", inp, r3, 1)
        b3 = self._conv(gb, f"{name}_3x3", b3r, c3, 3)
        b5r = self._conv(gb, f"{name}_5x5r", inp, r5, 1)
        b5 = self._conv(gb, f"{name}_5x5", b5r, c5, 5)
        gb.add_layer(f"{name}_pool",
                     SubsamplingLayer(kernel_size=3, stride=1,
                                      convolution_mode="same"), inp)
        bp = self._conv(gb, f"{name}_poolproj", f"{name}_pool", pp, 1)
        gb.add_vertex(f"{name}_out", MergeVertex(), b1, b3, b5, bp)
        return f"{name}_out"

    def conf(self):
        gb = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", Nesterovs(1e-2, 0.9)))
            .weight_init("relu")
            .graph_builder()
            .add_inputs("input")
            .set_input_types(InputType.convolutional(self.height, self.width,
                                                     self.channels))
        )
        x = self._conv(gb, "stem1", "input", 64, 7, 2)
        gb.add_layer("pool1", SubsamplingLayer(kernel_size=3, stride=2,
                                               convolution_mode="same"), x)
        gb.add_layer("lrn1", LocalResponseNormalization(), "pool1")
        x = self._conv(gb, "stem2r", "lrn1", 64, 1)
        x = self._conv(gb, "stem2", x, 192, 3)
        gb.add_layer("lrn2", LocalResponseNormalization(), x)
        gb.add_layer("pool2", SubsamplingLayer(kernel_size=3, stride=2,
                                               convolution_mode="same"), "lrn2")
        x = "pool2"
        for name in ("3a", "3b"):
            x = self._inception(gb, f"inc{name}", x, _INCEPTION[name])
        gb.add_layer("pool3", SubsamplingLayer(kernel_size=3, stride=2,
                                               convolution_mode="same"), x)
        x = "pool3"
        for name in ("4a", "4b", "4c", "4d", "4e"):
            x = self._inception(gb, f"inc{name}", x, _INCEPTION[name])
        gb.add_layer("pool4", SubsamplingLayer(kernel_size=3, stride=2,
                                               convolution_mode="same"), x)
        x = "pool4"
        for name in ("5a", "5b"):
            x = self._inception(gb, f"inc{name}", x, _INCEPTION[name])
        gb.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        gb.add_layer("dropout", DenseLayer(n_out=1024, activation="relu",
                                           dropout=0.4), "avgpool")
        gb.add_layer("output",
                     OutputLayer(n_out=self.num_classes, activation="softmax",
                                 loss="mcxent"), "dropout")
        gb.set_outputs("output")
        return gb.build()
