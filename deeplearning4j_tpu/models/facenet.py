"""Face-embedding models (reference ``zoo/model/FaceNetNN4Small2.java``
and ``InceptionResNetV1.java``): inception-style trunks producing an
L2-normalized 128-d embedding trained with softmax + center loss.

Both are ComputationGraphs ending in
embedding-dense → L2NormalizeVertex → CenterLossOutputLayer, the
reference's training head (triplet mining is out of scope there too).
"""

from __future__ import annotations

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import (
    ElementWiseVertex,
    L2NormalizeVertex,
    MergeVertex,
    ScaleVertex,
)
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNormalization,
    CenterLossOutputLayer,
    ConvolutionLayer,
    DenseLayer,
    GlobalPoolingLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.updaters import Adam


class _FaceEmbeddingModel(ZooModel):
    embedding_size = 128

    def __init__(self, num_classes: int = 1000, height: int = 160,
                 width: int = 160, channels: int = 3,
                 embedding_size: int = 128, **kwargs):
        super().__init__(num_classes=num_classes, **kwargs)
        self.height, self.width, self.channels = height, width, channels
        self.embedding_size = int(embedding_size)

    def _conv_bn(self, gb, name, inp, n_out, kernel, stride=1):
        gb.add_layer(f"{name}_c",
                     ConvolutionLayer(n_out=n_out, kernel_size=kernel,
                                      stride=stride, convolution_mode="same",
                                      activation="identity", has_bias=False),
                     inp)
        gb.add_layer(f"{name}_bn", BatchNormalization(activation="relu"),
                     f"{name}_c")
        return f"{name}_bn"

    def _head(self, gb, trunk_out):
        gb.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), trunk_out)
        gb.add_layer("embedding",
                     DenseLayer(n_out=self.embedding_size,
                                activation="identity"), "avgpool")
        gb.add_vertex("l2norm", L2NormalizeVertex(), "embedding")
        gb.add_layer("output",
                     CenterLossOutputLayer(n_out=self.num_classes,
                                           activation="softmax", loss="mcxent",
                                           alpha=0.05, lambda_=2e-4), "l2norm")
        gb.set_outputs("output")


class FaceNetNN4Small2(_FaceEmbeddingModel):
    """nn4.small2 (reference ``FaceNetNN4Small2.java``): GoogLeNet-style
    inception modules shrunk for 96-160px faces."""

    name = "facenetnn4small2"

    # (1x1, 3x3r, 3x3, 5x5r, 5x5, poolproj)
    MODULES = (
        (64, 96, 128, 16, 32, 32),
        (64, 96, 128, 32, 64, 64),
        (128, 128, 256, 32, 64, 64),
        (256, 96, 384, 32, 128, 128),
    )

    def _inception(self, gb, name, inp, spec):
        c1, r3, c3, r5, c5, pp = spec
        b1 = self._conv_bn(gb, f"{name}_1x1", inp, c1, 1)
        b3 = self._conv_bn(gb, f"{name}_3x3", self._conv_bn(gb, f"{name}_3x3r", inp, r3, 1), c3, 3)
        b5 = self._conv_bn(gb, f"{name}_5x5", self._conv_bn(gb, f"{name}_5x5r", inp, r5, 1), c5, 5)
        gb.add_layer(f"{name}_pool",
                     SubsamplingLayer(kernel_size=3, stride=1,
                                      convolution_mode="same"), inp)
        bp = self._conv_bn(gb, f"{name}_pp", f"{name}_pool", pp, 1)
        gb.add_vertex(f"{name}_out", MergeVertex(), b1, b3, b5, bp)
        return f"{name}_out"

    def conf(self):
        gb = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", Adam(1e-3)))
            .weight_init("relu")
            .graph_builder()
            .add_inputs("input")
            .set_input_types(InputType.convolutional(self.height, self.width,
                                                     self.channels))
        )
        x = self._conv_bn(gb, "stem1", "input", 64, 7, 2)
        gb.add_layer("pool1", SubsamplingLayer(kernel_size=3, stride=2,
                                               convolution_mode="same"), x)
        x = self._conv_bn(gb, "stem2", "pool1", 192, 3)
        gb.add_layer("pool2", SubsamplingLayer(kernel_size=3, stride=2,
                                               convolution_mode="same"), x)
        x = "pool2"
        for i, spec in enumerate(self.MODULES):
            x = self._inception(gb, f"inc{i}", x, spec)
            if i in (1, 2):
                gb.add_layer(f"incpool{i}",
                             SubsamplingLayer(kernel_size=3, stride=2,
                                              convolution_mode="same"), x)
                x = f"incpool{i}"
        self._head(gb, x)
        return gb.build()


class InceptionResNetV1(_FaceEmbeddingModel):
    """(reference ``InceptionResNetV1.java``): inception-resnet blocks with
    scaled residual adds (A x5, B x10, C x5) + reductions."""

    name = "inceptionresnetv1"

    def _res_block(self, gb, name, inp, branches, n_ch, scale=0.17):
        """Concat branches → 1x1 up → scaled residual add → relu."""
        outs = []
        for bi, chain in enumerate(branches):
            x = inp
            for ci, (n_out, k) in enumerate(chain):
                x = self._conv_bn(gb, f"{name}_b{bi}c{ci}", x, n_out, k)
            outs.append(x)
        if len(outs) > 1:
            gb.add_vertex(f"{name}_cat", MergeVertex(), *outs)
            cat = f"{name}_cat"
        else:
            cat = outs[0]
        gb.add_layer(f"{name}_up",
                     ConvolutionLayer(n_out=n_ch, kernel_size=1,
                                      convolution_mode="same",
                                      activation="identity"), cat)
        gb.add_vertex(f"{name}_scale", ScaleVertex(scale), f"{name}_up")
        gb.add_vertex(f"{name}_add", ElementWiseVertex("add"), inp, f"{name}_scale")
        gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                     f"{name}_add")
        return f"{name}_relu"

    def conf(self):
        gb = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", Adam(1e-3)))
            .weight_init("relu")
            .graph_builder()
            .add_inputs("input")
            .set_input_types(InputType.convolutional(self.height, self.width,
                                                     self.channels))
        )
        # stem: 3 convs + pool → 256
        x = self._conv_bn(gb, "stem1", "input", 32, 3, 2)
        x = self._conv_bn(gb, "stem2", x, 64, 3)
        gb.add_layer("stem_pool", SubsamplingLayer(kernel_size=3, stride=2,
                                                   convolution_mode="same"), x)
        x = self._conv_bn(gb, "stem3", "stem_pool", 128, 1)
        x = self._conv_bn(gb, "stem4", x, 256, 3, 2)
        # 5x inception-resnet-A (on 256 ch)
        for i in range(5):
            x = self._res_block(
                gb, f"resA{i}", x,
                [[(32, 1)], [(32, 1), (32, 3)], [(32, 1), (32, 3), (32, 3)]],
                256, scale=0.17,
            )
        # reduction-A → 768
        x = self._conv_bn(gb, "redA", x, 768, 3, 2)
        # 10x inception-resnet-B
        for i in range(10):
            x = self._res_block(
                gb, f"resB{i}", x,
                [[(128, 1)], [(128, 1), (128, (1, 7)), (128, (7, 1))]],
                768, scale=0.10,
            )
        # reduction-B → 1280
        x = self._conv_bn(gb, "redB", x, 1280, 3, 2)
        # 5x inception-resnet-C
        for i in range(5):
            x = self._res_block(
                gb, f"resC{i}", x,
                [[(192, 1)], [(192, 1), (192, (1, 3)), (192, (3, 1))]],
                1280, scale=0.20,
            )
        self._head(gb, x)
        return gb.build()
