"""LeNet (reference ``zoo/model/LeNet.java``): conv5x5-20 → pool →
conv5x5-50 → pool → dense500 → softmax. The reference's MNIST smoke model
(BASELINE.json config #1)."""

from __future__ import annotations

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.updaters import Adam


class LeNet(ZooModel):
    name = "lenet"

    def __init__(self, num_classes: int = 10, height: int = 28, width: int = 28,
                 channels: int = 1, **kwargs):
        super().__init__(num_classes=num_classes, **kwargs)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", Adam(1e-3)))
            .weight_init("xavier")
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=5, stride=1,
                                    convolution_mode="same", activation="relu"))
            .layer(SubsamplingLayer(kernel_size=2, stride=2, pooling_type="max"))
            .layer(ConvolutionLayer(n_out=50, kernel_size=5, stride=1,
                                    convolution_mode="same", activation="relu"))
            .layer(SubsamplingLayer(kernel_size=2, stride=2, pooling_type="max"))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=self.num_classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )
