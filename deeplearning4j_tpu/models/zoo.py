"""ZooModel base (reference ``zoo/ZooModel.java:23``): pretrained
weight restore with the reference's download + checksum machinery
(``:40-62``) — URL registry per dataset, resumable atomic download into
the cache dir, sha256 gate with delete-on-mismatch. Environments without
egress stage artifacts into the cache (or pass ``path=``) and the same
verification path runs."""

from __future__ import annotations

import os
from typing import Optional, Sequence


def _fsync_path(path: str) -> None:
    """Durability barrier for a downloaded ``.part`` before its atomic
    promote — the ``chaos/fslayer`` discipline, local so the zoo stays
    importable without the chaos package's flight plumbing."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)

CACHE_DIR = os.environ.get(
    "DL4J_TPU_DATA", os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu")
)


class ZooModel:
    """Subclasses implement ``conf()`` returning a built configuration and
    set ``input_shape`` / ``num_classes``."""

    name: str = "zoo"

    def __init__(self, num_classes: int = 1000, seed: int = 123, **kwargs):
        self.num_classes = int(num_classes)
        self.seed = int(seed)
        self.kwargs = kwargs

    def conf(self):
        raise NotImplementedError

    def init(self):
        """Build + init the network."""
        conf = self.conf()
        # global knobs every zoo model honors even when its conf() builder
        # does not thread them explicitly (CLI --compute-dtype /
        # --remat-policy reach every architecture through kwargs)
        for knob in ("compute_dtype", "remat_policy"):
            v = self.kwargs.get(knob)
            if v == "float32" and knob == "compute_dtype":
                v = None  # fp32 is the default — don't switch on the
                # cast pipeline for no-op casts (TransformerLM convention)
            if v is not None and getattr(conf, "global_conf", None) is not None:
                setattr(conf.global_conf, knob, v)
        from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration

        if isinstance(conf, MultiLayerConfiguration):
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            return MultiLayerNetwork(conf).init()
        try:
            from deeplearning4j_tpu.nn.graph import ComputationGraph
        except ImportError as e:
            raise NotImplementedError(
                "ComputationGraph runtime not available in this build"
            ) from e
        return ComputationGraph(conf).init()

    #: serving hint: per-model sequence-length buckets for the inference
    #: engine's shape-bucket policy (``serving.BucketPolicy``). None for
    #: fixed-shape models (images, tabular); sequence models (rank-3
    #: inputs) list the time-dim pad targets so mixed-length serving
    #: traffic compiles a bounded program set. Read by
    #: :meth:`serving_bucket_policy` / the ``cli serve`` wiring.
    serving_seq_buckets: Optional[tuple] = None

    #: serving hint: whether this architecture tolerates int8 weight-only
    #: quantization of its dense/output heads (per-channel scales,
    #: nn/ops/int8_matmul.py). Actual use is OPT-IN — ``cli serve
    #: --int8-serving`` / ``InferenceEngine(int8_serving=True)`` — and a
    #: model class that sets this False refuses the flag (e.g. heads
    #: whose logit gaps sit inside the quantization error).
    serving_int8: bool = True

    def serving_input_shape(self) -> Optional[tuple]:
        """Per-example input shape for serving warmup, from the built
        conf's input type (None when the conf declares none)."""
        from deeplearning4j_tpu.serving.engine import conf_example_shape

        return conf_example_shape(self.conf())

    def serving_bucket_policy(self, max_batch: int = 32,
                              batch_buckets: Optional[Sequence[int]] = None):
        """The model's serving bucket policy: caller-chosen batch
        buckets plus this model's ``serving_seq_buckets`` hint."""
        from deeplearning4j_tpu.serving.buckets import BucketPolicy

        return BucketPolicy(batch_buckets=batch_buckets,
                            max_batch=max_batch,
                            seq_buckets=self.serving_seq_buckets)

    #: per-dataset sha256 hex digests; subclasses (or callers staging
    #: weights into the cache) fill this so ``init_pretrained`` verifies
    #: integrity like the reference's checksum gate (``ZooModel.java:40-62``)
    pretrained_checksums: dict = {}
    #: per-dataset weight-artifact URLs (reference ``pretrainedUrl``):
    #: fill to enable ``init_pretrained(dataset)`` with no ``path=`` —
    #: the artifact downloads into the cache dir with resume + sha256
    pretrained_urls: dict = {}

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        # each model class gets its OWN registries: writing
        # LeNet.pretrained_checksums[...] must never leak a digest into
        # ResNet50's lookups through the shared base-class dict
        if "pretrained_checksums" not in cls.__dict__:
            cls.pretrained_checksums = dict(cls.pretrained_checksums)
        if "pretrained_urls" not in cls.__dict__:
            cls.pretrained_urls = dict(cls.pretrained_urls)

    def pretrained_url(self, dataset: str = "imagenet") -> Optional[str]:
        """URL of the weight artifact for ``dataset`` (reference
        ``ZooModel.pretrainedUrl``); None when not published."""
        return self.pretrained_urls.get(dataset)

    def pretrained_path(self, dataset: str = "imagenet") -> str:
        return os.path.join(CACHE_DIR, "zoo", f"{self.name}_{dataset}.zip")

    @staticmethod
    def _sha256(path: str) -> str:
        import hashlib

        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()

    @staticmethod
    def _download(url: str, dest: str, timeout: float = 60.0) -> None:
        """Fetch ``url`` into ``dest``: partial content accumulates in a
        ``.part`` sidecar and resumes with an HTTP Range request (the
        reference's copyURLToFile has no resume; interrupted multi-GB
        weight pulls motivated adding it), then moves into place
        atomically. Egress failures raise with staging guidance rather
        than leaving a half-written dest."""
        import urllib.error
        import urllib.request

        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        part = dest + ".part"
        have = os.path.getsize(part) if os.path.exists(part) else 0
        req = urllib.request.Request(url)
        if have:
            req.add_header("Range", f"bytes={have}-")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                if have and resp.status != 206:
                    have = 0  # server ignored Range: restart from zero
                mode = "ab" if have else "wb"
                with open(part, mode) as f:
                    while True:
                        chunk = resp.read(1 << 20)
                        if not chunk:
                            break
                        f.write(chunk)
        except urllib.error.HTTPError as e:
            if e.code == 416 and have:
                # Range past EOF: the .part already holds the whole file
                # (crash between read loop and rename) — promote it; the
                # caller's checksum gate validates the bytes
                _fsync_path(part)
                os.replace(part, dest)
                return
            raise ConnectionError(
                f"Could not download pretrained weights from {url}: {e}. "
                f"If this environment has no egress, stage the artifact "
                f"at {dest} manually (partial progress kept at {part})."
            ) from e
        except (urllib.error.URLError, OSError) as e:
            raise ConnectionError(
                f"Could not download pretrained weights from {url}: {e}. "
                f"If this environment has no egress, stage the artifact "
                f"at {dest} manually (partial progress kept at {part})."
            ) from e
        # fsync the downloaded bytes before the atomic publish: a power
        # loss after the rename must never leave an empty cache entry
        # the checksum gate would have to re-download anyway
        _fsync_path(part)
        os.replace(part, dest)

    def init_pretrained(self, dataset: str = "imagenet",
                        path: Optional[str] = None,
                        checksum: Optional[str] = None):
        """Restore a pretrained checkpoint (reference ``initPretrained``,
        ``ZooModel.java:40-62``): resolve the cache path; when absent and
        ``pretrained_urls[dataset]`` is registered, download (resumable,
        atomic) into the cache; verify sha256; load.

        The weight artifact is the reference zip checkpoint layout
        (``ModelSerializer``: configuration.json + coefficients.bin [+
        updaterState.bin]). ``checksum`` (sha256 hex) overrides the
        per-class ``pretrained_checksums[dataset]`` entry; when either is
        present the file hash MUST match — like the reference, a
        mismatched download is deleted before raising so a retry
        re-fetches instead of re-failing on the same bytes."""
        explicit_path = path is not None
        path = path or self.pretrained_path(dataset)
        downloaded = False  # True ONLY when THIS call fetched the file —
        # a user-staged cache artifact must never be deleted on mismatch
        if not os.path.exists(path):
            url = self.pretrained_url(dataset)
            if url is None or explicit_path:
                raise FileNotFoundError(
                    f"No pretrained weights at {path} and no URL "
                    f"registered for {type(self).__name__}[{dataset!r}] "
                    "(pretrained_urls). Stage a checkpoint there or "
                    "register its URL.")
            self._download(url, path)
            downloaded = True
        expect = checksum or self.pretrained_checksums.get(dataset)
        if expect:
            actual = self._sha256(path)
            if actual != expect.lower():
                if downloaded:
                    os.remove(path)  # reference semantics: clean up the
                    # bad artifact so the next call re-downloads
                raise ValueError(
                    f"Checksum mismatch for {path}: expected {expect}, "
                    f"got {actual} — refusing to load a corrupt/"
                    "substituted pretrained artifact"
                    + (" (deleted; retry will re-download)"
                       if downloaded else ""))
        from deeplearning4j_tpu.train.model_serializer import ModelGuesser

        return ModelGuesser.load_model_guess(path)

    initPretrained = init_pretrained
