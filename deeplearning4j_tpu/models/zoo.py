"""ZooModel base (reference ``zoo/ZooModel.java:23``; pretrained download
+ checksum at ``:40-62`` is gated here — no egress in this environment, so
``init_pretrained`` looks only in the local cache dir)."""

from __future__ import annotations

import os
from typing import Optional, Sequence

CACHE_DIR = os.environ.get(
    "DL4J_TPU_DATA", os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu")
)


class ZooModel:
    """Subclasses implement ``conf()`` returning a built configuration and
    set ``input_shape`` / ``num_classes``."""

    name: str = "zoo"

    def __init__(self, num_classes: int = 1000, seed: int = 123, **kwargs):
        self.num_classes = int(num_classes)
        self.seed = int(seed)
        self.kwargs = kwargs

    def conf(self):
        raise NotImplementedError

    def init(self):
        """Build + init the network."""
        conf = self.conf()
        # global knobs every zoo model honors even when its conf() builder
        # does not thread them explicitly (CLI --compute-dtype /
        # --remat-policy reach every architecture through kwargs)
        for knob in ("compute_dtype", "remat_policy"):
            v = self.kwargs.get(knob)
            if v == "float32" and knob == "compute_dtype":
                v = None  # fp32 is the default — don't switch on the
                # cast pipeline for no-op casts (TransformerLM convention)
            if v is not None and getattr(conf, "global_conf", None) is not None:
                setattr(conf.global_conf, knob, v)
        from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration

        if isinstance(conf, MultiLayerConfiguration):
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

            return MultiLayerNetwork(conf).init()
        try:
            from deeplearning4j_tpu.nn.graph import ComputationGraph
        except ImportError as e:
            raise NotImplementedError(
                "ComputationGraph runtime not available in this build"
            ) from e
        return ComputationGraph(conf).init()

    #: per-dataset sha256 hex digests; subclasses (or callers staging
    #: weights into the cache) fill this so ``init_pretrained`` verifies
    #: integrity like the reference's checksum gate (``ZooModel.java:40-62``)
    pretrained_checksums: dict = {}

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        # each model class gets its OWN registry: writing
        # LeNet.pretrained_checksums[...] must never leak a digest into
        # ResNet50's lookups through the shared base-class dict
        if "pretrained_checksums" not in cls.__dict__:
            cls.pretrained_checksums = dict(cls.pretrained_checksums)

    def pretrained_path(self, dataset: str = "imagenet") -> str:
        return os.path.join(CACHE_DIR, "zoo", f"{self.name}_{dataset}.zip")

    @staticmethod
    def _sha256(path: str) -> str:
        import hashlib

        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()

    def init_pretrained(self, dataset: str = "imagenet",
                        path: Optional[str] = None,
                        checksum: Optional[str] = None):
        """Restore a pretrained checkpoint (reference ``initPretrained``
        + its checksum verification, ``ZooModel.java:40-62``; the
        download half is impossible without egress, so weights come from
        ``path`` or the local cache dir).

        The weight artifact is the reference zip checkpoint layout
        (``ModelSerializer``: configuration.json + coefficients.bin [+
        updaterState.bin]). ``checksum`` (sha256 hex) overrides the
        per-class ``pretrained_checksums[dataset]`` entry; when either is
        present the file hash MUST match — a corrupt/wrong artifact
        raises instead of silently loading."""
        path = path or self.pretrained_path(dataset)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"No pretrained weights at {path}. This environment has no "
                "network egress; place a checkpoint there manually."
            )
        expect = checksum or self.pretrained_checksums.get(dataset)
        if expect:
            actual = self._sha256(path)
            if actual != expect.lower():
                raise ValueError(
                    f"Checksum mismatch for {path}: expected {expect}, "
                    f"got {actual} — refusing to load a corrupt/substituted "
                    "pretrained artifact (reference ZooModel deletes and "
                    "re-downloads; offline, re-stage the file)")
        from deeplearning4j_tpu.train.model_serializer import ModelGuesser

        return ModelGuesser.load_model_guess(path)

    initPretrained = init_pretrained
