"""ModelSelector + PretrainedType (reference ``zoo/ModelSelector.java``,
``zoo/PretrainedType.java``): name-based zoo lookup."""

from __future__ import annotations

from typing import Dict, Type

from deeplearning4j_tpu.models.alexnet import AlexNet
from deeplearning4j_tpu.models.darknet import TinyYOLO, YOLO2, Darknet19
from deeplearning4j_tpu.models.facenet import FaceNetNN4Small2, InceptionResNetV1
from deeplearning4j_tpu.models.googlenet import GoogLeNet
from deeplearning4j_tpu.models.lenet import LeNet
from deeplearning4j_tpu.models.resnet50 import ResNet50
from deeplearning4j_tpu.models.simplecnn import SimpleCNN
from deeplearning4j_tpu.models.textgen_lstm import TextGenerationLSTM
from deeplearning4j_tpu.models.vgg import VGG16, VGG19
from deeplearning4j_tpu.models.zoo import ZooModel


class PretrainedType:
    IMAGENET = "imagenet"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"


ZOO: Dict[str, Type[ZooModel]] = {
    m.name: m
    for m in (
        AlexNet, Darknet19, FaceNetNN4Small2, GoogLeNet, InceptionResNetV1,
        LeNet, ResNet50, SimpleCNN, TextGenerationLSTM, TinyYOLO, VGG16,
        VGG19, YOLO2,
    )
}


class UnknownZooModelError(KeyError):
    """Requested zoo model name is not registered. Subclasses
    ``KeyError`` for dict-style handler compat; typed so production
    callers never see a bare builtin."""


class ModelSelector:
    @staticmethod
    def select(name: str, **kwargs) -> ZooModel:
        key = name.lower()
        if key not in ZOO:
            raise UnknownZooModelError(
                f"Unknown zoo model '{name}'; available: {sorted(ZOO)}")
        return ZOO[key](**kwargs)

    @staticmethod
    def available() -> list:
        return sorted(ZOO)

    @staticmethod
    def load_or_init(source: str, **kwargs):
        """Resolve ``source`` into an initialized network — the serving
        CLI's single entry for "what model do I serve":

        - a **zoo model name** → fresh ``init()`` (smoke/warmup runs);
        - a **checkpoint zip** → ``ModelGuesser.load_model_guess``
          (type sniffed from the zip);
        - a **checkpoint directory** → the newest VALID checkpoint via
          ``train.faults.load_latest_valid`` (corrupt/truncated newest
          falls back to the previous good one).

        Returns ``(model, origin)`` where origin is the zoo name or the
        resolved checkpoint path."""
        import os

        key = source.lower()
        if key in ZOO:
            return ZOO[key](**kwargs).init(), key
        if os.path.isdir(source):
            from deeplearning4j_tpu.train.faults import load_latest_valid

            model, path = load_latest_valid(source)
            return model, path
        if os.path.isfile(source):
            from deeplearning4j_tpu.train.model_serializer import ModelGuesser

            return ModelGuesser.load_model_guess(source), source
        raise ValueError(
            f"model source {source!r} is neither a zoo model "
            f"({sorted(ZOO)}), a checkpoint zip, nor a checkpoint directory")
