"""ModelSelector + PretrainedType (reference ``zoo/ModelSelector.java``,
``zoo/PretrainedType.java``): name-based zoo lookup."""

from __future__ import annotations

from typing import Dict, Type

from deeplearning4j_tpu.models.alexnet import AlexNet
from deeplearning4j_tpu.models.darknet import TinyYOLO, YOLO2, Darknet19
from deeplearning4j_tpu.models.facenet import FaceNetNN4Small2, InceptionResNetV1
from deeplearning4j_tpu.models.googlenet import GoogLeNet
from deeplearning4j_tpu.models.lenet import LeNet
from deeplearning4j_tpu.models.resnet50 import ResNet50
from deeplearning4j_tpu.models.simplecnn import SimpleCNN
from deeplearning4j_tpu.models.textgen_lstm import TextGenerationLSTM
from deeplearning4j_tpu.models.vgg import VGG16, VGG19
from deeplearning4j_tpu.models.zoo import ZooModel


class PretrainedType:
    IMAGENET = "imagenet"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"


ZOO: Dict[str, Type[ZooModel]] = {
    m.name: m
    for m in (
        AlexNet, Darknet19, FaceNetNN4Small2, GoogLeNet, InceptionResNetV1,
        LeNet, ResNet50, SimpleCNN, TextGenerationLSTM, TinyYOLO, VGG16,
        VGG19, YOLO2,
    )
}


class ModelSelector:
    @staticmethod
    def select(name: str, **kwargs) -> ZooModel:
        key = name.lower()
        if key not in ZOO:
            raise KeyError(f"Unknown zoo model '{name}'; available: {sorted(ZOO)}")
        return ZOO[key](**kwargs)

    @staticmethod
    def available() -> list:
        return sorted(ZOO)
