"""Darknet family (reference ``zoo/model/Darknet19.java``,
``TinyYOLO.java``, ``YOLO2.java``).

- Darknet19: 19-conv classifier (BN + leaky-relu, 1x1 bottlenecks),
  1x1 conv to classes + global average pool + softmax.
- TinyYOLO: tiny-darknet trunk (convs 16..1024 with maxpools) + 1x1
  detection head + Yolo2OutputLayer.
- YOLO2: darknet19 trunk + passthrough route (SpaceToDepth of an earlier
  feature map concatenated with the deep path — reference uses the same
  reorg trick) + detection head.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    ConvolutionLayer,
    GlobalPoolingLayer,
    LossLayer,
    SpaceToDepthLayer,
    SubsamplingLayer,
    Yolo2OutputLayer,
)
from deeplearning4j_tpu.updaters import Adam, Nesterovs

# reference TinyYOLO/YOLO2 anchor priors (grid units, VOC-flavored)
TINY_YOLO_PRIORS = [[1.08, 1.19], [3.42, 4.41], [6.63, 11.38],
                    [9.42, 5.11], [16.62, 10.52]]
YOLO2_PRIORS = [[0.57273, 0.677385], [1.87446, 2.06253], [3.33843, 5.47434],
                [7.88282, 3.52778], [9.77052, 9.16828]]


def _conv_bn_leaky(n_out, kernel):
    """Darknet building block: conv (no bias) → BN → leaky relu."""
    return [
        ConvolutionLayer(n_out=n_out, kernel_size=kernel,
                         convolution_mode="same", activation="identity",
                         has_bias=False),
        BatchNormalization(activation="leakyrelu"),
    ]


class Darknet19(ZooModel):
    name = "darknet19"

    # (channels, kernel) runs separated by maxpools — the 19-conv layout
    BLOCKS = (
        [(32, 3)],
        [(64, 3)],
        [(128, 3), (64, 1), (128, 3)],
        [(256, 3), (128, 1), (256, 3)],
        [(512, 3), (256, 1), (512, 3), (256, 1), (512, 3)],
        [(1024, 3), (512, 1), (1024, 3), (512, 1), (1024, 3)],
    )

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, **kwargs):
        super().__init__(num_classes=num_classes, **kwargs)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", Nesterovs(1e-3, 0.9)))
            .weight_init("relu")
            .list()
        )
        for bi, block in enumerate(self.BLOCKS):
            if bi > 0:
                b = b.layer(SubsamplingLayer(kernel_size=2, stride=2))
            for n_out, k in block:
                for layer in _conv_bn_leaky(n_out, k):
                    b = b.layer(layer)
        return (
            b.layer(ConvolutionLayer(n_out=self.num_classes, kernel_size=1,
                                     convolution_mode="same",
                                     activation="identity"))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(LossLayer(loss="mcxent", activation="softmax"))
            .set_input_type(InputType.convolutional(self.height, self.width,
                                                    self.channels))
            .build()
        )


class TinyYOLO(ZooModel):
    name = "tinyyolo"

    def __init__(self, num_classes: int = 20, height: int = 416,
                 width: int = 416, channels: int = 3, priors=None, **kwargs):
        super().__init__(num_classes=num_classes, **kwargs)
        self.height, self.width, self.channels = height, width, channels
        self.priors = priors if priors is not None else TINY_YOLO_PRIORS

    def conf(self):
        B = len(self.priors)
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", Adam(1e-3)))
            .weight_init("relu")
            .list()
        )
        # tiny-darknet trunk: 16..512 with /2 pools, then 1024s at stride 1
        for i, n_out in enumerate((16, 32, 64, 128, 256, 512)):
            for layer in _conv_bn_leaky(n_out, 3):
                b = b.layer(layer)
            stride = 2 if i < 5 else 1
            b = b.layer(SubsamplingLayer(kernel_size=2, stride=stride,
                                         convolution_mode="same"))
        for n_out in (1024, 1024):
            for layer in _conv_bn_leaky(n_out, 3):
                b = b.layer(layer)
        return (
            b.layer(ConvolutionLayer(n_out=B * (5 + self.num_classes),
                                     kernel_size=1, convolution_mode="same",
                                     activation="identity"))
            .layer(Yolo2OutputLayer(bounding_box_priors=self.priors))
            .set_input_type(InputType.convolutional(self.height, self.width,
                                                    self.channels))
            .build()
        )


class YOLO2(ZooModel):
    name = "yolo2"

    def __init__(self, num_classes: int = 20, height: int = 416,
                 width: int = 416, channels: int = 3, priors=None, **kwargs):
        super().__init__(num_classes=num_classes, **kwargs)
        self.height, self.width, self.channels = height, width, channels
        self.priors = priors if priors is not None else YOLO2_PRIORS

    def _block(self, gb, name, inp, specs):
        x = inp
        for i, (n_out, k) in enumerate(specs):
            gb.add_layer(f"{name}_c{i}",
                         ConvolutionLayer(n_out=n_out, kernel_size=k,
                                          convolution_mode="same",
                                          activation="identity",
                                          has_bias=False), x)
            gb.add_layer(f"{name}_b{i}",
                         BatchNormalization(activation="leakyrelu"),
                         f"{name}_c{i}")
            x = f"{name}_b{i}"
        return x

    def conf(self):
        B = len(self.priors)
        gb = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", Adam(1e-3)))
            .weight_init("relu")
            .graph_builder()
            .add_inputs("input")
            .set_input_types(InputType.convolutional(self.height, self.width,
                                                     self.channels))
        )
        x = self._block(gb, "b1", "input", [(32, 3)])
        for bi, block in enumerate((
            [(64, 3)],
            [(128, 3), (64, 1), (128, 3)],
            [(256, 3), (128, 1), (256, 3)],
            [(512, 3), (256, 1), (512, 3), (256, 1), (512, 3)],
        )):
            gb.add_layer(f"pool{bi}", SubsamplingLayer(kernel_size=2, stride=2), x)
            x = self._block(gb, f"b{bi + 2}", f"pool{bi}", block)
        route = x  # 512-ch map at stride 16 — the passthrough source
        gb.add_layer("pool5", SubsamplingLayer(kernel_size=2, stride=2), x)
        x = self._block(gb, "b6", "pool5",
                        [(1024, 3), (512, 1), (1024, 3), (512, 1), (1024, 3)])
        x = self._block(gb, "head", x, [(1024, 3), (1024, 3)])
        # passthrough: stride-16 features reorged to stride 32 and concatenated
        gb.add_layer("reorg", SpaceToDepthLayer(block_size=2), route)
        gb.add_vertex("route_cat", MergeVertex(), "reorg", x)
        x = self._block(gb, "fuse", "route_cat", [(1024, 3)])
        gb.add_layer("det_head",
                     ConvolutionLayer(n_out=B * (5 + self.num_classes),
                                      kernel_size=1, convolution_mode="same",
                                      activation="identity"), x)
        gb.add_layer("yolo", Yolo2OutputLayer(bounding_box_priors=self.priors),
                     "det_head")
        gb.set_outputs("yolo")
        return gb.build()
