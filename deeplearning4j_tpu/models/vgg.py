"""VGG16 / VGG19 (reference ``zoo/model/VGG16.java`` / ``VGG19.java``:
3x3 conv blocks [64,128,256,512,512] + two 4096 dense + softmax)."""

from __future__ import annotations

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.updaters import Nesterovs


class _VGG(ZooModel):
    block_convs = ()  # convs per block; channels fixed at (64,128,256,512,512)

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, **kwargs):
        super().__init__(num_classes=num_classes, **kwargs)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", Nesterovs(1e-2, 0.9)))
            .weight_init("relu")
            .list()
        )
        for n_out, reps in zip((64, 128, 256, 512, 512), self.block_convs):
            for _ in range(reps):
                b = b.layer(ConvolutionLayer(n_out=n_out, kernel_size=3,
                                             convolution_mode="same",
                                             activation="relu"))
            b = b.layer(SubsamplingLayer(kernel_size=2, stride=2))
        return (
            b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )


class VGG16(_VGG):
    name = "vgg16"
    block_convs = (2, 2, 3, 3, 3)


class VGG19(_VGG):
    name = "vgg19"
    block_convs = (2, 2, 4, 4, 4)
