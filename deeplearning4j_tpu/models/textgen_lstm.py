"""TextGenerationLSTM (reference ``zoo/model/TextGenerationLSTM.java``:
char-level language model — two stacked (Graves)LSTM layers + per-timestep
softmax output, trained with truncated BPTT)."""

from __future__ import annotations

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
from deeplearning4j_tpu.updaters import RmsProp


class TextGenerationLSTM(ZooModel):
    name = "textgenlstm"

    # serving hint: char sequences arrive at arbitrary lengths; pad the
    # time dim to these buckets (masked — padded steps are dead) so the
    # inference engine compiles a bounded program set
    serving_seq_buckets = (8, 16, 32, 64)

    def __init__(self, num_classes: int = 77, units: int = 256,
                 max_length: int = 40, **kwargs):
        # num_classes = vocabulary (character set) size
        super().__init__(num_classes=num_classes, **kwargs)
        self.units = int(units)
        self.max_length = int(max_length)

    def conf(self):
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", RmsProp(1e-2)))
            .weight_init("xavier")
            .list()
            .layer(GravesLSTM(n_out=self.units, activation="tanh"))
            .layer(GravesLSTM(n_out=self.units, activation="tanh"))
            .layer(RnnOutputLayer(n_out=self.num_classes, activation="softmax",
                                  loss="mcxent"))
            .backprop_type("tbptt", self.max_length, self.max_length)
            .set_input_type(InputType.recurrent(self.num_classes))
            .build()
        )
