"""ResNet-50 (reference ``zoo/model/ResNet50.java``): bottleneck residual
graph — stem conv7/2 + maxpool, stages of [3,4,6,3] bottleneck blocks,
global average pool, softmax. The north-star throughput model
(BASELINE.md: ResNet-50 images/sec/chip).

TPU notes: all convs are NHWC with fused BN→relu epilogues (XLA fuses
them into the conv); the residual adds are ElementWiseVertex nodes in one
jitted graph — no per-block dispatch.
"""

from __future__ import annotations

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    GlobalPoolingLayer,
    OutputLayer,
    SpaceToDepthLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.updaters import Nesterovs


class ResNet50(ZooModel):
    name = "resnet50"

    # (blocks, bottleneck width); output channels = 4x width
    STAGES = ((3, 64), (4, 128), (6, 256), (3, 512))

    def __init__(self, num_classes: int = 1000, height: int = 224,
                 width: int = 224, channels: int = 3, **kwargs):
        super().__init__(num_classes=num_classes, **kwargs)
        self.height, self.width, self.channels = height, width, channels

    def _conv_bn(self, gb, name, inp, n_out, kernel, stride=1, relu=True):
        gb.add_layer(f"{name}_conv",
                     ConvolutionLayer(n_out=n_out, kernel_size=kernel,
                                      stride=stride, convolution_mode="same",
                                      activation="identity", has_bias=False),
                     inp)
        gb.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
        if relu:
            gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                         f"{name}_bn")
            return f"{name}_relu"
        return f"{name}_bn"

    def _bottleneck(self, gb, name, inp, width, stride, project):
        """1x1 reduce → 3x3 → 1x1 expand (+ identity/projection shortcut).

        With ``fused_pallas=True`` the whole block becomes ONE
        FusedResNetBottleneck vertex driving the Pallas fused
        conv+BN+ReLU kernels (compile-probe-gated; falls back to an
        identical XLA composition — VERDICT r3 item 1)."""
        if self.kwargs.get("fused_pallas"):
            from deeplearning4j_tpu.nn.conf.layers import (
                FusedResNetBottleneck,
            )

            gb.add_layer(name, FusedResNetBottleneck(
                width=width, stride=stride, project=project), inp)
            return name
        a = self._conv_bn(gb, f"{name}_a", inp, width, 1, stride)
        b = self._conv_bn(gb, f"{name}_b", a, width, 3, 1)
        c = self._conv_bn(gb, f"{name}_c", b, 4 * width, 1, 1, relu=False)
        if project:
            sc = self._conv_bn(gb, f"{name}_proj", inp, 4 * width, 1, stride,
                               relu=False)
        else:
            sc = inp
        gb.add_vertex(f"{name}_add", ElementWiseVertex("add"), c, sc)
        gb.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                     f"{name}_add")
        return f"{name}_out"

    def conf(self):
        gb = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", Nesterovs(1e-1, 0.9)))
            .weight_init("relu")
            .l2(1e-4)
            .compute_dtype(self.kwargs.get("compute_dtype"))
            .graph_builder()
            .add_inputs("input")
            .set_input_types(InputType.convolutional(self.height, self.width,
                                                     self.channels))
        )
        if self.kwargs.get("stem_space_to_depth"):
            # MLPerf-style TPU stem: 2x2 space-to-depth moves the 3-channel
            # input to 12 channels at half resolution, and the 7x7/2 conv
            # becomes an equivalent-receptive-field 4x4/1 conv — far better
            # MXU lane utilisation than C_in=3 (the 7x7 kernel zero-pads to
            # 8x8 = 4x4 on the s2d grid). Same 112x112x64 stem output.
            gb.add_layer("stem_s2d", SpaceToDepthLayer(block_size=2), "input")
            x = self._conv_bn(gb, "stem", "stem_s2d", 64, 4, 1)
        else:
            x = self._conv_bn(gb, "stem", "input", 64, 7, 2)
        gb.add_layer("stem_pool",
                     SubsamplingLayer(kernel_size=3, stride=2,
                                      convolution_mode="same"), x)
        x = "stem_pool"
        for si, (blocks, width) in enumerate(self.STAGES):
            for bi in range(blocks):
                stride = 2 if (bi == 0 and si > 0) else 1
                x = self._bottleneck(gb, f"s{si}b{bi}", x, width, stride,
                                     project=(bi == 0))
        gb.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        gb.add_layer("output",
                     OutputLayer(n_out=self.num_classes, activation="softmax",
                                 loss="mcxent"), "avgpool")
        gb.set_outputs("output")
        return gb.build()
