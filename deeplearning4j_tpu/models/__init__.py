"""Model zoo (reference ``deeplearning4j-zoo``: 13 architectures built
programmatically, ``zoo/model/*.java``)."""

from deeplearning4j_tpu.models.alexnet import AlexNet
from deeplearning4j_tpu.models.labels import (
    BaseLabels,
    COCOLabels,
    ClassPrediction,
    DarknetLabels,
    ImageNetLabels,
    VOCLabels,
)
from deeplearning4j_tpu.models.darknet import TinyYOLO, YOLO2, Darknet19
from deeplearning4j_tpu.models.facenet import FaceNetNN4Small2, InceptionResNetV1
from deeplearning4j_tpu.models.googlenet import GoogLeNet
from deeplearning4j_tpu.models.lenet import LeNet
from deeplearning4j_tpu.models.resnet50 import ResNet50
from deeplearning4j_tpu.models.selector import ZOO, ModelSelector, PretrainedType
from deeplearning4j_tpu.models.simplecnn import SimpleCNN
from deeplearning4j_tpu.models.textgen_lstm import TextGenerationLSTM
from deeplearning4j_tpu.models.transformer_lm import TransformerLM
from deeplearning4j_tpu.models.vgg import VGG16, VGG19
from deeplearning4j_tpu.models.zoo import ZooModel

__all__ = [
    "ZooModel", "ModelSelector", "PretrainedType", "ZOO",
    "AlexNet", "Darknet19", "FaceNetNN4Small2", "GoogLeNet",
    "InceptionResNetV1", "LeNet", "ResNet50", "SimpleCNN",
    "TextGenerationLSTM", "TinyYOLO", "VGG16", "VGG19", "YOLO2",
    "TransformerLM",
    "BaseLabels", "ClassPrediction", "ImageNetLabels", "DarknetLabels",
    "COCOLabels", "VOCLabels",
]
