"""Model zoo (reference ``deeplearning4j-zoo``: 13 architectures built
programmatically, ``zoo/model/*.java``)."""

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.models.lenet import LeNet
from deeplearning4j_tpu.models.simplecnn import SimpleCNN

__all__ = ["ZooModel", "LeNet", "SimpleCNN"]
