"""TransformerLM: GPT-style causal language model — the flagship
distributed-training model.

No reference analog (the reference predates transformers; SURVEY.md §2.5);
this is the mandated new long-context/distributed capability. The model is
deliberately built on an explicit stacked-parameter pytree rather than the
layer-list runtime:

- blocks are IDENTICAL TransformerBlocks whose params are stacked along a
  leading (n_layers,) axis → single-device forward is one ``lax.scan``
  (compile time O(1) in depth), and the same stacked axis shards over the
  mesh "pipe" axis for pipeline parallelism;
- the time axis shards over "seq" (ring attention), batch over "data",
  head/FFN dims over "model" (Megatron column→row split);
- see parallel/transformer.py for the distributed step.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf.layers.attention import (
    TransformerBlock,
    _layer_norm,
    dense_attention,
)

Array = jax.Array


class TransformerLMConfig:
    def __init__(self, vocab_size: int, d_model: int = 256, n_heads: int = 4,
                 n_layers: int = 4, mlp_ratio: int = 4, max_length: int = 512,
                 seed: int = 0, n_experts: int = 0, top_k: int = 2,
                 capacity_factor: float = 1.25, aux_loss_weight: float = 1e-2,
                 compute_dtype: Optional[str] = None,
                 fused_qkv: bool = False):
        if d_model % n_heads:
            raise ValueError("d_model must be divisible by n_heads")
        self.vocab_size = int(vocab_size)
        self.d_model = int(d_model)
        self.n_heads = int(n_heads)
        self.n_layers = int(n_layers)
        self.mlp_ratio = int(mlp_ratio)
        self.max_length = int(max_length)
        self.seed = int(seed)
        # MoE: n_experts > 0 replaces every block's dense FFN with a
        # GShard dense-dispatch mixture (homogeneous stack keeps the
        # scan/pipeline param layout); 0 = dense
        self.n_experts = int(n_experts)
        self.top_k = int(top_k)
        self.capacity_factor = float(capacity_factor)
        self.aux_loss_weight = float(aux_loss_weight)
        # mixed precision (same scheme as the layer stack's compute_dtype:
        # fp32 master params/updater/layernorm/softmax, bf16 matmuls and
        # carried activations). None/"float32" = uniform fp32.
        if compute_dtype not in (None, "float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype must be None, 'float32' or 'bfloat16', got "
                f"{compute_dtype!r}"
            )
        self.compute_dtype = None if compute_dtype == "float32" else compute_dtype
        # fused_qkv: compute Q,K,V as ONE (d, 3d) matmul per block instead
        # of three (d, d) dots — bitwise-identical outputs (each output
        # column block sees only its own weight block), but the activation
        # is read from HBM once instead of three times. Param layout is
        # UNCHANGED (Wq/Wk/Wv stay separate; the concat happens in-step),
        # so checkpoints, TP pspecs and the decode path are unaffected.
        # Opt-in pending hardware measurement (scripts/lm_perf_sweep.py).
        self.fused_qkv = bool(fused_qkv)

    def to_dict(self):
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


def init_params(cfg: TransformerLMConfig, rng: Optional[Array] = None,
                dtype=jnp.float32) -> Dict[str, Array]:
    """Stacked-parameter pytree: block params have leading (n_layers,)."""
    rng = rng if rng is not None else jax.random.PRNGKey(cfg.seed)
    d, h = cfg.d_model, cfg.d_model * cfg.mlp_ratio
    L, V = cfg.n_layers, cfg.vocab_size
    ks = jax.random.split(rng, 9)

    def w(key, shape, fan_in):
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)

    blocks = {
        "ln1_g": jnp.ones((L, d), dtype), "ln1_b": jnp.zeros((L, d), dtype),
        "Wq": w(ks[2], (L, d, d), d), "Wk": w(ks[3], (L, d, d), d),
        "Wv": w(ks[4], (L, d, d), d), "Wo": w(ks[5], (L, d, d), d),
        "bo": jnp.zeros((L, d), dtype),
        "ln2_g": jnp.ones((L, d), dtype), "ln2_b": jnp.zeros((L, d), dtype),
    }
    if cfg.n_experts > 0:
        E = cfg.n_experts
        kg, k1, k2 = jax.random.split(ks[6], 3)
        blocks.update({
            "Wg": w(kg, (L, d, E), d),
            "W1": w(k1, (L, E, d, h), d), "b1": jnp.zeros((L, E, h), dtype),
            "W2": w(k2, (L, E, h, d), h), "b2": jnp.zeros((L, E, d), dtype),
        })
    else:
        blocks.update({
            "W1": w(ks[6], (L, d, h), d), "b1": jnp.zeros((L, h), dtype),
            "W2": w(ks[7], (L, h, d), h), "b2": jnp.zeros((L, d), dtype),
        })
    return {
        "embed": 0.02 * jax.random.normal(ks[0], (V, d), dtype),
        "pos": 0.02 * jax.random.normal(ks[1], (cfg.max_length, d), dtype),
        "blocks": blocks,
        "lnf_g": jnp.ones((d,), dtype), "lnf_b": jnp.zeros((d,), dtype),
        "head": w(ks[8], (d, V), d),
    }


def _moe_capacity(cfg: TransformerLMConfig, n_tokens: int) -> int:
    from deeplearning4j_tpu.nn.conf.layers.moe import moe_capacity

    return moe_capacity(n_tokens, cfg.capacity_factor, cfg.top_k,
                        cfg.n_experts)


def _cdtype(cfg: TransformerLMConfig):
    return jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else None


def _ln(x, g, b, cd):
    """LayerNorm with fp32 statistics under mixed precision (the same
    exemption the layer stack's norm layers use)."""
    if cd is None:
        return _layer_norm(x, g, b)
    return _layer_norm(x.astype(jnp.float32), g, b).astype(cd)


def block_apply(cfg: TransformerLMConfig, bp: Dict[str, Array], x: Array,
                attn_fn=None, tp_axis: Optional[str] = None,
                expert_axis: Optional[str] = None):
    """One pre-LN block on (b, T, d); bp holds UNSTACKED (single-layer)
    params. ``attn_fn`` defaults to dense attention (ring under SP).
    Dense FFN → returns x. MoE (cfg.n_experts > 0) → returns (x, aux).
    Under compute_dtype="bfloat16": matmul operands and the carried
    activation are bf16; layernorm statistics fp32.

    ``tp_axis``/``expert_axis`` engage MANUAL tensor/expert parallelism
    for use inside a fully-manual shard_map region (parallel/transformer
    ``_blocks_fn``): bp arrives pre-sliced per param_pspecs — Wq/Wk/Wv/W1
    column-sliced and Wo/W2 row-sliced over ``tp_axis`` (Megatron
    column→row: one psum per sublayer, placed BEFORE the replicated bias
    add), MoE expert dim sliced over ``expert_axis``. Local head count is
    derived from the sliced Wq width, so the same code serves any tp
    degree (a size-1 axis psum is a no-op)."""
    b, T, d = x.shape
    hn = cfg.n_heads
    cd = _cdtype(cfg)
    if cd is not None:
        x = x.astype(cd)
        bp = {k2: (v.astype(cd) if k2[0] == "W" or k2[0] == "b" else v)
              for k2, v in bp.items()}
    a_in = _ln(x, bp["ln1_g"], bp["ln1_b"], cd)
    # under manual TP the head projections are column slices: this
    # shard owns d_local/head_dim of the hn heads
    d_local = bp["Wq"].shape[-1]
    hn_local = hn * d_local // d

    def heads(W):
        return (a_in @ W).reshape(b, T, hn_local, -1).transpose(0, 2, 1, 3)

    if cfg.fused_qkv:
        qkv = a_in @ jnp.concatenate(
            [bp["Wq"], bp["Wk"], bp["Wv"]], axis=-1)  # (b, T, 3*d_local)
        q, k, v = (s.reshape(b, T, hn_local, -1).transpose(0, 2, 1, 3)
                   for s in jnp.split(qkv, 3, axis=-1))
    else:
        q, k, v = heads(bp["Wq"]), heads(bp["Wk"]), heads(bp["Wv"])
    fn = attn_fn if attn_fn is not None else dense_attention
    o = fn(q, k, v, causal=True, mask=None)
    o = o.transpose(0, 2, 1, 3).reshape(b, T, d_local).astype(x.dtype)
    om = o @ bp["Wo"]
    if tp_axis is not None:
        om = jax.lax.psum(om, tp_axis)
    x = x + om + bp["bo"]
    m_in = _ln(x, bp["ln2_g"], bp["ln2_b"], cd)
    if cfg.n_experts > 0:
        from deeplearning4j_tpu.nn.conf.layers.moe import _moe_ffn

        y2, aux, _load = _moe_ffn(
            {k2: bp[k2] for k2 in ("Wg", "W1", "b1", "W2", "b2")},
            m_in.reshape(b * T, d), jax.nn.gelu,
            _moe_capacity(cfg, b * T), cfg.top_k,
            expert_axis=expert_axis, tp_axis=tp_axis,
        )
        return x + y2.reshape(b, T, d).astype(x.dtype), aux
    h = jax.nn.gelu(m_in @ bp["W1"] + bp["b1"])
    hm = h @ bp["W2"]
    if tp_axis is not None:
        hm = jax.lax.psum(hm, tp_axis)
    return x + hm + bp["b2"]


class ContextWindowExceeded(ValueError):
    """prompt_len + max_new would overflow the model's fixed
    ``max_length`` context window (the KV cache slab / positional table
    bound). Typed so serving layers can reject with a 4xx naming the
    limit instead of a bare ValueError; carries the numbers as
    attributes for programmatic handling."""

    def __init__(self, prompt_len: int, max_new: int, max_length: int):
        self.prompt_len = int(prompt_len)
        self.max_new = int(max_new)
        self.max_length = int(max_length)
        super().__init__(
            f"prompt ({prompt_len}) + max_new ({max_new}) exceeds the "
            f"model's max_length context window ({max_length}); shorten "
            f"the prompt, reduce max_new, or use generate() (which "
            f"windows to the most recent max_length tokens)")


def _validate_sampling(temperature: float, top_k: int, top_p: float) -> None:
    if (top_k or top_p) and temperature <= 0:
        raise ValueError("top_k/top_p sampling requires temperature > 0")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if top_p and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def _sample_next(logits: np.ndarray, temperature: float, top_k: int,
                 top_p: float, rng):
    """(b, V) logits → ((b,) int32 next ids, new rng). Greedy at
    temperature<=0; otherwise temperature + optional top-k then nucleus
    filtering (the shared sampler behind generate/generate_cached)."""
    if temperature <= 0:
        return logits.argmax(-1).astype(np.int32), rng
    logits = logits / temperature
    if top_k and top_k < logits.shape[-1]:
        kth = np.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = np.where(logits < kth, -np.inf, logits)
    if top_p and 0.0 < top_p < 1.0:
        order = np.argsort(-logits, axis=-1)
        sorted_l = np.take_along_axis(logits, order, -1)
        p_sorted = np.exp(sorted_l - sorted_l.max(-1, keepdims=True))
        p_sorted /= p_sorted.sum(-1, keepdims=True)
        cum = np.cumsum(p_sorted, -1)
        # keep tokens up to AND including the one crossing p
        cut = cum - p_sorted >= top_p
        sorted_l = np.where(cut, -np.inf, sorted_l)
        inv = np.argsort(order, axis=-1)
        logits = np.take_along_axis(sorted_l, inv, -1)
    rng, k = jax.random.split(rng)
    nxt = np.asarray(
        jax.random.categorical(k, jnp.asarray(logits))
    ).astype(np.int32)
    return nxt, rng


def _filter_logits(logits, temperature, top_k, top_p):
    """Shared in-graph sampling filter: (b, V) fp32 logits →
    temperature-scaled, top-k- and nucleus-filtered logits. The policy
    knobs may be scalars (one policy for the batch — the solo fused
    decode) or per-row (b,) arrays (the continuous-batching engine: each
    slot its own policy); every op is row-wise either way, so a row
    filtered among other slots is bit-identical to the same row filtered
    alone. All policy decisions are data-dependent ``where`` selects —
    ONE compiled program covers greedy and every knob combination."""
    V = logits.shape[-1]

    def col(x):  # scalar stays scalar; (b,) broadcasts per row
        return x if jnp.ndim(x) == 0 else x[:, None]

    t = jnp.where(temperature > 0, temperature, 1.0)
    l = logits / col(t)
    # top-k: keep the k highest (filter active only for 0 < k < V)
    k_eff = jnp.clip(top_k, 1, V)
    use_k = (top_k > 0) & (top_k < V)
    sorted_asc = jnp.sort(l, axis=-1)
    kth = jnp.take_along_axis(
        sorted_asc, jnp.broadcast_to(col(V - k_eff),
                                     (l.shape[0], 1)), axis=-1)
    l = jnp.where(col(use_k) & (l < kth), -jnp.inf, l)
    # nucleus: smallest prefix of descending-prob tokens reaching top_p
    use_p = (top_p > 0.0) & (top_p < 1.0)
    order = jnp.argsort(-l, axis=-1)
    sl = jnp.take_along_axis(l, order, -1)
    p_sorted = jnp.exp(sl - sl.max(-1, keepdims=True))
    p_sorted = p_sorted / p_sorted.sum(-1, keepdims=True)
    cum = jnp.cumsum(p_sorted, -1)
    # keep tokens up to AND including the one crossing p (host parity)
    cut = cum - p_sorted >= col(top_p)
    sl = jnp.where(col(use_p) & cut, -jnp.inf, sl)
    inv = jnp.argsort(order, axis=-1)
    return jnp.take_along_axis(sl, inv, -1)


def sample_next_device(logits, temperature, top_k, top_p, key):
    """In-graph mirror of :func:`_sample_next`: (b, V) fp32 logits →
    ((b,) int32 next ids, advanced key). One key chain for the whole
    batch, exactly like the host sampler — the solo
    ``generate_cached`` fused path.

    Parity: greedy and temperature/top-k outputs are bit-identical to
    the host sampler for the same key (sort/compare/divide are exact and
    the categorical draw uses the same key chain). top-p's cumsum may
    differ from NumPy's in reduction order, so nucleus CUTOFFS can
    differ at ties on the boundary — tolerance documented in
    ARCHITECTURE § Continuous batching. The key is split every call
    (data-independent chain) even under greedy, which ignores it."""
    l = _filter_logits(logits, temperature, top_k, top_p)
    key, sub = jax.random.split(key)
    sampled = jax.random.categorical(sub, l)
    nxt = jnp.where(temperature <= 0, jnp.argmax(logits, axis=-1), sampled)
    return nxt.astype(jnp.int32), key


def sample_next_rows(logits, temperature, top_k, top_p, keys):
    """Per-row variant for the continuous-batching engine: (b, V)
    logits, per-row policy knobs (b,) and per-row keys (b, 2) → ((b,)
    ids, advanced keys). The filter is the shared BATCHED implementation
    (vmapping the sorts is ruinously slow on XLA:CPU); only the
    per-key split + categorical draw are vmapped, and the draw uses a
    (1, V) lane exactly like a solo b=1 call — so lane s is bit-
    identical to ``sample_next_device(logits[s:s+1], ..., keys[s])``
    (counter-based PRNG + vmap semantics), which is what makes engine
    output ≡ solo output."""
    l = _filter_logits(logits, temperature, top_k, top_p)
    splits = jax.vmap(jax.random.split)(keys)  # (b, 2, 2)
    nkeys, subs = splits[:, 0], splits[:, 1]
    sampled = jax.vmap(
        lambda k, row: jax.random.categorical(k, row[None])[0])(subs, l)
    nxt = jnp.where(temperature <= 0, jnp.argmax(logits, axis=-1), sampled)
    return nxt.astype(jnp.int32), nkeys


def init_decode_cache(cfg: TransformerLMConfig, batch: int,
                      max_length: Optional[int] = None) -> Dict:
    """Preallocated per-layer KV cache for single-token decoding: static
    (L, b, heads, max_length, head_dim) buffers + a position counter —
    TPU-friendly (no growing shapes; writes are dynamic_update slices).
    ``max_length`` overrides the slab's time extent (the continuous-
    batching engine sizes its slots independently of the model's full
    window); default is ``cfg.max_length``."""
    cd = _cdtype(cfg) or jnp.float32
    hd = cfg.d_model // cfg.n_heads
    T = cfg.max_length if max_length is None else int(max_length)
    shape = (cfg.n_layers, batch, cfg.n_heads, T, hd)
    return {"k": jnp.zeros(shape, cd), "v": jnp.zeros(shape, cd),
            "pos": jnp.zeros((), jnp.int32)}


def prefill_cache(cfg: TransformerLMConfig, params: Dict[str, Array],
                  cache: Dict, ids: Array, length=None):
    """Batched prompt prefill: ids (b, Tp) int32 into a fresh cache →
    (last-position logits (b, V) fp32, cache with pos=Tp). One device
    launch regardless of prompt length (causal attention within the
    prompt, K/V written as one slice per layer); MoE routing competes all
    b*Tp prompt tokens, exactly like ``forward``.

    ``length`` (traced scalar int32, <= Tp) marks the REAL prompt length
    when ids is right-padded up to a bucketed Tp: logits are gathered at
    position length-1 and the cache's pos is set to length. Causal
    attention makes end-padding exact for dense models — position i
    attends only to <= i, so pad positions can never influence real
    ones; their K/V is written but masked from every future decode read
    (decode masks to <= pos) and overwritten as decoding advances. The
    one exception is MoE (cfg.n_experts > 0), where pad tokens compete
    for expert capacity — callers keep MoE prefill unbucketed (see
    ``TransformerLM.generate_cached``)."""
    cd = _cdtype(cfg)
    b, Tp = ids.shape
    hn = cfg.n_heads
    d = cfg.d_model
    x = params["embed"][ids] + params["pos"][:Tp][None]
    if cd is not None:
        x = x.astype(cd)

    def body(x, xs):
        bp, kc, vc = xs
        if cd is not None:
            bp = {k2: (v.astype(cd) if k2[0] in ("W", "b") else v)
                  for k2, v in bp.items()}
        a_in = _ln(x, bp["ln1_g"], bp["ln1_b"], cd)

        def heads(W):
            return (a_in @ W).reshape(b, Tp, hn, -1).transpose(0, 2, 1, 3)

        q, k, v = heads(bp["Wq"]), heads(bp["Wk"]), heads(bp["Wv"])
        kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype), (0, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype), (0, 0, 0, 0))
        o = dense_attention(q, k, v, causal=True, mask=None)
        o = o.transpose(0, 2, 1, 3).reshape(b, Tp, d).astype(x.dtype)
        x = x + o @ bp["Wo"] + bp["bo"]
        m_in = _ln(x, bp["ln2_g"], bp["ln2_b"], cd)
        if cfg.n_experts > 0:
            from deeplearning4j_tpu.nn.conf.layers.moe import _moe_ffn

            y2, _aux, _load = _moe_ffn(
                {k2: bp[k2] for k2 in ("Wg", "W1", "b1", "W2", "b2")},
                m_in.reshape(b * Tp, d), jax.nn.gelu,
                _moe_capacity(cfg, b * Tp), cfg.top_k,
            )
            x = x + y2.reshape(b, Tp, d).astype(x.dtype)
        else:
            h = jax.nn.gelu(m_in @ bp["W1"] + bp["b1"])
            x = x + h @ bp["W2"] + bp["b2"]
        return x, (kc, vc)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    if length is None:
        x_last = x[:, -1]
        pos_out = jnp.asarray(Tp, jnp.int32)
    else:
        pos_out = jnp.asarray(length, jnp.int32)
        x_last = jax.lax.dynamic_index_in_dim(x, pos_out - 1, axis=1,
                                              keepdims=False)
    x_last = _ln(x_last, params["lnf_g"], params["lnf_b"], cd)
    head = params["head"].astype(cd) if cd is not None else params["head"]
    logits = (x_last @ head).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v, "pos": pos_out}


def decode_step(cfg: TransformerLMConfig, params: Dict[str, Array],
                cache: Dict, ids_1: Array):
    """One autoregressive step: ids_1 (b,) int32 at position cache["pos"]
    → (logits (b, V) fp32, new cache). Attention reads the cached K/V
    (masked to positions ≤ pos) instead of re-running the prefix — O(T)
    decoding vs the O(T²) full-forward loop; greedy-parity tested against
    ``forward`` in tests/test_moe.py.

    ``cache["pos"]`` may be a scalar (every row at the same position —
    the single-request path) or a per-row (b,) vector (the continuous-
    batching engine: each slot carries its own position; K/V writes
    become a per-row scatter and the attention mask is per-row). The
    attention math is row-independent either way, so a row decoded among
    other slots is bit-identical to the same row decoded alone
    (parity-asserted in tests/test_generate.py).

    MoE note: decode routes only the b current-step tokens (per-step
    capacity), while the full forward competes all window tokens; when
    training-time capacity BINDS (dropped tokens), cached decoding can
    legitimately differ from ``generate`` — parity holds whenever no
    token is dropped."""
    cd = _cdtype(cfg)
    pos = cache["pos"]
    per_row = getattr(pos, "ndim", 0) == 1
    T = cache["k"].shape[3]
    ptab = jnp.take(params["pos"], pos, axis=0)  # clip-mode gather
    x = params["embed"][ids_1] + (ptab if per_row else ptab[None, :])
    if cd is not None:
        x = x.astype(cd)
    b = x.shape[0]
    hn = cfg.n_heads
    d = cfg.d_model
    scale = 1.0 / math.sqrt(d // hn)
    if per_row:
        valid = jnp.arange(T)[None, :] <= pos[:, None]  # (b, T)
        wp = jnp.minimum(pos, T - 1)  # clamped per-row write index
    else:
        valid = (jnp.arange(T) <= pos)  # (T,)

    def body(x, xs):
        bp, kc, vc = xs  # kc/vc: (b, hn, T, hd)
        if cd is not None:
            bp = {k2: (v.astype(cd) if k2[0] in ("W", "b") else v)
                  for k2, v in bp.items()}
        a_in = _ln(x, bp["ln1_g"], bp["ln1_b"], cd)

        def head_proj(W):
            return (a_in @ W).reshape(b, hn, -1)

        q, k, v = head_proj(bp["Wq"]), head_proj(bp["Wk"]), head_proj(bp["Wv"])
        if per_row:
            rows = jnp.arange(b)
            kc = kc.at[rows, :, wp].set(k.astype(kc.dtype))
            vc = vc.at[rows, :, wp].set(v.astype(vc.dtype))
        else:
            kc = jax.lax.dynamic_update_index_in_dim(
                kc, k.astype(kc.dtype), pos, 2)
            vc = jax.lax.dynamic_update_index_in_dim(
                vc, v.astype(vc.dtype), pos, 2)
        scores = jnp.einsum("bhd,bhtd->bht", q, kc).astype(jnp.float32) * scale
        scores = jnp.where(valid[:, None, :] if per_row
                           else valid[None, None, :], scores, -1e30)
        p = jax.nn.softmax(scores, axis=-1).astype(kc.dtype)
        o = jnp.einsum("bht,bhtd->bhd", p, vc).reshape(b, d).astype(x.dtype)
        x = x + o @ bp["Wo"] + bp["bo"]
        m_in = _ln(x, bp["ln2_g"], bp["ln2_b"], cd)
        if cfg.n_experts > 0:
            from deeplearning4j_tpu.nn.conf.layers.moe import _moe_ffn

            y2, _aux, _load = _moe_ffn(
                {k2: bp[k2] for k2 in ("Wg", "W1", "b1", "W2", "b2")},
                m_in, jax.nn.gelu, _moe_capacity(cfg, b), cfg.top_k,
            )
            x = x + y2.astype(x.dtype)
        else:
            h = jax.nn.gelu(m_in @ bp["W1"] + bp["b1"])
            x = x + h @ bp["W2"] + bp["b2"]
        return x, (kc, vc)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _ln(x, params["lnf_g"], params["lnf_b"], cd)
    head = params["head"].astype(cd) if cd is not None else params["head"]
    logits = (x @ head).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v, "pos": pos + 1}


def decode_steps(cfg: TransformerLMConfig, params: Dict[str, Array],
                 cache: Dict, ids_k: Array):
    """K-column decode for speculative verification: ids_k (b, K) int32
    where column 0 sits at per-row position ``cache["pos"]`` (a (b,)
    vector) and column j at pos+j → (logits (b, K, V) fp32, new cache).
    One dispatch scores all K positions: column j's logits are the
    model's next-token distribution AFTER consuming ids_k[:, :j+1], so a
    draft token at column j+1 is verified against logits[:, j] — exactly
    the distribution token-by-token decode would have produced, which is
    what makes speculative acceptance exact.

    K/V for all K columns is written (scatter at pos..pos+K-1) BEFORE
    attention, so column j attends to columns 0..j of the current block
    plus the prior context (mask t <= pos+j). Writes use ``mode="drop"``:
    a column whose absolute position falls past the slab (pos+j >= T)
    is dropped rather than clipped — clipping would land every
    out-of-range column on T-1 and corrupt the real write when a row's
    final token sits exactly at the slab edge. Callers must therefore
    never ACCEPT a column at pos+j > T-1 (its logits are garbage); the
    engine clamps draft lengths to the window.

    Rejected-draft "rollback" is free: stale K/V past the accepted
    position is masked from every later read (t <= pos') and each later
    dispatch rewrites its columns contiguously from pos' before reading
    them, so garbage is always overwritten before it becomes visible.

    MoE is unsupported (routing would compete b*K tokens per step where
    sequential decode competes b — acceptance would no longer be exact);
    callers keep MoE engines at k=1."""
    if cfg.n_experts > 0:
        raise ValueError("decode_steps does not support MoE models "
                         "(per-step routing capacity differs from "
                         "sequential decode); use decode_step")
    cd = _cdtype(cfg)
    pos = cache["pos"]
    T = cache["k"].shape[3]
    b, K = ids_k.shape
    hn = cfg.n_heads
    d = cfg.d_model
    scale = 1.0 / math.sqrt(d // hn)
    cols = pos[:, None] + jnp.arange(K)[None, :]  # (b, K) absolute pos
    ptab = jnp.take(params["pos"], cols, axis=0)  # clip-mode gather
    x = params["embed"][ids_k] + ptab
    if cd is not None:
        x = x.astype(cd)
    valid = jnp.arange(T)[None, None, :] <= cols[:, :, None]  # (b, K, T)
    rows = jnp.arange(b)

    def body(x, xs):
        bp, kc, vc = xs  # kc/vc: (b, hn, T, hd)
        if cd is not None:
            bp = {k2: (v.astype(cd) if k2[0] in ("W", "b") else v)
                  for k2, v in bp.items()}
        a_in = _ln(x, bp["ln1_g"], bp["ln1_b"], cd)

        def head_proj(W):
            return (a_in @ W).reshape(b, K, hn, -1)  # (b, K, hn, hd)

        k, v = head_proj(bp["Wk"]), head_proj(bp["Wv"])
        q = head_proj(bp["Wq"]).transpose(0, 2, 1, 3)  # (b, hn, K, hd)
        # advanced indices at axes 0 and 2 around the ':' slice → result
        # dims (b, K) lead, so the (b, K, hn, hd) values scatter directly
        kc = kc.at[rows[:, None], :, cols].set(k.astype(kc.dtype),
                                               mode="drop")
        vc = vc.at[rows[:, None], :, cols].set(v.astype(vc.dtype),
                                               mode="drop")
        scores = jnp.einsum("bhkd,bhtd->bhkt", q,
                            kc).astype(jnp.float32) * scale
        scores = jnp.where(valid[:, None, :, :], scores, -1e30)
        p = jax.nn.softmax(scores, axis=-1).astype(kc.dtype)
        o = jnp.einsum("bhkt,bhtd->bhkd", p, vc)
        o = o.transpose(0, 2, 1, 3).reshape(b, K, d).astype(x.dtype)
        x = x + o @ bp["Wo"] + bp["bo"]
        m_in = _ln(x, bp["ln2_g"], bp["ln2_b"], cd)
        h = jax.nn.gelu(m_in @ bp["W1"] + bp["b1"])
        x = x + h @ bp["W2"] + bp["b2"]
        return x, (kc, vc)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _ln(x, params["lnf_g"], params["lnf_b"], cd)
    head = params["head"].astype(cd) if cd is not None else params["head"]
    logits = (x @ head).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v, "pos": pos + K}


def prefill_bucket_lengths(max_length: int, hint=None):
    """Ascending prompt-length bucket list for prefill padding — the
    ``serving_seq_buckets`` discipline applied to the decode path: every
    prefill pads its prompt up to one of these lengths, so the jitted
    prefill compiles a BOUNDED program set instead of one program per
    distinct prompt length. ``hint`` (a model's ``serving_seq_buckets``)
    is filtered to <= max_length; default is powers of two from 8. The
    list always ends at ``max_length`` so any window-legal prompt has a
    bucket."""
    max_length = int(max_length)
    if hint:
        bs = sorted({int(t) for t in hint if 0 < int(t) <= max_length})
    else:
        bs, b = [], 8
        while b < max_length:
            bs.append(b)
            b *= 2
    if not bs or bs[-1] != max_length:
        bs.append(max_length)
    return bs


def forward(cfg: TransformerLMConfig, params: Dict[str, Array], ids: Array,
            attn_fn=None, pos_offset: int = 0, return_aux: bool = False,
            cast_logits: bool = True):
    """ids (b, T) int32 → logits (b, T, V) [, total MoE aux loss].
    Single-device path: blocks via lax.scan over the stacked layer axis.
    ``cast_logits=False`` keeps logits in the compute dtype — the loss
    path's choice, so no full-vocab fp32 tensor is materialized (see
    ``token_nll``)."""
    x = params["embed"][ids] + params["pos"][pos_offset:pos_offset + ids.shape[1]][None]
    cd = _cdtype(cfg)
    if cd is not None:
        x = x.astype(cd)  # stable scan-carry dtype; blocks keep it bf16

    if cfg.n_experts > 0:
        def body(carry, bp):
            x, aux = carry
            x, a = block_apply(cfg, bp, x, attn_fn=attn_fn)
            return (x, aux + a), None

        (x, aux), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), params["blocks"])
    else:
        def body(x, bp):
            return block_apply(cfg, bp, x, attn_fn=attn_fn), None

        x, _ = jax.lax.scan(body, x, params["blocks"])
        aux = jnp.zeros((), jnp.float32)
    x = _ln(x, params["lnf_g"], params["lnf_b"], cd)
    head = params["head"].astype(cd) if cd is not None else params["head"]
    logits = x @ head
    if cast_logits:
        logits = logits.astype(jnp.float32)  # inference APIs: fp32 logits
    if return_aux:
        return logits, aux
    return logits


def token_nll(logits, targets):
    """Per-token next-token NLL in the logsumexp - target-logit form:
    ``nll = lse(logits) - logits[target]``. Unlike
    ``log_softmax + gather``, no full-vocab log-prob tensor exists — the
    fp32 cast feeds only reductions and a gather, which XLA fuses, so at
    V=32k the loss head's HBM traffic drops by two full-vocab fp32
    passes per step (the LM step's single largest activation).
    logits (..., V) any float dtype; targets (...) int32, -1 = ignore.
    Returns (mean_nll, valid_count)."""
    lf = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lf, axis=-1)
    tgt = jnp.maximum(targets, 0)
    tgt_logit = jnp.take_along_axis(lf, tgt[..., None], axis=-1)[..., 0]
    valid = (targets >= 0).astype(jnp.float32)
    nll = (lse - tgt_logit) * valid
    count = jnp.maximum(jnp.sum(valid), 1.0)
    return jnp.sum(nll) / count, count


def lm_loss(cfg: TransformerLMConfig, params, ids, targets, attn_fn=None,
            segment_ids=None):
    """Mean next-token cross-entropy (+ weighted MoE aux loss when MoE).
    targets (b, T) int32 (-1 = ignore).

    ``segment_ids``: optional (b, T) int array for PACKED-sequence
    training (multiple documents per row): attention stays within each
    segment (dense_attention routes to the Pallas flash kernel's
    segment path when available). Cross-segment next-token targets
    should carry -1 so the boundary token doesn't predict into the next
    document."""
    if segment_ids is not None:
        if attn_fn is not None:
            raise ValueError("pass segment_ids OR a custom attn_fn, "
                             "not both")
        seg = segment_ids

        def attn_fn(q, k, v, *, causal, mask=None):
            return dense_attention(q, k, v, causal=causal, mask=mask,
                                   segment_ids=seg)

    logits, aux = forward(cfg, params, ids, attn_fn=attn_fn, return_aux=True,
                          cast_logits=False)
    loss, _ = token_nll(logits, targets)
    if cfg.n_experts > 0:
        loss = loss + cfg.aux_loss_weight * aux
    return loss


class TransformerLM(ZooModel):
    """Zoo wrapper with a simple single-device fit/generate surface; the
    distributed path is parallel/transformer.py's DistributedLMTrainer."""

    name = "transformerlm"

    #: prompt-length buckets for KV-cache prefill (filtered to the
    #: instance's max_length at use; see ``prefill_bucket_lengths``) —
    #: the generation counterpart of the forward path's seq buckets
    serving_seq_buckets = (16, 32, 64, 128, 256, 512)

    def __init__(self, vocab_size: int = 1000, d_model: int = 256,
                 n_heads: int = 4, n_layers: int = 4, mlp_ratio: int = 4,
                 max_length: int = 512, seed: int = 123, n_experts: int = 0,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 aux_loss_weight: float = 1e-2,
                 compute_dtype: Optional[str] = None,
                 fused_qkv: bool = False, **kwargs):
        super().__init__(num_classes=vocab_size, seed=seed, **kwargs)
        self.cfg = TransformerLMConfig(
            vocab_size, d_model, n_heads, n_layers, mlp_ratio, max_length,
            seed=seed, n_experts=n_experts, top_k=top_k,
            capacity_factor=capacity_factor, aux_loss_weight=aux_loss_weight,
            compute_dtype=compute_dtype, fused_qkv=fused_qkv,
        )
        self.params_: Optional[Dict] = None
        self.opt_state_: Optional[Dict] = None
        #: no layer running-state (the InferenceEngine snapshot surface
        #: reads this attribute on every served model)
        self.state_ = None
        self._jit_cache: Dict = {}
        #: fn-name → number of XLA programs traced (bumped at trace time
        #: inside the jitted callables — the retrace-guard instrument,
        #: same pattern as InferenceEngine.compile_count)
        self.trace_counts: Dict[str, int] = {}
        self.iteration = 0
        self.score_ = None

    def _bump_trace(self, key: str) -> None:
        counts = getattr(self, "trace_counts", None)
        if counts is None:  # models deserialized from older checkpoints
            counts = self.trace_counts = {}
        counts[key] = counts.get(key, 0) + 1

    def init(self):
        self.params_ = init_params(self.cfg)
        from deeplearning4j_tpu.updaters import Adam

        self.updater = self.kwargs.get("updater", Adam(3e-4))
        self.opt_state_ = jax.tree_util.tree_map(
            lambda a: self.updater.init_state(a), self.params_
        )
        return self

    def _make_step(self, with_seg: bool = False):
        cfg, upd = self.cfg, self.updater

        def step(params, opt_state, ids, targets, t, seg=None):
            loss, grads = jax.value_and_grad(
                lambda p: lm_loss(cfg, p, ids, targets,
                                  segment_ids=seg if with_seg else None)
            )(params)

            flat_p, treedef = jax.tree_util.tree_flatten(params)
            flat_g = treedef.flatten_up_to(grads)
            flat_o = treedef.flatten_up_to(opt_state)
            new_p, new_o = [], []
            for p, g, o in zip(flat_p, flat_g, flat_o):
                delta, o2 = upd.apply(g, o, t, t, 0)
                new_p.append(p - delta)
                new_o.append(o2)
            return (jax.tree_util.tree_unflatten(treedef, new_p),
                    jax.tree_util.tree_unflatten(treedef, new_o), loss)

        return jax.jit(step, donate_argnums=(0, 1))

    def fit_batch(self, ids: np.ndarray, targets: np.ndarray,
                  segment_ids: Optional[np.ndarray] = None) -> float:
        """One train step. ``segment_ids`` (b, T) int enables
        packed-sequence training (see ``lm_loss``)."""
        key = "step_seg" if segment_ids is not None else "step"
        if key not in self._jit_cache:
            self._jit_cache[key] = self._make_step(
                with_seg=segment_ids is not None)
        self.iteration += 1
        args = [self.params_, self.opt_state_, jnp.asarray(ids, jnp.int32),
                jnp.asarray(targets, jnp.int32),
                jnp.asarray(self.iteration, jnp.int32)]
        if segment_ids is not None:
            args.append(jnp.asarray(segment_ids, jnp.int32))
        self.params_, self.opt_state_, self.score_ = \
            self._jit_cache[key](*args)
        return float(self.score_)

    def logits(self, ids: np.ndarray) -> np.ndarray:
        if "fwd" not in self._jit_cache:
            self._jit_cache["fwd"] = jax.jit(
                lambda p, i: forward(self.cfg, p, i)
            )
        return np.asarray(self._jit_cache["fwd"](self.params_,
                                                 jnp.asarray(ids, jnp.int32)))

    def output(self, x, mask=None) -> np.ndarray:
        """Generic serving surface (the InferenceEngine fallback path —
        lets ``cli serve --model transformerlm`` stand up /predict next
        to /generate): token ids (b, T) → fp32 logits (b, T, V)."""
        return self.logits(np.asarray(x).astype(np.int32))

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params_))

    def generate(self, prompt_ids: np.ndarray, max_new: int = 20,
                 temperature: float = 0.0, rng=None, top_k: int = 0,
                 top_p: float = 0.0) -> np.ndarray:
        """Greedy/temperature sampling continuation (host loop; each step
        re-runs the jitted forward on the growing prefix). Contexts longer
        than ``cfg.max_length`` are windowed to the most recent
        ``max_length`` tokens — the positional table bounds the forward.

        ``top_k`` > 0 restricts sampling to the k highest-probability
        tokens; ``top_p`` in (0, 1] to the smallest nucleus whose
        cumulative probability reaches p. Both require temperature > 0
        and compose (top-k filter, then nucleus)."""
        ids = np.asarray(prompt_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        _validate_sampling(temperature, top_k, top_p)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        for _ in range(max_new):
            window = ids[:, -self.cfg.max_length:]
            logits = self.logits(window)[:, -1]
            nxt, rng = _sample_next(logits, temperature, top_k, top_p, rng)
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        return ids

    def prefill_buckets(self):
        """The bounded prefill program set: prompt lengths pad up to
        these (class hint filtered to this instance's max_length)."""
        return prefill_bucket_lengths(self.cfg.max_length,
                                      self.serving_seq_buckets)

    def generate_cached(self, prompt_ids: np.ndarray, max_new: int = 20,
                        temperature: float = 0.0, rng=None, top_k: int = 0,
                        top_p: float = 0.0) -> np.ndarray:
        """KV-cache decoding: the prompt prefills per-layer K/V buffers,
        then each new token is one O(T) ``decode_step`` instead of the
        O(T²) full-forward loop of ``generate`` (identical outputs —
        parity-tested; see ``sample_next_device`` for the one documented
        top-p tolerance). Raises :class:`ContextWindowExceeded` (a
        ValueError naming the limit) when prompt_len + max_new would
        overflow ``max_length`` — ``generate``'s windowing cannot apply
        here, the KV slab is the window.

        Zero host round-trips in the decode loop: sampling is fused into
        the jitted prefill/decode programs (``sample_next_device``), the
        sampled token feeds the next step as a device array, and the
        token stack is read back ONCE at the end. Prompt lengths pad up
        to ``prefill_buckets()`` so prefill compiles a bounded program
        set (the dense causal math is padding-exact; MoE prompts skip
        bucketing because pad tokens would compete for expert capacity —
        that path keeps one program per distinct prompt length).
        ``trace_counts`` records programs traced per function — the
        retrace-guard instrument."""
        ids = np.asarray(prompt_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        if ids.shape[1] + max_new > self.cfg.max_length:
            raise ContextWindowExceeded(ids.shape[1], max_new,
                                        self.cfg.max_length)
        _validate_sampling(temperature, top_k, top_p)
        if max_new <= 0:
            return ids
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        if "decode_s" not in self._jit_cache:
            cfg = self.cfg

            def _dec(p, c, tok, t, k, pp, key):
                self._bump_trace("decode")
                logits, c = decode_step(cfg, p, c, tok)
                nxt, key = sample_next_device(logits, t, k, pp, key)
                return nxt, c, key

            def _pre(p, c, i, ln, t, k, pp, key):
                self._bump_trace("prefill")
                logits, c = prefill_cache(cfg, p, c, i, length=ln)
                nxt, key = sample_next_device(logits, t, k, pp, key)
                return nxt, c, key

            self._jit_cache["decode_s"] = jax.jit(_dec, donate_argnums=(1,))
            self._jit_cache["prefill_s"] = jax.jit(_pre, donate_argnums=(1,))
        b, Tp = ids.shape
        if self.cfg.n_experts > 0:
            ids_in = ids  # MoE: padding would perturb routing capacity
        else:
            Tb = next(t for t in self.prefill_buckets() if t >= Tp)
            ids_in = np.zeros((b, Tb), np.int32)
            ids_in[:, :Tp] = ids
        t_ = jnp.asarray(float(temperature), jnp.float32)
        k_ = jnp.asarray(int(top_k), jnp.int32)
        p_ = jnp.asarray(float(top_p), jnp.float32)
        cache = init_decode_cache(self.cfg, b)
        tok, cache, key = self._jit_cache["prefill_s"](
            self.params_, cache, jnp.asarray(ids_in),
            jnp.asarray(Tp, jnp.int32), t_, k_, p_, rng)
        toks = [tok]
        step = self._jit_cache["decode_s"]
        for _ in range(max_new - 1):
            tok, cache, key = step(self.params_, cache, tok, t_, k_, p_, key)
            toks.append(tok)
        gen = np.stack([np.asarray(tk) for tk in toks], axis=1)
        return np.concatenate([ids, gen.astype(np.int32)], axis=1)

    def perplexity(self, ids: np.ndarray, targets: np.ndarray) -> float:
        """exp(mean next-token NLL) over valid targets (-1 = ignore) —
        the LM evaluation counterpart of Evaluation.accuracy()."""
        if "ppl" not in self._jit_cache:
            self._jit_cache["ppl"] = jax.jit(
                lambda p, i, t: lm_loss(
                    TransformerLMConfig(**{**self.cfg.to_dict(),
                                           "aux_loss_weight": 0.0}),
                    p, i, t)
            )
        nll = self._jit_cache["ppl"](
            self.params_, jnp.asarray(ids, jnp.int32),
            jnp.asarray(targets, jnp.int32))
        return float(np.exp(np.asarray(nll)))
