"""SimpleCNN (reference ``zoo/model/SimpleCNN.java``): small VGG-style
conv stack for 48x48+ inputs."""

from __future__ import annotations

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.updaters import AdaDelta


class SimpleCNN(ZooModel):
    name = "simplecnn"

    def __init__(self, num_classes: int = 10, height: int = 48, width: int = 48,
                 channels: int = 3, **kwargs):
        super().__init__(num_classes=num_classes, **kwargs)
        self.height, self.width, self.channels = height, width, channels

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(self.kwargs.get("updater", AdaDelta()))
            .weight_init("relu")
            .list()
        )
        for n_out, pool in [(16, False), (32, True), (64, True), (128, True)]:
            b = b.layer(ConvolutionLayer(n_out=n_out, kernel_size=3,
                                         convolution_mode="same", activation="relu"))
            b = b.layer(BatchNormalization())
            if pool:
                b = b.layer(SubsamplingLayer(kernel_size=2, stride=2))
        return (
            b.layer(DenseLayer(n_out=256, activation="relu", dropout=0.5))
            .layer(OutputLayer(n_out=self.num_classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )
