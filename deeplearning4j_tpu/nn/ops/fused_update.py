"""Fused ZeRO-1 weight update as a Pallas TPU kernel (TPP-style,
arXiv 2104.05755 applied to the arXiv 2004.13336 sharded update).

The ZeRO-1 step (parallel/zero.py) consumes the synchronized gradient
sharded over the data axis, updates each replica's 1/N flat shard, and
gathers the fresh shards back. GSPMD inserts the reduce-scatter (from
the ``P("data", None)`` constraint on the gradient) and the all-gather
(from the replicated constraint on the result); between them XLA lowers
the Adam math as ~8 separate elementwise HLOs whose intermediates
(m', v', the biased-corrected update, the subtraction) each round-trip
HBM over the full shard. This kernel computes the whole update —

    m' = β₁·m + (1-β₁)·g
    v' = β₂·v + (1-β₂)·g²
    p' = p - α·m'/(√v' + ε)        α = lr·√(1-β₂ᵗ)/(1-β₁ᵗ)

— in ONE pass over the flat shard: p/g/m/v stream HBM→VMEM once, three
results stream back, nothing else is materialized. α is computed
OUTSIDE the kernel with exactly the scalar expression ``Adam.apply``
uses, so the fused step is **bit-exact** vs the unfused reference — the
probe (and tests/test_fused_kernels.py) assert ``array_equal`` on
params AND both Adam slots, including the zero-padding lanes of
odd-count groups, which provably stay zero through the update.

The collectives stay where GSPMD puts them: the kernel's operands carry
the ``(N, chunk)`` flat-shard layout and its sharding constraints, so
reduce-scatter → fused-update → all-gather compiles into one program
with the update portion single-pass. The availability probe compiles
the kernel UNDER the actual training mesh's shardings (a partitioner
that cannot place a Pallas call inside the sharded region fails the
probe, not the training step) and falls back to the reference
composition — same contract as every kernel in ``nn.ops.registry``
(``DL4J_TPU_FUSED_ZERO1`` = 0 | 1 | interpret).

Coverage: exact-type :class:`~deeplearning4j_tpu.updaters.Adam` groups
in fp32 (the canonical ZeRO-1 configuration). Other updaters/dtypes
take the reference path per group — the layout already splits groups by
(updater config, dtype), so mixing costs nothing.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_LANE = 128
_BLOCK_ROWS = 256  # rows of 128 lanes per grid cell: 8 × 128 KiB in VMEM


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _adam_kernel(alpha_ref, p_ref, g_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref, *, b1: float, b2: float,
                 eps: float):
    g = g_ref[...]
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    alpha = alpha_ref[0, 0]
    update = alpha * m / (jnp.sqrt(v) + eps)
    po_ref[...] = p_ref[...] - update
    mo_ref[...] = m
    vo_ref[...] = v


def fused_adam_apply(p, g, m, v, alpha, *, b1: float, b2: float, eps: float,
                     interpret: bool = False
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One-pass Adam over arbitrarily-shaped fp32 operands (the flat
    (N, chunk) shard in the ZeRO-1 step). ``alpha`` is the precomputed
    bias-corrected step size (traced scalar). Returns (p', m', v')."""
    shape = p.shape
    total = int(np.prod(shape)) if shape else 1
    rows = _round_up(-(-total // _LANE), _BLOCK_ROWS)
    pad = rows * _LANE - total

    def to2d(a):
        flat = a.reshape(-1)
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)])
        return flat.reshape(rows, _LANE)

    alpha2 = jnp.asarray(alpha, p.dtype).reshape(1, 1)
    grid = (rows // _BLOCK_ROWS,)
    blk = pl.BlockSpec((_BLOCK_ROWS, _LANE), lambda r: (r, 0))
    out = pl.pallas_call(
        functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps),
        grid=grid,
        in_specs=[pl.BlockSpec((1, 1), lambda r: (0, 0)),
                  blk, blk, blk, blk],
        out_specs=[blk, blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANE), p.dtype)] * 3,
        interpret=interpret,
    )(alpha2, to2d(p), to2d(g), to2d(m), to2d(v))

    def back(a):
        return a.reshape(-1)[:total].reshape(shape)

    return back(out[0]), back(out[1]), back(out[2])


# --------------------------------------------------------------------------
# group-level impl + probe (wired from parallel/zero.py)
# --------------------------------------------------------------------------
def _adam_alpha(upd, t, iteration, epoch):
    """EXACTLY ``Adam.apply``'s scalar pipeline — bit-parity depends on
    reusing the same expressions in the same order."""
    tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
    return upd.lr(iteration, epoch) * jnp.sqrt(1 - upd.beta2 ** tf) \
        / (1 - upd.beta1 ** tf)


def _make_impl(interpret: bool) -> Callable:
    def impl(upd, p2d, g2d, state, t, iteration, epoch):
        alpha = _adam_alpha(upd, t, iteration, epoch)
        new_p, m, v = fused_adam_apply(
            p2d, g2d, state["m"], state["v"], alpha,
            b1=upd.beta1, b2=upd.beta2, eps=upd.epsilon,
            interpret=interpret)
        return new_p, {"m": m, "v": v}
    return impl


def _probe_group(upd, n_shards: int, mesh, interpret: bool) -> None:
    """Compile (AOT) and execute the fused update UNDER the training
    mesh's flat-shard shardings; assert bit-exactness vs the unfused
    reference program. A GSPMD partitioner that cannot place the Pallas
    call inside the sharded region fails HERE, not in the train step."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    chunk = 2 * _LANE
    shape = (max(int(n_shards), 1), chunk)
    rng = np.random.default_rng(0)

    def mk():
        # numpy: probes can run under an ambient trace (see fused_lstm)
        return np.asarray(rng.standard_normal(shape), np.float32)

    p, g, m = mk(), mk(), mk()
    v = np.abs(mk())  # v is a running mean of squares — non-negative
    t = np.asarray(3.0, np.float32)
    it = np.asarray(2, np.int32)
    ep = np.asarray(0, np.int32)
    impl = _make_impl(interpret)

    def fused_fn(p, g, m, v, t, it, ep):
        new_p, st = impl(upd, p, g, {"m": m, "v": v}, t, it, ep)
        return new_p, st["m"], st["v"]

    def ref_fn(p, g, m, v, t, it, ep):
        delta, st = upd.apply(g, {"m": m, "v": v}, t, it, ep)
        return p - delta, st["m"], st["v"]

    if mesh is not None:
        shard = NamedSharding(mesh, P("data", None))
        repl = NamedSharding(mesh, P())
        in_sh = (shard,) * 4 + (repl,) * 3
        out_sh = (repl,) * 3
        args = tuple(jax.device_put(a, s)
                     for a, s in zip((p, g, m, v, t, it, ep), in_sh))
        k = jax.jit(fused_fn, in_shardings=in_sh,
                    out_shardings=out_sh)
        r = jax.jit(ref_fn, in_shardings=in_sh, out_shardings=out_sh)
    else:
        args = (p, g, m, v, t, it, ep)
        k = jax.jit(fused_fn)
        r = jax.jit(ref_fn)
    shapes = [jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
              for a in (p, g, m, v, t, it, ep)]
    got = k.lower(*shapes).compile()(*args)
    want = r.lower(*shapes).compile()(*args)
    for name, a, b in zip(("p", "m", "v"), got, want):
        a = np.asarray(a)
        b = np.asarray(b)
        if not np.array_equal(a, b):
            err = float(np.max(np.abs(a - b)))
            raise RuntimeError(
                f"fused ZeRO-1 update parity check failed ({name}): "
                f"max abs err {err:.3e} (bit-exactness required)")


def resolve_group_impls(layout, mesh=None,
                        enabled: Optional[bool] = None) -> List[Optional[Callable]]:
    """One fused-update impl (or None → reference ``updater.apply``)
    per layout group, resolved ONCE at step-build time through the
    kernel registry. ``enabled=False`` short-circuits (explicit opt-out
    knob); None/True go through the env/backend route."""
    from deeplearning4j_tpu.nn.ops.registry import default_kernel_registry
    from deeplearning4j_tpu.updaters import Adam

    impls: List[Optional[Callable]] = []
    if enabled is False:
        return [None] * len(layout.groups)
    reg = default_kernel_registry()
    for grp in layout.groups:
        if type(grp.updater) is not Adam or \
                jnp.dtype(grp.dtype) != jnp.float32:
            impls.append(None)
            continue
        key = ("adam", jnp.dtype(grp.dtype).name, int(layout.n_shards))
        interpret = reg.resolve(
            "fused_zero1", key,
            lambda interp, grp=grp: functools.partial(
                _probe_group, grp.updater, layout.n_shards, mesh, interp))
        impls.append(None if interpret is None else _make_impl(interpret))
    return impls
