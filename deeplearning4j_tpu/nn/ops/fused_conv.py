"""Fused Pallas conv+BN+ReLU training kernels (VERDICT r3 item 1; the
TPU-native analogue of the reference's hand-tuned conv fast-path module,
``deeplearning4j-cuda/.../CudnnConvolutionHelper.java:1``).

Why these exist — the round-3 roofline (BASELINE.md): the ResNet-50 train
step is HBM-bandwidth-bound at ~31% MFU; ~18% of the traffic is
structural, forced by XLA op boundaries between conv / BN-stats /
normalize+ReLU. The fix is to change WHERE the normalize happens: these
kernels emit the RAW conv output plus its per-channel (sum, sum-of-
squares) statistics in the conv epilogue (one pass), and apply the
PREVIOUS layer's BN normalize+ReLU on the fly while READING their input
tile in VMEM (zero extra passes). Activations cross HBM exactly once in
each direction, and the normalized tensors are never stored at all — the
backward kernels re-derive them in VMEM from the raw input (remat inside
the kernel, where recompute is free because the operands are already
resident).

Op granularity:       y, stats = conv(act(x * scale + shift), W)
with ``scale``/``shift`` the folded per-channel affine of the upstream
BatchNormalization (gamma/beta/mean/var combine OUTSIDE the kernel, in
plain jnp on (C,)-vectors) and ``stats[0] = colsum(y)``,
``stats[1] = colsum(y^2)`` feeding the downstream BN. Because stats are
ordinary differentiable outputs, the cross-layer gradient chain
(next layer's normalize → this conv's statistics) is handled by jax
autodiff composing the custom VJPs — no hand-plumbed whole-block
backward.

Coverage: stride-1 pointwise (1x1) and stride-1 SAME 3x3 — the dominant
FLOP carriers of the bottleneck block. Stems, stride-2 convs, pooling and
the FC head stay on the XLA path (see ``nn/conf/layers/fused_block.py``).

Like the flash-attention kernel, callers must compile-probe these ops
(the axon tunnel's server-side Mosaic has rejected bf16 matmuls before —
BASELINE.md r3) and fall back to the XLA composition on failure.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128          # MXU/VPU lane width
SUBLANE_F32 = 8

from deeplearning4j_tpu.nn.ops.kernel_compat import (  # noqa: E402
    PRECISION as _PREC,
)

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _pad_axis(a, axis: int, to: int):
    pad = to - a.shape[axis]
    if pad <= 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths)


def _fold(x, scale, shift, relu_in: bool):
    """In-VMEM input fold: normalize+activation of the upstream layer,
    computed in f32 on the VPU, re-cast to bf16 for the MXU."""
    u = x.astype(jnp.float32) * scale + shift
    if relu_in:
        u = jnp.maximum(u, 0.0)
    return u


# ---------------------------------------------------------------------------
# pointwise (1x1, stride 1) fused conv
# ---------------------------------------------------------------------------


def _pw_fwd_kernel(x_ref, s_ref, t_ref, w_ref, y_ref, st_ref, acc_ref,
                   *, relu_in: bool, m_valid: int, bm: int):
    j, i = pl.program_id(0), pl.program_id(1)
    xn = _fold(x_ref[...], s_ref[0, :], t_ref[0, :], relu_in)
    acc_ref[...] = jnp.dot(xn.astype(jnp.bfloat16), w_ref[...],
                           preferred_element_type=jnp.float32, precision=_PREC)
    y = acc_ref[...]
    y_ref[...] = y.astype(jnp.bfloat16)
    # rows past m_valid are padding — keep them out of the statistics
    rows = jax.lax.broadcasted_iota(jnp.int32, y.shape, 0) + i * bm
    ym = jnp.where(rows < m_valid, y, 0.0)

    @pl.when(i == 0)
    def _():
        st_ref[...] = jnp.zeros_like(st_ref)

    st_ref[0:1, :] += jnp.sum(ym, axis=0, keepdims=True)
    st_ref[1:2, :] += jnp.sum(ym * ym, axis=0, keepdims=True)


def _pw_bwd_dx_kernel(x_ref, s_ref, t_ref, w_ref, z_ref, dz_ref, ds_ref,
                      dx_ref, gs_ref, gt_ref,
                      *, relu_in: bool, m_valid: int, bm: int):
    """dx (+ dscale/dshift) for the pointwise op. Grid (1, I): full Cin
    and Cout resident. dz_eff = dz + dsum + 2*z*dsumsq recomputed on the
    fly; xn re-derived from x (never stored)."""
    i = pl.program_id(1)
    dzeff = (dz_ref[...].astype(jnp.float32) + ds_ref[0:1, :]
             + 2.0 * z_ref[...].astype(jnp.float32) * ds_ref[1:2, :])
    rows = jax.lax.broadcasted_iota(jnp.int32, dzeff.shape, 0) + i * bm
    dzeff = jnp.where(rows < m_valid, dzeff, 0.0)
    # dxn = dzeff @ W^T
    dxn = jax.lax.dot_general(
        dzeff.astype(jnp.bfloat16), w_ref[...],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=_PREC,
    )
    x = x_ref[...].astype(jnp.float32)
    u = x * s_ref[0, :] + t_ref[0, :]
    du = jnp.where(u > 0, dxn, 0.0) if relu_in else dxn
    dx_ref[...] = (du * s_ref[0, :]).astype(dx_ref.dtype)

    @pl.when(i == 0)
    def _():
        gs_ref[...] = jnp.zeros_like(gs_ref)
        gt_ref[...] = jnp.zeros_like(gt_ref)

    gs_ref[0:1, :] += jnp.sum(du * x, axis=0, keepdims=True)
    gt_ref[0:1, :] += jnp.sum(du, axis=0, keepdims=True)


def _pw_bwd_dw_kernel(x_ref, s_ref, t_ref, z_ref, dz_ref, ds_ref, dw_ref,
                      *, relu_in: bool, m_valid: int, bm: int):
    """dW = xn^T @ dz_eff, accumulated over the M grid. Grid (I,)."""
    i = pl.program_id(0)
    dzeff = (dz_ref[...].astype(jnp.float32) + ds_ref[0:1, :]
             + 2.0 * z_ref[...].astype(jnp.float32) * ds_ref[1:2, :])
    rows = jax.lax.broadcasted_iota(jnp.int32, dzeff.shape, 0) + i * bm
    dzeff = jnp.where(rows < m_valid, dzeff, 0.0)
    xn = _fold(x_ref[...], s_ref[0, :], t_ref[0, :], relu_in)

    @pl.when(i == 0)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dw_ref[...] += jax.lax.dot_general(
        xn.astype(jnp.bfloat16), dzeff.astype(jnp.bfloat16),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=_PREC,
    )


def _pw_shapes(x, w):
    m, cin = x.shape
    cout = w.shape[1]
    mp = _round_up(m, LANE)
    cinp = _round_up(cin, LANE)
    coutp = _round_up(cout, LANE)
    return m, cin, cout, mp, cinp, coutp


# Scoped-VMEM budget for choosing the M-block. The hardware limit is
# ~16MB; at bm=512, Cin=512, Cout=2048 the dw kernel's footprint is
# 20.9MB (measured OOM, BENCH r4) — the resident (Cin, Cout) panel plus
# double-buffered M-blocks plus f32 intermediates. The estimate below is
# deliberately coarse (panel + 12 bytes per M-row element covers the
# bf16 blocks twice for pipelining and one f32 intermediate each side);
# 12MB leaves headroom for Mosaic's own scratch.
_VMEM_BUDGET = 12 * 1024 * 1024


def _pw_block_m(mp: int, cinp: int, coutp: int) -> int:
    for bm in (512, 256, 128):
        if bm <= max(mp, 128) and (
                4 * cinp * coutp + 12 * bm * (cinp + coutp)) <= _VMEM_BUDGET:
            return bm
    return 128


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def pw_conv(x, scale, shift, w, relu_in: bool = False,
            interpret: bool = False):
    """Fused pointwise conv: (y, stats) = 1x1conv(act(x*scale+shift), W).

    x: (M, Cin) bf16 raw upstream output; scale/shift: (Cin,) f32;
    w: (Cin, Cout) bf16. Returns y (M, Cout) bf16 and stats (2, Cout)
    f32 = [colsum(y); colsum(y^2)] for the downstream BatchNormalization.
    """
    y, st = _pw_forward(x, scale, shift, w, relu_in, interpret)
    return y, st


def _pw_forward(x, scale, shift, w, relu_in, interpret):
    m, cin, cout, mp, cinp, coutp = _pw_shapes(x, w)
    bm = _pw_block_m(mp, cinp, coutp)
    mp = _round_up(mp, bm)
    xp = _pad_axis(_pad_axis(x, 0, mp), 1, cinp)
    wp = _pad_axis(_pad_axis(w, 0, cinp), 1, coutp)
    sp = _pad_axis(scale.reshape(1, -1), 1, cinp)
    tp = _pad_axis(shift.reshape(1, -1), 1, cinp)
    grid = (1, mp // bm)
    y, st = pl.pallas_call(
        functools.partial(_pw_fwd_kernel, relu_in=relu_in, m_valid=m, bm=bm),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, cinp), lambda j, i: (i, 0)),
            pl.BlockSpec((1, cinp), lambda j, i: (0, 0)),
            pl.BlockSpec((1, cinp), lambda j, i: (0, 0)),
            pl.BlockSpec((cinp, coutp), lambda j, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, coutp), lambda j, i: (i, 0)),
            pl.BlockSpec((SUBLANE_F32, coutp), lambda j, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, coutp), jnp.bfloat16),
            jax.ShapeDtypeStruct((SUBLANE_F32, coutp), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bm, coutp), jnp.float32)],
        interpret=interpret,
    )(xp, sp, tp, wp)
    return y[:m, :cout], st[:2, :cout]


def _pw_fwd_rule(x, scale, shift, w, relu_in, interpret):
    y, st = _pw_forward(x, scale, shift, w, relu_in, interpret)
    return (y, st), (x, scale, shift, w, y)


def _pw_bwd_rule(relu_in, interpret, res, cts):
    x, scale, shift, w, z = res
    dz, dst = cts
    m, cin, cout, mp, cinp, coutp = _pw_shapes(x, w)
    bm = _pw_block_m(mp, cinp, coutp)
    mp = _round_up(mp, bm)
    xp = _pad_axis(_pad_axis(x, 0, mp), 1, cinp)
    zp = _pad_axis(_pad_axis(z, 0, mp), 1, coutp)
    dzp = _pad_axis(_pad_axis(dz, 0, mp), 1, coutp)
    dstp = _pad_axis(_pad_axis(dst, 0, SUBLANE_F32), 1, coutp)
    wp = _pad_axis(_pad_axis(w, 0, cinp), 1, coutp)
    sp = _pad_axis(scale.reshape(1, -1), 1, cinp)
    tp = _pad_axis(shift.reshape(1, -1), 1, cinp)

    dx, gs, gt = pl.pallas_call(
        functools.partial(_pw_bwd_dx_kernel, relu_in=relu_in, m_valid=m,
                          bm=bm),
        grid=(1, mp // bm),
        in_specs=[
            pl.BlockSpec((bm, cinp), lambda j, i: (i, 0)),
            pl.BlockSpec((1, cinp), lambda j, i: (0, 0)),
            pl.BlockSpec((1, cinp), lambda j, i: (0, 0)),
            pl.BlockSpec((cinp, coutp), lambda j, i: (0, 0)),
            pl.BlockSpec((bm, coutp), lambda j, i: (i, 0)),
            pl.BlockSpec((bm, coutp), lambda j, i: (i, 0)),
            pl.BlockSpec((SUBLANE_F32, coutp), lambda j, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, cinp), lambda j, i: (i, 0)),
            pl.BlockSpec((SUBLANE_F32, cinp), lambda j, i: (0, 0)),
            pl.BlockSpec((SUBLANE_F32, cinp), lambda j, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, cinp), x.dtype),
            jax.ShapeDtypeStruct((SUBLANE_F32, cinp), jnp.float32),
            jax.ShapeDtypeStruct((SUBLANE_F32, cinp), jnp.float32),
        ],
        interpret=interpret,
    )(xp, sp, tp, wp, zp, dzp, dstp)

    dw = pl.pallas_call(
        functools.partial(_pw_bwd_dw_kernel, relu_in=relu_in, m_valid=m,
                          bm=bm),
        grid=(mp // bm,),
        in_specs=[
            pl.BlockSpec((bm, cinp), lambda i: (i, 0)),
            pl.BlockSpec((1, cinp), lambda i: (0, 0)),
            pl.BlockSpec((1, cinp), lambda i: (0, 0)),
            pl.BlockSpec((bm, coutp), lambda i: (i, 0)),
            pl.BlockSpec((bm, coutp), lambda i: (i, 0)),
            pl.BlockSpec((SUBLANE_F32, coutp), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((cinp, coutp), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((cinp, coutp), jnp.float32),
        interpret=interpret,
    )(xp, sp, tp, zp, dzp, dstp)

    return (dx[:m, :cin],
            gs[0, :cin],
            gt[0, :cin],
            dw[:cin, :cout].astype(w.dtype))


pw_conv.defvjp(_pw_fwd_rule, _pw_bwd_rule)


# ---------------------------------------------------------------------------
# 3x3 SAME stride-1 fused conv
# ---------------------------------------------------------------------------


def _c3_fwd_kernel(x_ref, s_ref, t_ref, w_ref, y_ref, st_ref, xp_ref,
                   acc_ref, *, relu_in: bool, h: int, wd: int, cinp: int):
    n = pl.program_id(0)
    xn = _fold(x_ref[0], s_ref[0, :], t_ref[0, :], relu_in).astype(jnp.bfloat16)
    xp_ref[...] = jnp.zeros_like(xp_ref)
    xp_ref[1:h + 1, 1:wd + 1, :] = xn
    acc_ref[...] = jnp.zeros_like(acc_ref)
    for dy in range(3):
        for dx in range(3):
            op = xp_ref[dy:dy + h, dx:dx + wd, :].reshape(h * wd, cinp)
            acc_ref[...] += jnp.dot(op, w_ref[dy, dx],
                                    preferred_element_type=jnp.float32, precision=_PREC)
    y = acc_ref[...]
    y_ref[0] = y.reshape(h, wd, -1).astype(jnp.bfloat16)

    @pl.when(n == 0)
    def _():
        st_ref[...] = jnp.zeros_like(st_ref)

    st_ref[0:1, :] += jnp.sum(y, axis=0, keepdims=True)
    st_ref[1:2, :] += jnp.sum(y * y, axis=0, keepdims=True)


def _c3_bwd_dx_kernel(x_ref, s_ref, t_ref, w_ref, z_ref, dz_ref, ds_ref,
                      dx_ref, gs_ref, gt_ref, dxp_ref,
                      *, relu_in: bool, h: int, wd: int, coutp: int):
    n = pl.program_id(0)
    dzeff = (dz_ref[0].astype(jnp.float32)
             + ds_ref[0:1, :].reshape(1, 1, -1)
             + 2.0 * z_ref[0].astype(jnp.float32)
             * ds_ref[1:2, :].reshape(1, 1, -1))
    dzf = dzeff.reshape(h * wd, coutp).astype(jnp.bfloat16)
    dxp_ref[...] = jnp.zeros_like(dxp_ref)
    for dy in range(3):
        for dx in range(3):
            g = jax.lax.dot_general(
                dzf, w_ref[dy, dx],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            ).reshape(h, wd, -1)
            dxp_ref[dy:dy + h, dx:dx + wd, :] += g
    x = x_ref[0].astype(jnp.float32)
    u = x * s_ref[0, :] + t_ref[0, :]
    dxn = dxp_ref[1:h + 1, 1:wd + 1, :]
    du = jnp.where(u > 0, dxn, 0.0) if relu_in else dxn
    dx_ref[0] = (du * s_ref[0, :]).astype(dx_ref.dtype)

    @pl.when(n == 0)
    def _():
        gs_ref[...] = jnp.zeros_like(gs_ref)
        gt_ref[...] = jnp.zeros_like(gt_ref)

    gs_ref[0:1, :] += jnp.sum(du * x, axis=(0, 1)).reshape(1, -1)
    gt_ref[0:1, :] += jnp.sum(du, axis=(0, 1)).reshape(1, -1)


def _c3_bwd_dw_kernel(x_ref, s_ref, t_ref, z_ref, dz_ref, ds_ref, dw_ref,
                      xp_ref, *, relu_in: bool, h: int, wd: int, cinp: int,
                      coutp: int):
    n = pl.program_id(0)
    xn = _fold(x_ref[0], s_ref[0, :], t_ref[0, :], relu_in).astype(jnp.bfloat16)
    xp_ref[...] = jnp.zeros_like(xp_ref)
    xp_ref[1:h + 1, 1:wd + 1, :] = xn
    dzeff = (dz_ref[0].astype(jnp.float32)
             + ds_ref[0:1, :].reshape(1, 1, -1)
             + 2.0 * z_ref[0].astype(jnp.float32)
             * ds_ref[1:2, :].reshape(1, 1, -1))
    dzf = dzeff.reshape(h * wd, coutp).astype(jnp.bfloat16)

    @pl.when(n == 0)
    def _():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    for dy in range(3):
        for dx in range(3):
            op = xp_ref[dy:dy + h, dx:dx + wd, :].reshape(h * wd, cinp)
            dw_ref[dy, dx] += jax.lax.dot_general(
                op, dzf,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=_PREC,
            )


def _c3_shapes(x, w):
    n, h, wd, cin = x.shape
    cout = w.shape[-1]
    cinp = _round_up(cin, LANE)
    coutp = _round_up(cout, LANE)
    return n, h, wd, cin, cout, cinp, coutp


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def conv3x3(x, scale, shift, w, relu_in: bool = False,
            interpret: bool = False):
    """Fused 3x3 SAME stride-1 conv: (y, stats) with the same contract as
    :func:`pw_conv`. x: (N, H, W, Cin) bf16; w: (3, 3, Cin, Cout) bf16."""
    return _c3_forward(x, scale, shift, w, relu_in, interpret)


def _c3_forward(x, scale, shift, w, relu_in, interpret):
    n, h, wd, cin, cout, cinp, coutp = _c3_shapes(x, w)
    xp = _pad_axis(x, 3, cinp)
    wp = _pad_axis(_pad_axis(w, 2, cinp), 3, coutp)
    sp = _pad_axis(scale.reshape(1, -1), 1, cinp)
    tp = _pad_axis(shift.reshape(1, -1), 1, cinp)
    y, st = pl.pallas_call(
        functools.partial(_c3_fwd_kernel, relu_in=relu_in, h=h, wd=wd,
                          cinp=cinp),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, wd, cinp), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, cinp), lambda i: (0, 0)),
            pl.BlockSpec((1, cinp), lambda i: (0, 0)),
            pl.BlockSpec((3, 3, cinp, coutp), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, wd, coutp), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((SUBLANE_F32, coutp), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wd, coutp), jnp.bfloat16),
            jax.ShapeDtypeStruct((SUBLANE_F32, coutp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((h + 2, wd + 2, cinp), jnp.bfloat16),
            pltpu.VMEM((h * wd, coutp), jnp.float32),
        ],
        interpret=interpret,
    )(xp, sp, tp, wp)
    return y[..., :cout], st[:2, :cout]


def _c3_fwd_rule(x, scale, shift, w, relu_in, interpret):
    y, st = _c3_forward(x, scale, shift, w, relu_in, interpret)
    return (y, st), (x, scale, shift, w, y)


def _c3_bwd_rule(relu_in, interpret, res, cts):
    x, scale, shift, w, z = res
    dz, dst = cts
    n, h, wd, cin, cout, cinp, coutp = _c3_shapes(x, w)
    xp = _pad_axis(x, 3, cinp)
    zp = _pad_axis(z, 3, coutp)
    dzp = _pad_axis(dz, 3, coutp)
    dstp = _pad_axis(_pad_axis(dst, 0, SUBLANE_F32), 1, coutp)
    wp = _pad_axis(_pad_axis(w, 2, cinp), 3, coutp)
    sp = _pad_axis(scale.reshape(1, -1), 1, cinp)
    tp = _pad_axis(shift.reshape(1, -1), 1, cinp)

    dx, gs, gt = pl.pallas_call(
        functools.partial(_c3_bwd_dx_kernel, relu_in=relu_in, h=h, wd=wd,
                          coutp=coutp),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, wd, cinp), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, cinp), lambda i: (0, 0)),
            pl.BlockSpec((1, cinp), lambda i: (0, 0)),
            pl.BlockSpec((3, 3, cinp, coutp), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((1, h, wd, coutp), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, h, wd, coutp), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((SUBLANE_F32, coutp), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, wd, cinp), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((SUBLANE_F32, cinp), lambda i: (0, 0)),
            pl.BlockSpec((SUBLANE_F32, cinp), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wd, cinp), x.dtype),
            jax.ShapeDtypeStruct((SUBLANE_F32, cinp), jnp.float32),
            jax.ShapeDtypeStruct((SUBLANE_F32, cinp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((h + 2, wd + 2, cinp), jnp.float32),
        ],
        interpret=interpret,
    )(xp, sp, tp, wp, zp, dzp, dstp)

    dw = pl.pallas_call(
        functools.partial(_c3_bwd_dw_kernel, relu_in=relu_in, h=h, wd=wd,
                          cinp=cinp, coutp=coutp),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, wd, cinp), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, cinp), lambda i: (0, 0)),
            pl.BlockSpec((1, cinp), lambda i: (0, 0)),
            pl.BlockSpec((1, h, wd, coutp), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, h, wd, coutp), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((SUBLANE_F32, coutp), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((3, 3, cinp, coutp), lambda i: (0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((3, 3, cinp, coutp), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((h + 2, wd + 2, cinp), jnp.bfloat16),
        ],
        interpret=interpret,
    )(xp, sp, tp, zp, dzp, dstp)

    return (dx[..., :cin],
            gs[0, :cin],
            gt[0, :cin],
            dw[:, :, :cin, :cout].astype(w.dtype))


conv3x3.defvjp(_c3_fwd_rule, _c3_bwd_rule)


# ---------------------------------------------------------------------------
# compile-probe gate (the flash-attention pattern: AOT compile + execute a
# tiny instance, value-check fwd AND grads against the XLA reference; a
# lagging server-side Mosaic can reject OR miscompile)
# ---------------------------------------------------------------------------

_PROBE_CACHE: dict = {}


def fused_conv_available(dtype=jnp.bfloat16) -> bool:
    """True when the Pallas fused-conv ops compile AND compute correct
    values/gradients on this backend. Verdicts live in the kernel
    REGISTRY (probe-once-per-process, ``DL4J_TPU_FUSED_CONV=0`` kill
    switch honored, fallbacks observable); ``_PROBE_CACHE`` mirrors them
    for introspection only — the registry is authoritative, so
    ``KernelRegistry.reset("fused_conv")`` genuinely re-probes. The
    interpret mode is not supported here (the fused-block layer calls
    the compiled kernels; tests drive ``interpret=`` explicitly)."""
    from deeplearning4j_tpu.nn.ops.registry import default_kernel_registry

    key = jnp.dtype(dtype).name
    reg = default_kernel_registry()
    cached = reg.enabled("fused_conv", (key,))
    if cached is not None:
        _PROBE_CACHE[key] = cached
        return cached
    if reg.mode("fused_conv") == "off":
        reg.disable("fused_conv", (key,),
                    "disabled via DL4J_TPU_FUSED_CONV=0")
        _PROBE_CACHE[key] = False
        return False

    def probe():
        rng = np.random.default_rng(0)

        def mk(shape, scale=1.0, shift=0.0, dt=dtype):
            # numpy (never jnp): under an ambient trace jnp.asarray
            # stages into the caller's graph and the AOT executables
            # below would be handed tracers instead of concrete
            # buffers — the exact latent bug the flash probe had
            return np.asarray(rng.standard_normal(shape) * scale + shift,
                              np.float32).astype(jnp.dtype(dt))

        x2 = mk((64, 128))
        s = mk(128, 0.2, 1.0, jnp.float32)
        t = mk(128, 0.1, 0.0, jnp.float32)
        w2 = mk((128, 128), 0.05)
        x4 = mk((1, 8, 8, 128))
        w4 = mk((3, 3, 128, 128), 0.05)

        def loss(fn):
            def f(x, s, t, w):
                y, st = fn(x, s, t, w)
                return jnp.sum(y.astype(jnp.float32) ** 2) * 1e-3 + jnp.sum(
                    st * 1e-4)
            return f

        for kern, ref, args in (
            (functools.partial(pw_conv, relu_in=True),
             functools.partial(pw_conv_reference, relu_in=True),
             (x2, s, t, w2)),
            (functools.partial(conv3x3, relu_in=True),
             functools.partial(conv3x3_reference, relu_in=True),
             (x4, s, t, w4)),
        ):
            shapes = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args]
            vg_k = jax.jit(jax.value_and_grad(
                loss(kern), argnums=(0, 1, 2, 3))).lower(*shapes).compile()
            vg_r = jax.jit(jax.value_and_grad(
                loss(ref), argnums=(0, 1, 2, 3))).lower(*shapes).compile()
            vk, gk = vg_k(*args)
            vr, gr = vg_r(*args)
            tol = 5e-2
            if not np.isfinite(float(vk)) or abs(float(vk) - float(vr)) > \
                    tol * (abs(float(vr)) + 1.0):
                raise RuntimeError(f"fused-conv probe value mismatch: "
                                   f"{float(vk)} vs {float(vr)}")
            for a, b in zip(jax.tree_util.tree_leaves(gk),
                            jax.tree_util.tree_leaves(gr)):
                a = np.asarray(a, np.float32)
                b = np.asarray(b, np.float32)
                err = np.max(np.abs(a - b) / (np.abs(b) + 1.0))
                if not np.isfinite(err) or err > tol:
                    raise RuntimeError(
                        f"fused-conv probe grad mismatch: rel {err:.3e}")

    ok = reg.probe("fused_conv", (key,), probe)
    _PROBE_CACHE[key] = ok
    return ok


# ---------------------------------------------------------------------------
# pure-XLA reference implementations (parity oracle + fallback path)
# ---------------------------------------------------------------------------


def pw_conv_reference(x, scale, shift, w, relu_in: bool = False):
    xn = _fold(x, scale, shift, relu_in).astype(x.dtype)
    # plain XLA — inherits the package "highest" default (fp32 parity);
    # the _PREC pin is for in-Mosaic-kernel dots only
    y = jnp.dot(xn, w, preferred_element_type=jnp.float32)
    st = jnp.stack([y.sum(0), (y * y).sum(0)])
    return y.astype(x.dtype), st


def conv3x3_reference(x, scale, shift, w, relu_in: bool = False):
    # f32 operands on bf16-rounded values == bf16 matmul with f32
    # accumulation (products exact in f32), and keeps the autodiff
    # cotangent dtypes consistent
    xn = _fold(x, scale, shift, relu_in).astype(x.dtype).astype(jnp.float32)
    y = jax.lax.conv_general_dilated(
        xn, w.astype(jnp.float32), window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    st = jnp.stack([y.sum((0, 1, 2)), (y * y).sum((0, 1, 2))])
    return y.astype(x.dtype), st
