"""Int8 weight-quantized serving matmul with per-channel scales
(TPP-style, arXiv 2104.05755; the weight-only-quantization serving
recipe).

The big serving matmuls — dense/output heads at batch-bucket shapes —
are memory-bandwidth-bound on TPU: the weight matrix streams from HBM
once per dispatch while the MXU idles. Storing W as int8 with one fp32
scale per OUTPUT channel halves-to-quarters the weight bytes:

    q[:, j]  = clip(round(W[:, j] / s_j), -127, 127),   s_j = max|W[:, j]|/127
    y        = (x @ float(q)) · s        (scale applied AFTER accumulation)

The Pallas kernel streams the int8 tile HBM→VMEM (the bandwidth win),
widens on the VPU, hits the MXU with f32 accumulation and applies the
per-channel scale to the accumulator tile before it leaves VMEM. The
XLA reference path (`int8_matmul_reference`) computes the SAME
expression — it is the fallback on probe failure and the parity oracle:
kernel vs reference carries a small documented tolerance (one MXU pass
vs the package's "highest"-precision XLA dot); quantized-vs-f32 carries
the quantization error itself (≈ |W|∞/254 per channel — documented, and
bounded in tests by serving top-1 agreement on zoo models).

Opt-in only: training never sees int8 — quantization happens when an
``InferenceEngine(int8_serving=True)`` builds a serving snapshot
(``quantize_model_params``), rewriting eligible layers' param dicts
from ``{"W": ...}`` to ``{"W_q8": int8, "W_scale": f32}``. The layers'
forward routes through :func:`serving_matmul`, which dispatches on the
dict keys at trace time — fp32 params compile the exact program they
always did. Availability via ``nn.ops.registry``
(``DL4J_TPU_INT8_MATMUL`` = 0 | 1 | interpret).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from deeplearning4j_tpu.nn.ops.kernel_compat import PRECISION as _PREC

_LANE = 128
_SUBLANE = 8

#: params-dict key suffixes of a quantized weight (serving snapshots only)
Q_SUFFIX = "_q8"
SCALE_SUFFIX = "_scale"


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


# --------------------------------------------------------------------------
# quantization (host side, once per serving snapshot)
# --------------------------------------------------------------------------
def quantize_int8(w) -> Tuple[np.ndarray, np.ndarray]:
    """(K, N) float weights → (int8 (K, N), fp32 per-output-channel
    scale (N,)). Symmetric round-to-nearest; all-zero channels get a
    tiny scale so dequantization is exact zero."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=0)
    scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def int8_matmul_reference(x, q, scale):
    """The XLA composition — fallback path + parity oracle. Same
    expression as the kernel: scale AFTER the f32 accumulation."""
    return (x @ q.astype(x.dtype)) * scale.astype(x.dtype)


# --------------------------------------------------------------------------
# the kernel
# --------------------------------------------------------------------------
def _int8_kernel(x_ref, q_ref, s_ref, o_ref):
    x = x_ref[...]
    w = q_ref[...].astype(x.dtype)  # widen in VMEM — int8 crossed HBM
    y = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=_PREC)
    o_ref[...] = (y * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def int8_matmul(x, q, scale, *, interpret: bool = False):
    """x (B, K) · q (K, N) int8, per-channel ``scale`` (N,). Serving
    only (no VJP — quantized weights are never trained through)."""
    B, K = x.shape
    N = q.shape[1]
    B_p = _round_up(B, _SUBLANE)
    K_p = _round_up(K, _LANE)
    N_p = _round_up(N, _LANE)
    xp = jnp.pad(x, ((0, B_p - B), (0, K_p - K)))
    qp = jnp.pad(q, ((0, K_p - K), (0, N_p - N)))
    sp = jnp.pad(scale.reshape(1, -1), ((0, 0), (0, N_p - N)))
    out = pl.pallas_call(
        _int8_kernel,
        out_shape=jax.ShapeDtypeStruct((B_p, N_p), x.dtype),
        interpret=interpret,
    )(xp, qp, sp)
    return out[:B, :N]


# --------------------------------------------------------------------------
# probe + trace-time dispatch
# --------------------------------------------------------------------------
def _probe_int8(K: int, N: int, dtype, interpret: bool,
                B: int = 8) -> None:
    """``B`` is the caller's padded dispatch batch, not a toy size: the
    un-gridded kernel holds the whole (B, K) activation tile in VMEM,
    so an overflow at the real bucket must fail the probe, not the
    serving dispatch's compile."""
    rng = np.random.default_rng(0)
    # numpy args: probes may run under an ambient trace (see fused_lstm)
    x = np.asarray(rng.standard_normal((B, K)),
                   np.float32).astype(jnp.dtype(dtype))
    w = np.asarray(rng.standard_normal((K, N)) * 0.1, np.float32)
    q, s = quantize_int8(w)

    def kern(x, q, s):
        return int8_matmul(x, q, s, interpret=interpret)

    shapes = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in (x, q, s)]
    got = jax.jit(kern).lower(*shapes).compile()(x, q, s)
    want = jax.jit(int8_matmul_reference).lower(*shapes).compile()(x, q, s)
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    denom = np.max(np.abs(want)) + 1e-6
    err = np.max(np.abs(got - want)) / denom
    tol = 2e-2 if jnp.dtype(dtype) == jnp.bfloat16 else 1e-4
    if not np.isfinite(err) or err > tol:
        raise RuntimeError(
            f"int8 matmul kernel vs reference mismatch: rel {err:.3e} "
            f"> {tol}")


def _impl_for(K: int, N: int, dtype, batch: int = 8):
    """Kernel impl (or the XLA reference) for this instantiation,
    registry-cached per (K, N, dtype, padded-batch)."""
    from deeplearning4j_tpu.nn.ops.registry import default_kernel_registry

    dtype = jnp.dtype(dtype)
    B_p = _round_up(max(int(batch), 1), _SUBLANE)
    key = (int(K), int(N), dtype.name, B_p)
    interpret = default_kernel_registry().resolve(
        "int8_matmul", key,
        lambda interp: functools.partial(
            _probe_int8, int(K), int(N), dtype, interp, B=B_p))
    if interpret is None:
        return int8_matmul_reference
    return functools.partial(int8_matmul, interpret=interpret)


def serving_matmul(params: Dict, x, name: str = "W"):
    """``x @ params[name]`` — or the int8 route when ``params`` carries
    the quantized form (``name_q8``/``name_scale``). The branch is a
    trace-time dict-key check: fp32 snapshots compile the program they
    always did. Handles rank-2 (B, K) and rank-3 (B, T, K) activations
    (the per-timestep heads)."""
    q = params.get(name + Q_SUFFIX)
    if q is None:
        return x @ params[name]
    scale = params[name + SCALE_SUFFIX]
    if x.ndim == 2:
        impl = _impl_for(q.shape[0], q.shape[1], x.dtype, x.shape[0])
        return impl(x, q, scale)
    lead = x.shape[:-1]
    rows = int(np.prod(lead))
    impl = _impl_for(q.shape[0], q.shape[1], x.dtype, rows)
    y = impl(x.reshape((rows, x.shape[-1])), q, scale)
    return y.reshape(lead + (q.shape[1],))


# --------------------------------------------------------------------------
# model-level quantization (engine snapshot build)
# --------------------------------------------------------------------------
def quantizable_layer(layer) -> bool:
    """Layers whose ``W`` routes through :func:`serving_matmul`: the
    dense/output heads. Recurrent gate matrices stay fp32 (decode runs
    at slot-count batch — compute-bound, and the fused cell owns that
    path)."""
    from deeplearning4j_tpu.nn.conf.layers.core import (
        BaseOutputLayer,
        DenseLayer,
    )
    from deeplearning4j_tpu.nn.conf.layers.recurrent import RnnOutputLayer

    return isinstance(layer, (DenseLayer, BaseOutputLayer, RnnOutputLayer))


def quantize_layer_params(params: Dict, name: str = "W") -> Dict:
    """One layer's param dict with ``name`` replaced by its quantized
    form. No-op (same dict) when the weight is absent/not 2-D."""
    w = params.get(name)
    if w is None or getattr(w, "ndim", 0) != 2:
        return params
    q, s = quantize_int8(np.asarray(w, np.float32))
    out = {k: v for k, v in params.items() if k != name}
    out[name + Q_SUFFIX] = jnp.asarray(q)
    out[name + SCALE_SUFFIX] = jnp.asarray(s)
    return out


def quantize_model_params(model) -> Tuple[list, dict]:
    """A COPY of ``model.params_`` with every eligible layer's W
    int8-quantized + a byte report. The model itself is untouched —
    this is a serving-snapshot transform, not a training mutation."""
    layers = model.layers
    new_params = []
    fp32_bytes = 0
    int8_bytes = 0
    n_q = 0
    for layer, p in zip(layers, model.params_):
        if quantizable_layer(layer) and "W" in p:
            w = p["W"]
            qp = quantize_layer_params(p)
            if "W" + Q_SUFFIX in qp:
                n_q += 1
                fp32_bytes += int(np.prod(w.shape)) * w.dtype.itemsize
                int8_bytes += int(np.prod(w.shape)) + w.shape[1] * 4
                new_params.append(qp)
                continue
        new_params.append(p)
    return new_params, {
        "layers_quantized": n_q,
        "weight_bytes_fp32": fp32_bytes,
        "weight_bytes_int8": int8_bytes,
        "bytes_saved": fp32_bytes - int8_bytes,
    }
