"""Fused LSTM cell as a Pallas TPU kernel (TPP-style, arXiv 2104.05755).

One time step of the (Graves)LSTM — the hot inner loop of the textgen
training scan and of the GenerationEngine's per-slot decode — is four
gate matmuls plus a chain of elementwise ops:

    z = x_t @ Wx + h @ Wh + b          # (B, 4n): gates [i, f, o, g]
    i = σ(z_i [+ pI·c]); f = σ(z_f [+ pF·c]); g = tanh(z_g)
    c' = f·c + i·g
    o = σ(z_o [+ pO·c'])
    h' = o·tanh(c')

XLA lowers this as separate gemm + elementwise ops whose intermediates
(z, the four gates, c') round-trip HBM every step of every scan
iteration. This kernel computes the whole cell in one ``pallas_call``:
both gemms hit the MXU with f32 accumulation, the gate chain runs on the
VPU over the z tile still resident in VMEM, and only (h', c') leave the
kernel — the scan-friendly carry layout, ``(B, n)`` each, exactly what
``lax.scan`` carries between steps.

Layout: gate blocks are padded **independently** to the 128-lane tile
(``Wx (nIn, 4, n) → (nIn_p, 4·n_p)``), so in-kernel gate slicing at
``n_p`` boundaries reads the same values the reference reads at ``n``
boundaries; padded lanes carry zero weights/bias and provably stay zero
through the gate chain (σ(0)·tanh(0) = 0), so the sliced-off columns
never contaminate real ones.

Differentiation: ``custom_vjp``. The forward is the fused kernel; the
backward recomputes the gates from the saved ``(x, h, c)`` residuals and
applies the standard LSTM cell gradient as an XLA composition (the
flash-attention recompute discipline — recompute in the backward instead
of materializing gate activations in the forward). Parity contract
(tests/test_fused_kernels.py): forward bit-exact vs the reference step
at fp32 under the interpreter; gradients allclose at ≤1e-5; bf16 carries
the documented ~1e-2 tolerance of one MXU pass vs the "highest"
-precision XLA path.

Availability runs through ``nn.ops.registry`` (probe-once-per-process,
``kernel_fallback`` flight event + ``kernel_enabled{name=fused_lstm}``
gauge): kill/mode switch ``DL4J_TPU_FUSED_LSTM`` = 0 | 1 (auto) |
interpret. Only tanh/sigmoid cells route to the kernel — exotic
activations stay on the reference step.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from deeplearning4j_tpu.nn.ops.kernel_compat import PRECISION as _PREC

_LANE = 128
_SUBLANE = 8


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


# --------------------------------------------------------------------------
# reference cell (the exact math of LSTM._step / GravesLSTM._step)
# --------------------------------------------------------------------------
def reference_lstm_cell(x, h, c, Wx, Wh, b, pI=None, pF=None, pO=None):
    """The pure-XLA cell — fallback path and parity oracle. Must stay
    bit-identical to ``recurrent.LSTM._step`` (tanh/sigmoid instance):
    same expressions, same order."""
    z = x @ Wx + h @ Wh + b
    n = h.shape[-1]
    if pI is not None:
        i = jax.nn.sigmoid(z[:, :n] + pI * c)
        f = jax.nn.sigmoid(z[:, n:2 * n] + pF * c)
        g = jnp.tanh(z[:, 3 * n:])
        c_new = f * c + i * g
        o = jax.nn.sigmoid(z[:, 2 * n:3 * n] + pO * c_new)
    else:
        i = jax.nn.sigmoid(z[:, :n])
        f = jax.nn.sigmoid(z[:, n:2 * n])
        o = jax.nn.sigmoid(z[:, 2 * n:3 * n])
        g = jnp.tanh(z[:, 3 * n:])
        c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


# --------------------------------------------------------------------------
# the kernel
# --------------------------------------------------------------------------
def _cell_kernel(x_ref, h_ref, c_ref, wx_ref, wh_ref, b_ref, *rest,
                 n_p: int, peephole: bool):
    if peephole:
        pi_ref, pf_ref, po_ref, h_out, c_out = rest
    else:
        (h_out, c_out) = rest
    x = x_ref[...]
    h = h_ref[...]
    c = c_ref[...].astype(jnp.float32)
    # both gate gemms accumulate f32 on the MXU; bias add on the VPU
    z = jax.lax.dot_general(x, wx_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=_PREC)
    z = z + jax.lax.dot_general(h, wh_ref[...], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_PREC)
    z = z + b_ref[...].astype(jnp.float32)
    zi = z[:, :n_p]
    zf = z[:, n_p:2 * n_p]
    zo = z[:, 2 * n_p:3 * n_p]
    zg = z[:, 3 * n_p:]
    if peephole:
        i = jax.nn.sigmoid(zi + pi_ref[...] * c)
        f = jax.nn.sigmoid(zf + pf_ref[...] * c)
        g = jnp.tanh(zg)
        c_new = f * c + i * g
        o = jax.nn.sigmoid(zo + po_ref[...] * c_new)
    else:
        i = jax.nn.sigmoid(zi)
        f = jax.nn.sigmoid(zf)
        o = jax.nn.sigmoid(zo)
        g = jnp.tanh(zg)
        c_new = f * c + i * g
    h_out[...] = (o * jnp.tanh(c_new)).astype(h_out.dtype)
    c_out[...] = c_new.astype(c_out.dtype)


def _pack_gates(w, n: int, n_p: int):
    """(d, 4n) gate-packed matrix → (d, 4·n_p) with each gate block
    zero-padded independently to the lane tile."""
    d = w.shape[0]
    w4 = w.reshape(d, 4, n)
    if n_p != n:
        w4 = jnp.pad(w4, ((0, 0), (0, 0), (0, n_p - n)))
    return w4.reshape(d, 4 * n_p)


def _cell_impl(x, h, c, Wx, Wh, b, peeps, interpret: bool):
    B, n_in = x.shape
    n = h.shape[-1]
    n_p = _round_up(n, _LANE)
    in_p = _round_up(n_in, _LANE)
    B_p = _round_up(B, _SUBLANE)

    def pad2(a, rows, cols):
        return jnp.pad(a, ((0, rows - a.shape[0]), (0, cols - a.shape[1])))

    xp = pad2(x, B_p, in_p)
    hp = pad2(h, B_p, n_p)
    cp = pad2(c, B_p, n_p)
    wxp = pad2(_pack_gates(Wx, n, n_p), in_p, 4 * n_p)
    whp = pad2(_pack_gates(Wh, n, n_p), n_p, 4 * n_p)
    bp = _pack_gates(b.reshape(1, -1), n, n_p)
    args = [xp, hp, cp, wxp, whp, bp]
    if peeps is not None:
        for pvec in peeps:
            args.append(jnp.pad(pvec.reshape(1, -1), ((0, 0), (0, n_p - n))))
    kern = functools.partial(_cell_kernel, n_p=n_p,
                             peephole=peeps is not None)
    h_new, c_new = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct((B_p, n_p), h.dtype),
                   jax.ShapeDtypeStruct((B_p, n_p), c.dtype)],
        interpret=interpret,
    )(*args)
    return h_new[:B, :n], c_new[:B, :n]


# --------------------------------------------------------------------------
# backward (XLA composition; recomputes gates from residuals)
# --------------------------------------------------------------------------
def _cell_bwd_math(x, h, c, Wx, Wh, b, peeps, dh, dc):
    pI, pF, pO = peeps if peeps is not None else (None, None, None)
    z = x @ Wx + h @ Wh + b
    n = h.shape[-1]
    zi, zf, zo, zg = (z[:, :n], z[:, n:2 * n], z[:, 2 * n:3 * n],
                      z[:, 3 * n:])
    if pI is not None:
        i = jax.nn.sigmoid(zi + pI * c)
        f = jax.nn.sigmoid(zf + pF * c)
    else:
        i = jax.nn.sigmoid(zi)
        f = jax.nn.sigmoid(zf)
    g = jnp.tanh(zg)
    c_new = f * c + i * g
    o = jax.nn.sigmoid(zo + pO * c_new if pO is not None else zo)
    tanh_c = jnp.tanh(c_new)

    do = dh * tanh_c
    dzo = do * o * (1.0 - o)
    dc_t = dc + dh * o * (1.0 - tanh_c * tanh_c)
    if pO is not None:
        dc_t = dc_t + dzo * pO
    di = dc_t * g
    df = dc_t * c
    dg = dc_t * i
    dzi = di * i * (1.0 - i)
    dzf = df * f * (1.0 - f)
    dzg = dg * (1.0 - g * g)
    dc_prev = dc_t * f
    if pI is not None:
        dc_prev = dc_prev + dzi * pI + dzf * pF
    dz = jnp.concatenate([dzi, dzf, dzo, dzg], axis=1)
    dx = dz @ Wx.T
    dh_prev = dz @ Wh.T
    dWx = x.T @ dz
    dWh = h.T @ dz
    db = jnp.sum(dz, axis=0)
    out = (dx, dh_prev, dc_prev, dWx.astype(Wx.dtype),
           dWh.astype(Wh.dtype), db.astype(b.dtype))
    if pI is not None:
        dpI = jnp.sum(dzi * c, axis=0).astype(pI.dtype)
        dpF = jnp.sum(dzf * c, axis=0).astype(pF.dtype)
        dpO = jnp.sum(dzo * c_new, axis=0).astype(pO.dtype)
        return out + (dpI, dpF, dpO)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def _cell_plain(x, h, c, Wx, Wh, b, interpret):
    return _cell_impl(x, h, c, Wx, Wh, b, None, interpret)


def _cell_plain_fwd(x, h, c, Wx, Wh, b, interpret):
    out = _cell_impl(x, h, c, Wx, Wh, b, None, interpret)
    return out, (x, h, c, Wx, Wh, b)


def _cell_plain_bwd(interpret, res, cts):
    x, h, c, Wx, Wh, b = res
    dh, dc = cts
    return _cell_bwd_math(x, h, c, Wx, Wh, b, None, dh, dc)


_cell_plain.defvjp(_cell_plain_fwd, _cell_plain_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(9,))
def _cell_peep(x, h, c, Wx, Wh, b, pI, pF, pO, interpret):
    return _cell_impl(x, h, c, Wx, Wh, b, (pI, pF, pO), interpret)


def _cell_peep_fwd(x, h, c, Wx, Wh, b, pI, pF, pO, interpret):
    out = _cell_impl(x, h, c, Wx, Wh, b, (pI, pF, pO), interpret)
    return out, (x, h, c, Wx, Wh, b, pI, pF, pO)


def _cell_peep_bwd(interpret, res, cts):
    x, h, c, Wx, Wh, b, pI, pF, pO = res
    dh, dc = cts
    return _cell_bwd_math(x, h, c, Wx, Wh, b, (pI, pF, pO), dh, dc)


_cell_peep.defvjp(_cell_peep_fwd, _cell_peep_bwd)


def fused_lstm_cell(x, h, c, Wx, Wh, b, pI=None, pF=None, pO=None, *,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """One fused LSTM step → (h_new, c_new). Peephole (GravesLSTM) when
    pI/pF/pO are given. Differentiable (custom VJP; backward is the XLA
    gate-recompute composition)."""
    if pI is not None:
        return _cell_peep(x, h, c, Wx, Wh, b, pI, pF, pO, interpret)
    return _cell_plain(x, h, c, Wx, Wh, b, interpret)


# --------------------------------------------------------------------------
# probe + routing (registry-cached per instantiation)
# --------------------------------------------------------------------------
def _probe_cell(n_in: int, n: int, dtype, peephole: bool,
                interpret: bool, B: int = 8) -> None:
    """Compile (AOT — safe under an ambient trace) and EXECUTE the fused
    cell forward + grad at a (B, n_in/n) instance; compare against the
    reference cell. Raises on any mismatch — a lagging server-side
    Mosaic can MIScompile, not just reject. ``B`` is the CALLER's padded
    batch, not a toy size: a VMEM overflow at the real batch must fail
    the probe, not the training step's compile."""
    rng = np.random.default_rng(0)

    def mk(shape):
        # numpy (never jnp): under an ambient trace jnp ops stage into
        # the caller's graph and the AOT executables below would be
        # handed tracers instead of concrete buffers
        return np.asarray(rng.standard_normal(shape),
                          np.float32).astype(jnp.dtype(dtype))

    x, h, c = mk((B, n_in)), mk((B, n)), mk((B, n))
    Wx, Wh = mk((n_in, 4 * n)), mk((n, 4 * n))
    b = mk((4 * n,))
    peeps = (mk((n,)), mk((n,)), mk((n,))) if peephole else ()
    args = (x, h, c, Wx, Wh, b) + peeps
    shapes = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args]

    def loss(cell):
        def f(*a):
            h_new, c_new = cell(*a)
            return (jnp.sum(h_new.astype(jnp.float32) ** 2)
                    + jnp.sum(c_new.astype(jnp.float32) ** 2))
        return f

    def fused(*a):
        return fused_lstm_cell(*a, interpret=interpret)

    argnums = tuple(range(len(args)))
    k_fwd = jax.jit(fused).lower(*shapes).compile()
    k_vg = jax.jit(jax.value_and_grad(
        loss(fused), argnums=argnums)).lower(*shapes).compile()
    r_fwd = jax.jit(reference_lstm_cell).lower(*shapes).compile()
    r_vg = jax.jit(jax.value_and_grad(
        loss(reference_lstm_cell), argnums=argnums)).lower(*shapes).compile()

    tol = 2e-2 if jnp.dtype(dtype) == jnp.bfloat16 else 1e-5

    def check(name, a, b_, scale=1.0):
        err = np.max(np.abs(np.asarray(a, np.float32)
                            - np.asarray(b_, np.float32)))
        if not np.isfinite(err) or err > tol * scale:
            raise RuntimeError(
                f"fused LSTM cell value check failed ({name}): "
                f"max err {err:.3e} > {tol * scale}")

    for name, a, b_ in zip(("h", "c"), k_fwd(*args), r_fwd(*args)):
        check(name, a, b_)
    _, gk = k_vg(*args)
    _, gr = r_vg(*args)
    for idx, (a, b_) in enumerate(zip(gk, gr)):
        check(f"grad[{idx}]", a, b_, scale=8.0)


def cell_for(layer, dtype, batch: Optional[int] = None
             ) -> Optional["functools.partial"]:
    """The fused cell bound for ``layer`` (an LSTM/GravesLSTM instance)
    or None → reference step. Routes through the kernel registry:
    probe-once per (class, n_in, n_out, dtype, padded-batch), mode
    switch ``DL4J_TPU_FUSED_LSTM``, auto mode requires the TPU backend.
    Only tanh/sigmoid cells qualify — anything else is reference-path
    by construction."""
    if getattr(layer, "activation", None) != "tanh" or \
            getattr(layer, "gate_activation", None) != "sigmoid":
        return None
    n_in, n = layer.n_in, layer.n_out
    if not n_in or not n:
        return None
    # mro walk instead of isinstance: importing recurrent.py here would
    # be a cycle (recurrent routes its _step through this module)
    peephole = any(b.__name__ == "GravesLSTM" for b in type(layer).__mro__)
    from deeplearning4j_tpu.nn.ops.registry import default_kernel_registry

    dtype = jnp.dtype(dtype)
    # key on the PADDED batch (sublane granularity): the probe must fail
    # where the real batch's VMEM working set would, not at a toy size
    B_p = _round_up(max(int(batch or 1), 1), _SUBLANE)
    key = (type(layer).__name__, int(n_in), int(n), dtype.name, B_p)
    interpret = default_kernel_registry().resolve(
        "fused_lstm", key,
        lambda interp: functools.partial(
            _probe_cell, int(n_in), int(n), dtype, peephole, interp,
            B=B_p))
    if interpret is None:
        return None
    return functools.partial(fused_lstm_cell, interpret=interpret)
