"""Kernel registry: the single availability decision point for every
in-tree Pallas kernel (flash attention, fused conv, fused LSTM cell,
fused ZeRO-1 update, int8 serving matmul).

Before this module each kernel carried its own ad-hoc probe cache
(``attention._FLASH_PROBE_CACHE``, ``fused_conv._PROBE_CACHE``) and its
own ``probe_with_retry`` call site. The registry unifies the contract:

- **probe once per process per (kernel, instantiation key)** — Mosaic
  lowering varies with shapes/dtypes, so availability is keyed, not
  global; a resolved key is a dict hit forever after;
- every resolution is **observable**: a failed (or skipped) probe emits
  ONE ``kernel_fallback`` flight event naming the kernel, key and
  reason, and a ``kernel_enabled{name=}`` gauge on the default metrics
  registry tracks whether any instantiation of that kernel is live —
  "why is this hot path on the slow route" is answerable from the
  black box and the scrape surface, not just process logs;
- one **mode switch per kernel** via environment:
  ``DL4J_TPU_<KERNEL>`` = ``0`` (off), ``1``/unset (auto: probe on the
  TPU backend, fall back elsewhere), or ``interpret`` (force the Pallas
  interpreter — the CPU testing/bench mode; slow, but executes the real
  kernel math). ``interpret`` is honored by the kernels that resolve
  through :meth:`KernelRegistry.resolve` (fused_lstm, fused_zero1,
  int8_matmul); flash_attention and fused_conv predate it and support
  ``0``/``1`` only (their layers call the compiled kernels directly —
  tests drive their ``interpret=`` arguments explicitly).

The probes themselves stay in the kernel modules (each knows its own
reference oracle and tolerance); the registry owns caching, retry
(``kernel_compat.probe_with_retry`` — transient axon remote-compile
crashes get one retry, deterministic rejects cost one attempt) and
reporting.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, Optional, Tuple

from deeplearning4j_tpu.nn.ops.kernel_compat import probe_with_retry

log = logging.getLogger(__name__)

#: kernel name → environment kill/mode switch
ENV_FLAGS = {
    "flash_attention": "DL4J_TPU_FLASH_ATTENTION",
    "fused_conv": "DL4J_TPU_FUSED_CONV",
    "fused_lstm": "DL4J_TPU_FUSED_LSTM",
    "fused_zero1": "DL4J_TPU_FUSED_ZERO1",
    "int8_matmul": "DL4J_TPU_INT8_MATMUL",
}


class KernelRegistry:
    """Probe-once-per-process kernel availability cache + reporter."""

    def __init__(self):
        self._lock = threading.RLock()
        #: (name, key) -> (ok: bool, reason: str)
        self._resolved: Dict[Tuple[str, tuple], Tuple[bool, str]] = {}
        #: (name, key) -> Event while a probe for that key is running —
        #: probes compile for SECONDS and must not hold the registry
        #: lock (concurrent engine warmups resolving other kernels would
        #: re-serialize); same-key racers wait on the event instead of
        #: probing twice
        self._inflight: Dict[Tuple[str, tuple], threading.Event] = {}

    # -- mode ----------------------------------------------------------------
    def mode(self, name: str) -> str:
        """'off' | 'auto' | 'interpret' for ``name`` (see module doc)."""
        raw = os.environ.get(ENV_FLAGS.get(name, ""), "1").strip().lower()
        if raw in ("0", "off", "false"):
            return "off"
        if raw == "interpret":
            return "interpret"
        return "auto"

    # -- resolution ----------------------------------------------------------
    def enabled(self, name: str, key: tuple) -> Optional[bool]:
        """Cached verdict for (name, key); None when never probed."""
        with self._lock:
            got = self._resolved.get((name, tuple(key)))
        return None if got is None else got[0]

    def route(self, name: str, key: tuple) -> Optional[bool]:
        """The mode/backend gate that runs before any probe: None when
        the kernel must not be used (kill switch, or auto mode off the
        TPU backend — recorded as a fallback), else the ``interpret``
        flag to build the probe/impl with."""
        import jax

        mode = self.mode(name)
        if mode == "off":
            self.disable(name, key,
                         f"disabled via {ENV_FLAGS.get(name)}=0")
            return None
        if mode == "interpret":
            return True
        try:
            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 — backend query failed: treat as non-TPU
            backend = "unknown"
        if backend != "tpu":
            self.disable(name, key,
                         f"non-TPU backend ({backend}); reference path "
                         "serves this instantiation")
            return None
        return False

    def resolve(self, name: str, key: tuple,
                probe_factory: Callable[[bool], Callable[[], None]]
                ) -> Optional[bool]:
        """The whole resolution protocol in one place: cached verdict →
        mode/backend gate → probe. Returns the ``interpret`` flag when
        the kernel may be used, None for the reference path.
        ``probe_factory(interpret)`` builds the zero-arg probe."""
        key = tuple(key)
        cached = self.enabled(name, key)
        if cached is False:
            return None
        interpret = self.route(name, key)
        if interpret is None:
            return None
        if cached is None and not self.probe(name, key,
                                             probe_factory(interpret)):
            return None
        return interpret

    def probe(self, name: str, key: tuple, probe_fn: Callable[[], None]
              ) -> bool:
        """Resolve (name, key): run ``probe_fn`` (raises on failure)
        through the shared transient-crash retry, cache the verdict, and
        report it (flight event on fallback, gauge either way). The
        probe itself runs OUTSIDE the registry lock; concurrent callers
        of the same key wait for the one in-flight probe. Safe to call
        from inside an ambient trace as long as ``probe_fn`` uses AOT
        lower+compile (the discipline every in-tree probe follows)."""
        key = tuple(key)
        while True:
            with self._lock:
                got = self._resolved.get((name, key))
                if got is not None:
                    return got[0]
                ev = self._inflight.get((name, key))
                if ev is None:
                    ev = threading.Event()
                    self._inflight[(name, key)] = ev
                    break
            ev.wait()  # another thread is probing this exact key

        failure = {}

        def on_fail(e, will_retry):
            failure["error"] = f"{type(e).__name__}: " \
                f"{str(e).splitlines()[0] if str(e) else ''}"
            log.info(
                "kernel %s unavailable for %s (%s)%s", name, key,
                failure["error"],
                " — transient remote-compile crash, retrying once"
                if will_retry else "")

        def probing():
            # chaos seam: mode 'transient_compile' carries the tunnel-
            # crash signature, so the drill exercises the REAL
            # probe_with_retry transient-retry path (one crash, then
            # the genuine probe runs)
            from deeplearning4j_tpu.chaos import hooks as _chaos

            _chaos.fire("kernel.probe", kernel=name)
            probe_fn()

        ok = False
        try:
            ok = probe_with_retry(probing, on_fail)
        finally:
            with self._lock:
                self._record(name, key, ok,
                             "probe ok" if ok
                             else failure.get("error", "probe failed"))
                self._inflight.pop((name, key), None)
            ev.set()
        return ok

    def disable(self, name: str, key: tuple, reason: str) -> None:
        """Cache (name, key) as unavailable WITHOUT probing — the
        backend/mode/shape gate said no before a compile was attempted
        (e.g. non-TPU backend in auto mode). Reported exactly like a
        probe failure so the fallback is visible."""
        key = tuple(key)
        with self._lock:
            if (name, key) in self._resolved:
                return
            self._record(name, key, False, reason)

    def _record(self, name: str, key: tuple, ok: bool, reason: str) -> None:
        # caller holds the lock
        self._resolved[(name, key)] = (ok, reason)
        try:
            from deeplearning4j_tpu.obs import flight as _flight
            from deeplearning4j_tpu.obs.metrics import default_registry

            if not ok:
                _flight.record("kernel_fallback", kernel=name,
                               key=repr(key), reason=reason)
            any_on = any(v for (n, _), (v, _r) in self._resolved.items()
                         if n == name)
            default_registry().gauge(
                "kernel_enabled",
                "1 when any instantiation of the named Pallas kernel "
                "probed OK this process, 0 when every resolution fell "
                "back to the reference path",
                labels={"name": name}).set(1.0 if any_on else 0.0)
        except Exception:  # reporting must never break the compute path
            log.debug("kernel registry reporting failed", exc_info=True)

    # -- introspection / tests ----------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, dict]]:
        """{kernel: {key-repr: {enabled, reason}}} — debugging surface."""
        with self._lock:
            out: Dict[str, Dict[str, dict]] = {}
            for (name, key), (ok, reason) in self._resolved.items():
                out.setdefault(name, {})[repr(key)] = {
                    "enabled": ok, "reason": reason}
            return out

    def reset(self, name: Optional[str] = None) -> None:
        """Drop cached verdicts (all, or one kernel's) — test hook for
        exercising probe/fallback paths repeatedly in one process."""
        with self._lock:
            if name is None:
                self._resolved.clear()
            else:
                for k in [k for k in self._resolved if k[0] == name]:
                    del self._resolved[k]


_default: Optional[KernelRegistry] = None
_default_lock = threading.Lock()


def default_kernel_registry() -> KernelRegistry:
    global _default
    with _default_lock:
        if _default is None:
            _default = KernelRegistry()
        return _default


def kernel_route(name: str, key: tuple) -> Optional[bool]:
    """:meth:`KernelRegistry.route` on the default registry."""
    return default_kernel_registry().route(name, key)
