"""Shared toolchain-compatibility bits for the in-tree Pallas kernels
(flash attention, fused conv) — the single home for two hard-won axon
findings (PROBE_BISECT.md):

1. ``PRECISION``: every in-Mosaic-kernel dot must pin
   ``precision=DEFAULT``. The package sets
   ``jax_default_matmul_precision="highest"`` (fp32-means-fp32 for the
   XLA paths); inside a Mosaic kernel that flag makes a bf16 matmul
   request a multi-pass algorithm the axon tunnel's server-side
   compiler CRASHES on ("tpu_compile_helper subprocess exit code 1").
   DEFAULT loses nothing there: operands are explicitly bf16 (one MXU
   pass is exact for them) and accumulation stays f32 via
   ``preferred_element_type``.

2. ``probe_with_retry``: the tunnel's remote-compile helper can also
   crash TRANSIENTLY (observed while it was recovering from a
   concurrent OOM'd compile, BENCH r4), and a one-shot compile-probe
   would then pin the slow fallback for the whole process. Genuine
   toolchain rejects are deterministic, so only failures matching the
   tunnel-crash signature are retried — a plain lowering error (or any
   failure on a non-TPU backend) still costs exactly one attempt.
"""

from __future__ import annotations

import time

import jax

#: precision for every dot inside a Mosaic kernel (see module docstring)
PRECISION = jax.lax.Precision.DEFAULT

#: substrings identifying the axon remote-compile service falling over,
#: as opposed to a deterministic Mosaic lowering reject
_TRANSIENT_MARKERS = ("remote_compile", "tpu_compile_helper", "HTTP 500")


def is_transient_compile_error(e: Exception) -> bool:
    msg = str(e)
    return any(m in msg for m in _TRANSIENT_MARKERS)


def probe_with_retry(probe, on_fail, retry_delay_s: float = 2.0):
    """Run ``probe()``; retry once (after ``retry_delay_s``) iff the
    failure looks like a transient remote-compile crash. ``on_fail``
    receives ``(exception, will_retry)`` for logging. Returns True when
    a probe attempt succeeded."""
    for attempt in range(2):
        try:
            probe()
            return True
        except Exception as e:  # noqa: BLE001 — ANY probe failure selects the fallback, reported via on_fail
            will_retry = attempt == 0 and is_transient_compile_error(e)
            on_fail(e, will_retry)
            if not will_retry:
                return False
            time.sleep(retry_delay_s)
    return False
