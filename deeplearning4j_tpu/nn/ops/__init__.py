"""Hand-written Pallas TPU kernels — the SURVEY §7 "Pallas for the hot
ops" path (the reference's analog is the cuDNN helper layer, §2.4,
absorbed elsewhere by XLA lowering; these kernels exist where XLA's
op-boundary materialization costs real HBM traffic).

The kernel SUBSYSTEM (this package):

- ``flash_attention`` / ``fused_conv`` — the attention/conv fast paths;
- ``fused_lstm`` — the LSTM cell (training scan + engine decode);
- ``fused_update`` — the single-pass ZeRO-1 Adam update;
- ``int8_matmul`` — int8 weight-quantized serving matmul;
- ``registry`` — the shared probe-once/fallback/observability contract
  every kernel resolves through (``KernelRegistry``).
"""

from deeplearning4j_tpu.nn.ops.flash_attention import flash_attention
from deeplearning4j_tpu.nn.ops.registry import (
    KernelRegistry,
    default_kernel_registry,
)

__all__ = ["flash_attention", "KernelRegistry", "default_kernel_registry"]
