"""Hand-written Pallas TPU kernels — the SURVEY §7 "Pallas for the hot
ops" path (the reference's analog is the cuDNN helper layer, §2.4,
absorbed elsewhere by XLA lowering; these kernels exist where XLA's
op-boundary materialization costs real HBM traffic)."""

from deeplearning4j_tpu.nn.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
