"""Flash attention as hand-written Pallas TPU kernels (fwd + bwd).

Why not the jax-bundled kernel: the axon tunnel's server-side Mosaic
(runtime libtpu) lags the JAX client (r3 it rejected an accumulating
bf16 ``tpu.matmul`` with "Bad lhs type"; since fixed upstream), and the
bundled kernel inherits the caller's matmul-precision default — under
this package's ``jax_default_matmul_precision="highest"`` a bf16 Mosaic
matmul crashes the remote compiler outright (PROBE_BISECT.md). This
kernel restricts itself to plain 2-D ``dot_general`` per grid cell with
``precision=DEFAULT`` pinned on every dot. Design (deliberately simpler
than the bundled op; r5 adds segment-id support — packed sequences run
on the flash path; attention *bias* still routes to dense XLA):

- grid ``(b·h, T/B)``; K and V rows for the (batch, head) live whole in
  VMEM (their BlockSpec index map is constant in the q-block dimension,
  so Mosaic DMAs them once per b·h), bounding T at ~4k for bf16 —
  longer sequences belong to ring attention (sequence parallelism)
  across devices anyway.
- online softmax (flash style): running row-max ``m`` and row-sum ``l``
  carried through a ``fori_loop`` over KV blocks in fp32; the causal
  variant loops only to the diagonal block and masks inside it.
- per-row stats are kept lane-broadcast ``(B, 128)`` — the TPU-native
  layout for per-sublane scalars under the (8/16, 128) tile constraint.
- segment ids (packed sequences) enter twice, in the layout each side
  of the score matrix wants: lane-broadcast ``(b·h, T, 128)`` for query
  rows (sublane axis) and natural ``(b·h, 1, T)`` for key columns (lane
  axis); the in-kernel mask is one int compare + where, fused into the
  score tile.
- backward = two kernels (dq over q-blocks; dkv over kv-blocks), each
  recomputing P from the saved log-sum-exp ``L`` (FlashAttention-2
  style; ``D = rowsum(dO·O)`` is a cheap fused XLA reduction outside).

Head dims are zero-padded to a lane multiple (128): padded q/k lanes
add zero to every score and padded v lanes produce zeros that are
sliced off, so the math is unchanged.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANE = 128
_TRANS_B = (((1,), (1,)), ((), ()))   # x (m,k) · y (n,k) -> (m,n)
_TRANS_A = (((0,), (0,)), ((), ()))   # x (k,m) · y (k,n) -> (m,n)
_NEG_INF = -1e30
from deeplearning4j_tpu.nn.ops.kernel_compat import PRECISION as _PREC


def _pick_block(T: int) -> int:
    for b in (512, 256, 128):
        if T % b == 0:
            return b
    raise ValueError(f"T={T} must be a multiple of 128")


def _pad_head(x):
    hd = x.shape[-1]
    pad = (-hd) % _LANE
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, pad)))
    return x, hd


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------
def _seg_where(qseg, kseg, s, B):
    """Mask scores where q and k segments differ. qseg (B,1) int32 (lane
    0 of the lane-broadcast layout); kseg (B,) int32 (natural lane
    layout); broadcast compare → (B,B)."""
    return jnp.where(qseg == kseg.reshape(1, B), s, _NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale: float,
                causal: bool, block: int, T: int, has_seg: bool):
    if has_seg:
        segq_ref, segk_ref, o_ref, lse_ref = rest
    else:
        (o_ref, lse_ref), segq_ref, segk_ref = rest, None, None
    i = pl.program_id(1)
    q = q_ref[0]                                        # (B, hd)
    B = block
    n_kv = jax.lax.select(causal, i + 1, T // B)

    def body(j, carry):
        o, m, l = carry                                 # (B,hd) f32, (B,1) f32
        k = k_ref[0, pl.dslice(j * B, B), :]            # (B, hd)
        v = v_ref[0, pl.dslice(j * B, B), :]
        s = jax.lax.dot_general(q, k, _TRANS_B,
                                preferred_element_type=jnp.float32, precision=_PREC) * scale
        if causal:
            rows = i * B + jax.lax.broadcasted_iota(jnp.int32, (B, B), 0)
            cols = j * B + jax.lax.broadcasted_iota(jnp.int32, (B, B), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if has_seg:
            s = _seg_where(segq_ref[0][:, 0:1],
                           segk_ref[0, 0, pl.dslice(j * B, B)], s, B)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                          # (B, B) f32
        alpha = jnp.exp(m - m_new)                      # (B, 1)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32, precision=_PREC)
        o = o * alpha + pv
        return o, m_new, l

    o0 = jnp.zeros((B, q.shape[-1]), jnp.float32)
    m0 = jnp.full((B, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_kv, body, (o0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l_safe).astype(o_ref.dtype)
    lse = m + jnp.log(l_safe)                           # (B, 1)
    lse_ref[0] = jnp.broadcast_to(lse, (B, _LANE))


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref, *rest,
               scale: float, causal: bool, block: int, T: int,
               has_seg: bool):
    if has_seg:
        segq_ref, segk_ref, dq_ref = rest
    else:
        (dq_ref,), segq_ref, segk_ref = rest, None, None
    i = pl.program_id(1)
    B = block
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, 0:1]                            # (B, 1)
    dcap = dcap_ref[0][:, 0:1]
    n_kv = jax.lax.select(causal, i + 1, T // B)

    def body(j, dq):
        k = k_ref[0, pl.dslice(j * B, B), :]
        v = v_ref[0, pl.dslice(j * B, B), :]
        s = jax.lax.dot_general(q, k, _TRANS_B,
                                preferred_element_type=jnp.float32, precision=_PREC) * scale
        if causal:
            rows = i * B + jax.lax.broadcasted_iota(jnp.int32, (B, B), 0)
            cols = j * B + jax.lax.broadcasted_iota(jnp.int32, (B, B), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if has_seg:
            s = _seg_where(segq_ref[0][:, 0:1],
                           segk_ref[0, 0, pl.dslice(j * B, B)], s, B)
        p = jnp.exp(s - lse)                            # (B, B)
        dp = jax.lax.dot_general(do, v, _TRANS_B,
                                 preferred_element_type=jnp.float32, precision=_PREC)
        ds = p * (dp - dcap) * scale
        dq = dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_PREC)
        return dq

    dq0 = jnp.zeros((B, q.shape[-1]), jnp.float32)
    dq = jax.lax.fori_loop(0, n_kv, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dcap_ref, *rest,
                scale: float, causal: bool, block: int, T: int,
                has_seg: bool):
    if has_seg:
        segq_ref, segk_ref, dk_ref, dv_ref = rest
    else:
        (dk_ref, dv_ref), segq_ref, segk_ref = rest, None, None
    j = pl.program_id(1)
    B = block
    k = k_ref[0]                                        # (B, hd) this kv block
    v = v_ref[0]
    n_q = T // B
    start = jax.lax.select(causal, j, 0)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(i * B, B), :]
        do = do_ref[0, pl.dslice(i * B, B), :]
        lse = lse_ref[0, pl.dslice(i * B, B), :][:, 0:1]
        dcap = dcap_ref[0, pl.dslice(i * B, B), :][:, 0:1]
        s = jax.lax.dot_general(q, k, _TRANS_B,
                                preferred_element_type=jnp.float32, precision=_PREC) * scale
        if causal:
            rows = i * B + jax.lax.broadcasted_iota(jnp.int32, (B, B), 0)
            cols = j * B + jax.lax.broadcasted_iota(jnp.int32, (B, B), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if has_seg:
            s = _seg_where(
                segq_ref[0, pl.dslice(i * B, B), :][:, 0:1],
                segk_ref[0, 0], s, B)  # segk blocked on j: (B,)
        p = jnp.exp(s - lse)                            # (B_q, B_k)
        dv = dv + jax.lax.dot_general(p.astype(do.dtype), do, _TRANS_A,
                                      preferred_element_type=jnp.float32, precision=_PREC)
        dp = jax.lax.dot_general(do, v, _TRANS_B,
                                 preferred_element_type=jnp.float32, precision=_PREC)
        ds = p * (dp - dcap) * scale
        dk = dk + jax.lax.dot_general(ds.astype(q.dtype), q, _TRANS_A,
                                      preferred_element_type=jnp.float32, precision=_PREC)
        return dk, dv

    z = jnp.zeros((B, k.shape[-1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, n_q, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------------------
# wrapper with custom VJP
# --------------------------------------------------------------------------
def _seg_layouts(seg):
    """(b, T) int32 → (lane-broadcast q layout (b,T,LANE), natural k
    layout (b,1,T)). Kept at BATCH granularity — the grid's b·h axis
    index-maps back with ``// h`` so the head dimension is never
    materialized (heads share their row's segment ids)."""
    b, T = seg.shape
    seg = seg.astype(jnp.int32)
    return (jnp.broadcast_to(seg[:, :, None], (b, T, _LANE)),
            seg[:, None, :])


def _fwd_impl(q, k, v, seg, causal: bool, scale: float, interpret: bool):
    bh, T, hd = q.shape
    B = _pick_block(T)
    has_seg = seg is not None
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block=B, T=T, has_seg=has_seg)
    row_spec = lambda b, i: (b, i, 0)
    full_spec = lambda b, i: (b, 0, 0)
    in_specs = [
        pl.BlockSpec((1, B, hd), row_spec),
        pl.BlockSpec((1, T, hd), full_spec),
        pl.BlockSpec((1, T, hd), full_spec),
    ]
    args = [q, k, v]
    if has_seg:
        segq, segk = _seg_layouts(seg)
        h = bh // seg.shape[0]  # heads share segments: index-map // h
        in_specs += [pl.BlockSpec((1, B, _LANE),
                                  lambda b, i: (b // h, i, 0)),
                     pl.BlockSpec((1, 1, T),
                                  lambda b, i: (b // h, 0, 0))]
        args += [segq, segk]
    o, lse = pl.pallas_call(
        kern,
        grid=(bh, T // B),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, B, hd), row_spec),
            pl.BlockSpec((1, B, _LANE), row_spec),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, T, hd), q.dtype),
            jax.ShapeDtypeStruct((bh, T, _LANE), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return o, lse


def _bwd_impl(q, k, v, seg, o, lse, do, causal: bool, scale: float,
              interpret: bool):
    bh, T, hd = q.shape
    B = _pick_block(T)
    has_seg = seg is not None
    # D_i = rowsum(dO·O): cheap fused XLA reduction, lane-broadcast layout
    dcap = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), -1,
                keepdims=True), (bh, T, _LANE))
    row_spec = lambda b, i: (b, i, 0)
    full_spec = lambda b, i: (b, 0, 0)
    dq_in_specs = [
        pl.BlockSpec((1, B, hd), row_spec),      # q block
        pl.BlockSpec((1, T, hd), full_spec),     # k full
        pl.BlockSpec((1, T, hd), full_spec),     # v full
        pl.BlockSpec((1, B, hd), row_spec),      # do block
        pl.BlockSpec((1, B, _LANE), row_spec),   # lse block
        pl.BlockSpec((1, B, _LANE), row_spec),   # D block
    ]
    dkv_in_specs = [
        pl.BlockSpec((1, T, hd), full_spec),     # q full
        pl.BlockSpec((1, B, hd), row_spec),      # k block
        pl.BlockSpec((1, B, hd), row_spec),      # v block
        pl.BlockSpec((1, T, hd), full_spec),     # do full
        pl.BlockSpec((1, T, _LANE), full_spec),  # lse full
        pl.BlockSpec((1, T, _LANE), full_spec),  # D full
    ]
    dq_args = [q, k, v, do, lse, dcap]
    dkv_args = [q, k, v, do, lse, dcap]
    if has_seg:
        segq, segk = _seg_layouts(seg)
        h = bh // seg.shape[0]  # heads share segments: index-map // h
        dq_in_specs += [
            pl.BlockSpec((1, B, _LANE), lambda b, i: (b // h, i, 0)),
            pl.BlockSpec((1, 1, T), lambda b, i: (b // h, 0, 0))]
        dkv_in_specs += [
            pl.BlockSpec((1, T, _LANE), lambda b, j: (b // h, 0, 0)),
            pl.BlockSpec((1, 1, B), lambda b, j: (b // h, 0, j))]
        dq_args += [segq, segk]
        dkv_args += [segq, segk]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, block=B,
                          T=T, has_seg=has_seg),
        grid=(bh, T // B),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, B, hd), row_spec),
        out_shape=jax.ShapeDtypeStruct((bh, T, hd), q.dtype),
        interpret=interpret,
    )(*dq_args)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, block=B,
                          T=T, has_seg=has_seg),
        grid=(bh, T // B),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, B, hd), row_spec),
            pl.BlockSpec((1, B, hd), row_spec),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, T, hd), k.dtype),
            jax.ShapeDtypeStruct((bh, T, hd), v.dtype),
        ],
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal: bool, scale: float, interpret: bool):
    o, _ = _fwd_impl(q, k, v, None, causal, scale, interpret)
    return o


def _flash_fwd(q, k, v, causal, scale, interpret):
    o, lse = _fwd_impl(q, k, v, None, causal, scale, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, interpret, res, do):
    q, k, v, o, lse = res
    return _bwd_impl(q, k, v, None, o, lse, do, causal, scale, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_seg(q, k, v, seg, causal: bool, scale: float, interpret: bool):
    o, _ = _fwd_impl(q, k, v, seg, causal, scale, interpret)
    return o


def _flash_seg_fwd(q, k, v, seg, causal, scale, interpret):
    o, lse = _fwd_impl(q, k, v, seg, causal, scale, interpret)
    return o, (q, k, v, seg, o, lse)


def _flash_seg_bwd(causal, scale, interpret, res, do):
    import numpy as _np

    q, k, v, seg, o, lse = res
    dq, dk, dv = _bwd_impl(q, k, v, seg, o, lse, do, causal, scale,
                           interpret)
    # integer input → float0 cotangent (jax's symbolic zero for ints)
    dseg = _np.zeros(seg.shape, jax.dtypes.float0)
    return dq, dk, dv, dseg


_flash_seg.defvjp(_flash_seg_fwd, _flash_seg_bwd)

# VMEM budget: K+V rows resident per (b·h) — bf16 at hd=128 costs
# 2·T·128·2B; cap T so kernel working set stays well under ~16 MB
MAX_SEQ_LEN = 4096


def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: float | None = None,
                    segment_ids=None,
                    interpret: bool = False):
    """O(T)-memory attention. q, k, v: (b, h, T, head_dim) with equal
    q/kv lengths, T a multiple of 128 and ≤ MAX_SEQ_LEN. Differentiable
    (custom VJP, FlashAttention-2-style backward).

    ``segment_ids``: optional (b, T) int array for packed sequences —
    a token attends only to keys with the SAME segment id (composes
    with ``causal``). ``interpret=True`` runs the Pallas interpreter
    (CPU testing)."""
    b, h, T, hd = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(
            f"q/k/v shapes must match exactly (got q={q.shape}, "
            f"k={k.shape}, v={v.shape}); cross-attention / differing kv "
            "lengths are not supported by this kernel — use dense_attention")
    if T % _LANE or T > MAX_SEQ_LEN:
        raise ValueError(
            f"T={T} must be a multiple of {_LANE} and <= {MAX_SEQ_LEN} "
            "(longer sequences: use ring attention / dense)")
    scale = float(sm_scale) if sm_scale is not None else hd ** -0.5
    qp, _ = _pad_head(q)
    kp, _ = _pad_head(k)
    vp, _ = _pad_head(v)
    hp = qp.shape[-1]
    q3 = qp.reshape(b * h, T, hp)
    k3 = kp.reshape(b * h, T, hp)
    v3 = vp.reshape(b * h, T, hp)
    if segment_ids is not None:
        if segment_ids.shape != (b, T):
            raise ValueError(
                f"segment_ids must be (b, T)=({b}, {T}), got "
                f"{segment_ids.shape}")
        seg = jnp.asarray(segment_ids, jnp.int32)  # (b, T); heads share
        out = _flash_seg(q3, k3, v3, seg, causal, scale, interpret)
    else:
        out = _flash(q3, k3, v3, causal, scale, interpret)
    return out.reshape(b, h, T, hp)[..., :hd]
