"""Numerical gradient checking — the test-suite backbone.

Reference: ``gradientcheck/GradientCheckUtil.java:109`` (MultiLayerNetwork),
``:331`` (ComputationGraph) — perturb every parameter ±ε in fp64, compare
relative error against the analytic gradient. The reference checks in
double precision; jax's CPU backend runs fp32 by default, so the checker
promotes the whole computation to float64 via ``jax.enable_x64``
(SURVEY.md §7 hard-part 2: fp64-on-CPU reference for the checker). Tests
call this on tiny nets where the O(P) forward passes are cheap.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet

DEFAULT_EPS = 1e-6
DEFAULT_MAX_REL_ERROR = 1e-3
DEFAULT_MIN_ABS_ERROR = 1e-8


def enable_x64(enabled=True):
    """``jax.enable_x64`` across jax versions: the top-level alias landed
    after 0.4.x, where only ``jax.experimental.enable_x64`` exists."""
    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx
    return ctx(enabled)


def _to64(tree):
    return jax.tree_util.tree_map(
        lambda a: jnp.asarray(np.asarray(a), jnp.float64), tree
    )


def _opt64(a):
    return None if a is None else jnp.asarray(np.asarray(a), jnp.float64)


def _central_difference_check(
    loss_fn,
    params64,
    analytic,
    keys,
    eps: float,
    max_rel_error: float,
    min_abs_error: float,
    print_results: bool,
    copy_with,
) -> bool:
    """Shared ±ε loop. ``keys`` iterates container keys (int layer index or
    vertex name); ``copy_with(params, key, name, arr)`` returns a fresh
    params pytree with one array replaced."""
    loss_fn_j = jax.jit(loss_fn)
    total, failed = 0, 0
    max_err_seen = 0.0
    for key in keys:
        for name, arr in params64[key].items():
            flat = np.array(arr, np.float64).reshape(-1)  # writable copy
            g_flat = np.asarray(analytic[key][name], np.float64).reshape(-1)
            for j in range(flat.size):
                orig = flat[j]
                flat[j] = orig + eps
                s_plus = float(loss_fn_j(copy_with(params64, key, name, flat.reshape(arr.shape))))
                flat[j] = orig - eps
                s_minus = float(loss_fn_j(copy_with(params64, key, name, flat.reshape(arr.shape))))
                flat[j] = orig
                numeric = (s_plus - s_minus) / (2 * eps)
                analytic_g = g_flat[j]
                denom = abs(numeric) + abs(analytic_g)
                rel = abs(numeric - analytic_g) / denom if denom > 0 else 0.0
                total += 1
                if rel > max_rel_error and abs(numeric - analytic_g) > min_abs_error:
                    failed += 1
                    if print_results:
                        print(
                            f"FAIL {key} param {name}[{j}]: "
                            f"analytic={analytic_g:.8g} numeric={numeric:.8g} rel={rel:.4g}"
                        )
                max_err_seen = max(max_err_seen, rel if denom > 0 else 0.0)
    if print_results:
        print(f"Gradient check: {total - failed}/{total} passed; max rel err {max_err_seen:.3g}")
    return failed == 0


def _list_copy_with(params, i, name, new_arr):
    out = [dict(p) for p in params]
    out[i][name] = jnp.asarray(new_arr, jnp.float64)
    return out


def _dict_copy_with(params, key, name, new_arr):
    out = {k: dict(v) for k, v in params.items()}
    out[key][name] = jnp.asarray(new_arr, jnp.float64)
    return out


def check_gradients(
    net,
    ds: DataSet,
    eps: float = DEFAULT_EPS,
    max_rel_error: float = DEFAULT_MAX_REL_ERROR,
    min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
    print_results: bool = False,
    rng_seed: int = 12345,
) -> bool:
    """Analytic vs numerical gradients for a MultiLayerNetwork.

    Deterministic rng is reused for every evaluation so dropout/noise layers
    see identical masks (the reference requires deterministic=true layers).
    Returns True if all parameters pass.
    """
    with enable_x64(True):
        params64 = _to64(net.params_)
        state64 = _to64(net.state_)
        f = _opt64(ds.features)
        l = _opt64(ds.labels)
        fm = _opt64(ds.features_mask)
        lm = _opt64(ds.labels_mask)
        rng = jax.random.PRNGKey(rng_seed)

        def loss_fn(p):
            loss, _ = net._loss_and_new_state(p, state64, f, l, fm, lm, rng, train=True)
            return loss + net._reg_score(p)

        analytic = jax.grad(loss_fn)(params64)
        return _central_difference_check(
            loss_fn, params64, analytic, range(len(params64)),
            eps, max_rel_error, min_abs_error, print_results, _list_copy_with,
        )


def check_gradients_graph(
    net,
    mds,
    eps: float = DEFAULT_EPS,
    max_rel_error: float = DEFAULT_MAX_REL_ERROR,
    min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
    print_results: bool = False,
    rng_seed: int = 12345,
) -> bool:
    """ComputationGraph analog (reference ``GradientCheckUtil.java:331``).

    ``mds`` is a MultiDataSet (or DataSet, adapted)."""
    from deeplearning4j_tpu.nn.graph import _as_multi

    mds = _as_multi(mds)
    with enable_x64(True):
        params64 = _to64(net.params_)
        state64 = _to64(net.state_)
        feats = tuple(_opt64(f) for f in mds.features)
        labels = tuple(_opt64(l) for l in mds.labels)
        fmasks = tuple(_opt64(m) for m in mds.features_masks)
        lmasks = tuple(_opt64(m) for m in mds.labels_masks)
        rng = jax.random.PRNGKey(rng_seed)

        def loss_fn(p):
            loss, _ = net._loss_and_new_state(
                p, state64, feats, labels, fmasks, lmasks, rng, train=True
            )
            return loss + net._reg_score(p)

        analytic = jax.grad(loss_fn)(params64)
        return _central_difference_check(
            loss_fn, params64, analytic, list(params64),
            eps, max_rel_error, min_abs_error, print_results, _dict_copy_with,
        )
