"""Numerical gradient checking — the test-suite backbone.

Reference: ``gradientcheck/GradientCheckUtil.java:109`` — perturb every
parameter ±ε in fp64, compare relative error against the analytic gradient.
The reference checks in double precision; jax's CPU backend runs fp32 by
default, so the checker promotes the whole computation to float64 via
``jax.enable_x64`` (SURVEY.md §7 hard-part 2: fp64-on-CPU reference for the
checker). Tests call this on tiny nets where the O(P) forward passes are
cheap.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet

DEFAULT_EPS = 1e-6
DEFAULT_MAX_REL_ERROR = 1e-3
DEFAULT_MIN_ABS_ERROR = 1e-8


def check_gradients(
    net,
    ds: DataSet,
    eps: float = DEFAULT_EPS,
    max_rel_error: float = DEFAULT_MAX_REL_ERROR,
    min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
    print_results: bool = False,
    rng_seed: int = 12345,
) -> bool:
    """Analytic vs numerical gradients for a MultiLayerNetwork.

    Deterministic rng is reused for every evaluation so dropout/noise layers
    see identical masks (the reference requires deterministic=true layers).
    Returns True if all parameters pass.
    """
    with jax.enable_x64(True):
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), net.params_
        )
        state64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), net.state_
        )
        f = jnp.asarray(np.asarray(ds.features), jnp.float64)
        l = None if ds.labels is None else jnp.asarray(np.asarray(ds.labels), jnp.float64)
        fm = None if ds.features_mask is None else jnp.asarray(np.asarray(ds.features_mask), jnp.float64)
        lm = None if ds.labels_mask is None else jnp.asarray(np.asarray(ds.labels_mask), jnp.float64)
        rng = jax.random.PRNGKey(rng_seed)

        def loss_fn(p):
            loss, _ = net._loss_and_new_state(p, state64, f, l, fm, lm, rng, train=True)
            return loss + net._reg_score(p)

        analytic = jax.grad(loss_fn)(params64)
        loss_fn_j = jax.jit(loss_fn)

        total, failed = 0, 0
        max_err_seen = 0.0
        for i, layer_params in enumerate(params64):
            for name, arr in layer_params.items():
                flat = np.array(arr, np.float64).reshape(-1)  # writable copy
                g_flat = np.asarray(analytic[i][name], np.float64).reshape(-1)
                for j in range(flat.size):
                    orig = flat[j]
                    flat[j] = orig + eps
                    p_plus = _with(params64, i, name, flat.reshape(arr.shape))
                    s_plus = float(loss_fn_j(p_plus))
                    flat[j] = orig - eps
                    p_minus = _with(params64, i, name, flat.reshape(arr.shape))
                    s_minus = float(loss_fn_j(p_minus))
                    flat[j] = orig
                    numeric = (s_plus - s_minus) / (2 * eps)
                    analytic_g = g_flat[j]
                    denom = abs(numeric) + abs(analytic_g)
                    rel = abs(numeric - analytic_g) / denom if denom > 0 else 0.0
                    total += 1
                    if rel > max_rel_error and abs(numeric - analytic_g) > min_abs_error:
                        failed += 1
                        if print_results:
                            print(
                                f"FAIL layer {i} param {name}[{j}]: "
                                f"analytic={analytic_g:.8g} numeric={numeric:.8g} rel={rel:.4g}"
                            )
                    max_err_seen = max(max_err_seen, rel if denom > 0 else 0.0)
        if print_results:
            print(f"Gradient check: {total - failed}/{total} passed; max rel err {max_err_seen:.3g}")
        return failed == 0


def _with(params, i, name, new_arr):
    out = [dict(p) for p in params]
    out[i][name] = jnp.asarray(new_arr, jnp.float64)
    return out
