"""Graph vertex catalog for ComputationGraph.

Reference: ``nn/conf/graph/*.java`` (14+3 config classes) +
``nn/graph/vertex/impl/*.java`` runtimes — Merge, ElementWise, Subset,
Stack/Unstack, L2/L2Normalize, Scale/Shift, Reshape, Preprocessor, and the
rnn vertices (LastTimeStep, DuplicateToTimeSeries, ReverseTimeSeries).

TPU-native design: as with layers, the config object IS the runtime — each
vertex is a pure function over its input activations, traced inside the
jitted train step. No params on any of these vertices (the reference's
GraphVertex.numParams()==0 for all of them).

Layout note: activations are NHWC / (b,t,size), so feature-axis merges are
always ``axis=-1`` regardless of family (the reference needs per-family
axis logic for NCHW / (b,size,t)).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import serde
from deeplearning4j_tpu.nn.conf.input_type import InputType

Array = jax.Array


class GraphVertex:
    """Base vertex config/runtime (reference ``nn/conf/graph/GraphVertex.java``)."""

    def get_output_type(self, *input_types: InputType) -> InputType:
        if len(input_types) != 1:
            raise ValueError(f"{type(self).__name__} expects 1 input")
        return input_types[0]

    def apply(self, inputs: List[Array], masks: List[Optional[Array]],
              *, train: bool = False, rng: Optional[Array] = None) -> Array:
        raise NotImplementedError

    def feed_forward_mask(self, masks: List[Optional[Array]]) -> Optional[Array]:
        """Output mask given input masks; default: first non-None."""
        for m in masks:
            if m is not None:
                return m
        return None

    # -- serde ---------------------------------------------------------------
    def to_dict(self) -> dict:
        return serde.generic_to_dict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "GraphVertex":
        actual = serde.lookup(data.get("@class", cls.__name__))
        return serde.generic_from_dict(actual, data)

    def __eq__(self, other):
        return type(self) is type(other) and serde.encode(self) == serde.encode(other)

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items() if v is not None}
        return f"{type(self).__name__}({fields})"


@serde.register
class MergeVertex(GraphVertex):
    """Concatenate along the feature axis (reference ``MergeVertex.java``).
    NHWC ⇒ channel concat and feature concat are both ``axis=-1``.

    ``require_rank`` (optional) asserts the input rank at apply time —
    used by the Keras importer when an explicit axis (e.g. Concatenate
    axis=3) is only last-axis-equivalent at a specific rank."""

    def __init__(self, require_rank=None, **kwargs):
        super().__init__(**kwargs)
        self.require_rank = require_rank

    def get_output_type(self, *input_types: InputType) -> InputType:
        if not input_types:
            raise ValueError("MergeVertex needs >=1 input")
        first = input_types[0]
        if first.kind == "convolutional":
            ch = sum(t.channels for t in input_types)
            for t in input_types:
                if (t.height, t.width) != (first.height, first.width):
                    raise ValueError("MergeVertex: mismatched spatial dims")
            return InputType.convolutional(first.height, first.width, ch)
        if first.kind == "recurrent":
            return InputType.recurrent(sum(t.size for t in input_types), first.timesteps)
        return InputType.feed_forward(sum(t.size for t in input_types))

    def apply(self, inputs, masks, *, train=False, rng=None):
        rr = getattr(self, "require_rank", None)
        if rr is not None and inputs and inputs[0].ndim != rr:
            raise ValueError(
                f"MergeVertex: expected rank-{rr} inputs (explicit concat "
                f"axis is only last-axis at that rank); got rank "
                f"{inputs[0].ndim}"
            )
        if len(inputs) == 1:
            return inputs[0]
        return jnp.concatenate(inputs, axis=-1)


@serde.register
class ElementWiseVertex(GraphVertex):
    """Pointwise op over N same-shaped inputs (reference
    ``ElementWiseVertex.java``; ops Add/Subtract/Product/Average/Max)."""

    OPS = ("add", "subtract", "product", "average", "max")

    def __init__(self, op: str = "add"):
        op = op.lower()
        if op not in self.OPS:
            raise ValueError(f"ElementWiseVertex op must be one of {self.OPS}")
        self.op = op

    def get_output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, inputs, masks, *, train=False, rng=None):
        if self.op == "subtract":
            if len(inputs) != 2:
                raise ValueError("subtract needs exactly 2 inputs")
            return inputs[0] - inputs[1]
        out = inputs[0]
        for x in inputs[1:]:
            if self.op in ("add", "average"):
                out = out + x
            elif self.op == "product":
                out = out * x
            else:
                out = jnp.maximum(out, x)
        if self.op == "average":
            out = out / len(inputs)
        return out


@serde.register
class SubsetVertex(GraphVertex):
    """Feature-range slice [from, to] inclusive (reference ``SubsetVertex.java``)."""

    def __init__(self, from_idx: int, to_idx: int):
        self.from_idx = int(from_idx)
        self.to_idx = int(to_idx)

    def get_output_type(self, *input_types: InputType) -> InputType:
        t = input_types[0]
        n = self.to_idx - self.from_idx + 1
        if t.kind == "recurrent":
            return InputType.recurrent(n, t.timesteps)
        if t.kind == "convolutional":
            return InputType.convolutional(t.height, t.width, n)
        return InputType.feed_forward(n)

    def apply(self, inputs, masks, *, train=False, rng=None):
        return inputs[0][..., self.from_idx : self.to_idx + 1]


@serde.register
class StackVertex(GraphVertex):
    """Concatenate along the batch axis (reference ``StackVertex.java``)."""

    def get_output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, inputs, masks, *, train=False, rng=None):
        return jnp.concatenate(inputs, axis=0)

    def feed_forward_mask(self, masks):
        if all(m is None for m in masks):
            return None
        if any(m is None for m in masks):
            raise ValueError("StackVertex: all-or-none masks required")
        return jnp.concatenate(masks, axis=0)


@serde.register
class UnstackVertex(GraphVertex):
    """Take slice ``from_idx`` of ``stack_size`` equal batch chunks
    (reference ``UnstackVertex.java``)."""

    def __init__(self, from_idx: int, stack_size: int):
        self.from_idx = int(from_idx)
        self.stack_size = int(stack_size)

    def get_output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, inputs, masks, *, train=False, rng=None):
        x = inputs[0]
        if x.shape[0] % self.stack_size != 0:
            raise ValueError(
                f"UnstackVertex: batch {x.shape[0]} not divisible by "
                f"stackSize {self.stack_size} (reference throws here too)"
            )
        step = x.shape[0] // self.stack_size
        return x[self.from_idx * step : (self.from_idx + 1) * step]

    def feed_forward_mask(self, masks):
        m = masks[0]
        if m is None:
            return None
        if m.shape[0] % self.stack_size != 0:
            raise ValueError(
                f"UnstackVertex: mask batch {m.shape[0]} not divisible by "
                f"stackSize {self.stack_size}"
            )
        step = m.shape[0] // self.stack_size
        return m[self.from_idx * step : (self.from_idx + 1) * step]


@serde.register
class L2NormalizeVertex(GraphVertex):
    """x / ||x||₂ over the non-batch axes (reference ``L2NormalizeVertex.java``)."""

    def __init__(self, eps: float = 1e-8):
        self.eps = float(eps)

    def apply(self, inputs, masks, *, train=False, rng=None):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True))
        return x / (norm + self.eps)


@serde.register
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs → (batch, 1)
    (reference ``L2Vertex.java``)."""

    def __init__(self, eps: float = 1e-8):
        self.eps = float(eps)

    def get_output_type(self, *input_types: InputType) -> InputType:
        return InputType.feed_forward(1)

    def apply(self, inputs, masks, *, train=False, rng=None):
        a, b = inputs
        d = (a - b).reshape(a.shape[0], -1)
        return jnp.sqrt(jnp.sum(jnp.square(d), axis=1, keepdims=True) + self.eps)


@serde.register
class ScaleVertex(GraphVertex):
    """x * scale (reference ``ScaleVertex.java``)."""

    def __init__(self, scale: float):
        self.scale = float(scale)

    def apply(self, inputs, masks, *, train=False, rng=None):
        return inputs[0] * self.scale


@serde.register
class ShiftVertex(GraphVertex):
    """x + shift (reference ``ShiftVertex.java``)."""

    def __init__(self, shift: float):
        self.shift = float(shift)

    def apply(self, inputs, masks, *, train=False, rng=None):
        return inputs[0] + self.shift


@serde.register
class PoolHelperVertex(GraphVertex):
    """Strip the first spatial row and column of a CNN activation
    (reference ``PoolHelperVertex.java:doForward`` — a legacy helper
    compensating Caffe-style ceil-mode pooling in imported GoogLeNet
    models; NCHW ``[:, :, 1:, 1:]`` there, NHWC here)."""

    def get_output_type(self, *input_types: InputType) -> InputType:
        (it,) = input_types
        if it.kind != "convolutional":
            raise ValueError("PoolHelperVertex expects convolutional input")
        return InputType.convolutional(it.height - 1, it.width - 1,
                                       it.channels)

    def apply(self, inputs, masks, *, train=False, rng=None):
        return inputs[0][:, 1:, 1:, :]


@serde.register
class ReshapeVertex(GraphVertex):
    """Reshape to ``new_shape`` (batch dim may be -1; reference
    ``ReshapeVertex.java``)."""

    def __init__(self, new_shape: Sequence[int], output_type: Optional[dict] = None):
        self.new_shape = [int(s) for s in new_shape]
        # explicit output InputType dict when shape inference can't derive it
        self.output_type = output_type

    def get_output_type(self, *input_types: InputType) -> InputType:
        if self.output_type is not None:
            return InputType.from_dict(self.output_type)
        shp = self.new_shape
        if len(shp) == 2:
            return InputType.feed_forward(shp[1])
        if len(shp) == 3:
            return InputType.recurrent(shp[2], shp[1])
        if len(shp) == 4:
            return InputType.convolutional(shp[1], shp[2], shp[3])
        raise ValueError(f"Cannot infer InputType from shape {shp}")

    def apply(self, inputs, masks, *, train=False, rng=None):
        return jnp.reshape(inputs[0], self.new_shape)


@serde.register
class PreprocessorVertex(GraphVertex):
    """Wrap an InputPreProcessor as a standalone vertex (reference
    ``PreprocessorVertex.java``)."""

    def __init__(self, preprocessor):
        self.preprocessor = preprocessor

    def get_output_type(self, *input_types: InputType) -> InputType:
        return self.preprocessor.get_output_type(input_types[0])

    def apply(self, inputs, masks, *, train=False, rng=None):
        return self.preprocessor.pre_process(inputs[0], masks[0])

    def feed_forward_mask(self, masks):
        return self.preprocessor.feed_forward_mask(masks[0])

    def to_dict(self) -> dict:
        return {"@class": "PreprocessorVertex", "preprocessor": serde.encode(self.preprocessor)}

    @classmethod
    def from_dict(cls, data: dict) -> "PreprocessorVertex":
        return cls(serde.decode(data["preprocessor"]))


@serde.register
class LastTimeStepVertex(GraphVertex):
    """(b, T, s) → (b, s): last *valid* step per example using the mask of
    the named network input (reference ``LastTimeStepVertex.java``)."""

    def __init__(self, mask_input: Optional[str] = None):
        self.mask_input = mask_input  # resolved by the graph runtime

    def get_output_type(self, *input_types: InputType) -> InputType:
        t = input_types[0]
        return InputType.feed_forward(t.size)

    def apply(self, inputs, masks, *, train=False, rng=None):
        x = inputs[0]
        m = masks[0]
        if m is None:
            return x[:, -1, :]
        lengths = jnp.sum(m.astype(jnp.int32), axis=1)
        idx = jnp.clip(lengths - 1, 0, x.shape[1] - 1)
        return jax.vmap(lambda row, i: row[i])(x, idx)

    def feed_forward_mask(self, masks):
        return None  # mask consumed


@serde.register
class DuplicateToTimeSeriesVertex(GraphVertex):
    """(b, s) → (b, T, s), T taken from a reference activation supplied as a
    second input by the runtime (reference ``DuplicateToTimeSeriesVertex.java``
    uses a named network input)."""

    def __init__(self, timesteps_input: str):
        self.timesteps_input = timesteps_input

    def get_output_type(self, *input_types: InputType) -> InputType:
        base = input_types[0]
        ts = input_types[1].timesteps if len(input_types) > 1 else None
        return InputType.recurrent(base.size, ts)

    def apply(self, inputs, masks, *, train=False, rng=None):
        x, ref = inputs[0], inputs[1]
        T = ref.shape[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], T, x.shape[1]))

    def feed_forward_mask(self, masks):
        return masks[1] if len(masks) > 1 else None


@serde.register
class ReverseTimeSeriesVertex(GraphVertex):
    """Reverse the time axis; with a mask, only the valid prefix is reversed
    (reference ``ReverseTimeSeriesVertex.java``)."""

    def __init__(self, mask_input: Optional[str] = None):
        self.mask_input = mask_input

    def apply(self, inputs, masks, *, train=False, rng=None):
        x = inputs[0]
        m = masks[0]
        if m is None:
            return jnp.flip(x, axis=1)
        T = x.shape[1]
        lengths = jnp.sum(m.astype(jnp.int32), axis=1)  # (b,)
        t = jnp.arange(T)[None, :]  # (1, T)
        idx = jnp.where(t < lengths[:, None], lengths[:, None] - 1 - t, t)  # (b, T)
        return jnp.take_along_axis(x, idx[:, :, None], axis=1)
