"""Dropout variants and weight noise (reference
``nn/conf/dropout/{Dropout,AlphaDropout,GaussianDropout,GaussianNoise}.java``
and ``nn/conf/weightnoise/{DropConnect,WeightNoise}.java``).

A layer's ``dropout`` argument accepts a float (plain inverted dropout on
the layer input — drop probability, the package's existing convention) or
one of the IDropout objects below. ``weight_noise`` accepts an
IWeightNoise applied to the layer's parameters at forward time during
training (reference applies it in ``BaseLayer.getParamsWithNoise``).
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import serde

Array = jax.Array


class IDropout:
    """SPI (reference ``IDropout``): transform the layer input at train
    time; identity at inference."""

    def apply(self, x: Array, rng: Array) -> Array:
        raise NotImplementedError

    def to_dict(self) -> dict:
        return serde.generic_to_dict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "IDropout":
        actual = serde.lookup(data["@class"])
        return serde.generic_from_dict(actual, data)


@serde.register
class Dropout(IDropout):
    """Inverted dropout; ``p`` = DROP probability."""

    def __init__(self, p: float = 0.5):
        self.p = float(p)

    def apply(self, x, rng):
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


@serde.register
class AlphaDropout(IDropout):
    """SELU-compatible dropout (reference ``AlphaDropout.java``): dropped
    units are set to alpha' and the result is affinely rescaled so mean
    and variance are preserved (Klambauer et al. 2017)."""

    _ALPHA = 1.6732632423543772
    _SCALE = 1.0507009873554805

    def __init__(self, p: float = 0.5):
        self.p = float(p)

    def apply(self, x, rng):
        keep = 1.0 - self.p
        alpha_p = -self._ALPHA * self._SCALE
        a = (keep + alpha_p * alpha_p * keep * (1 - keep)) ** -0.5
        b = -a * alpha_p * (1 - keep)
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)


@serde.register
class GaussianDropout(IDropout):
    """Multiplicative gaussian noise ~ N(1, rate/(1-rate)) (reference
    ``GaussianDropout.java``); mean-preserving, no inference rescale."""

    def __init__(self, rate: float = 0.5):
        self.rate = float(rate)

    def apply(self, x, rng):
        stdev = math.sqrt(self.rate / max(1.0 - self.rate, 1e-8))
        noise = 1.0 + stdev * jax.random.normal(rng, x.shape, x.dtype)
        return x * noise


@serde.register
class GaussianNoise(IDropout):
    """Additive gaussian noise N(0, stddev²) (reference
    ``GaussianNoise.java``)."""

    def __init__(self, stddev: float = 0.1):
        self.stddev = float(stddev)

    def apply(self, x, rng):
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype)


# --------------------------------------------------------------------------
# weight noise
# --------------------------------------------------------------------------
class IWeightNoise:
    """SPI (reference ``IWeightNoise``): transform a layer's param dict at
    forward time during training."""

    def apply_to_params(self, params: Dict[str, Array], rng: Array) -> Dict[str, Array]:
        raise NotImplementedError

    def to_dict(self) -> dict:
        return serde.generic_to_dict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "IWeightNoise":
        actual = serde.lookup(data["@class"])
        return serde.generic_from_dict(actual, data)

    @staticmethod
    def _is_weight(name: str) -> bool:
        # bias conventions across the layer catalog: b, bo, b1, b2, beta
        return not name.startswith(("b", "beta"))


@serde.register
class DropConnect(IWeightNoise):
    """Drops individual WEIGHTS (not activations) with probability
    ``1 - weight_retain_prob`` (reference ``DropConnect.java``)."""

    def __init__(self, weight_retain_prob: float = 0.5,
                 apply_to_biases: bool = False):
        self.weight_retain_prob = float(weight_retain_prob)
        self.apply_to_biases = bool(apply_to_biases)

    def apply_to_params(self, params, rng):
        out = {}
        for i, (k, v) in enumerate(sorted(params.items())):
            if (self.apply_to_biases or self._is_weight(k)) and \
                    jnp.issubdtype(v.dtype, jnp.floating):
                keep = self.weight_retain_prob
                mask = jax.random.bernoulli(
                    jax.random.fold_in(rng, i), keep, v.shape
                )
                out[k] = jnp.where(mask, v / keep, 0.0).astype(v.dtype)
            else:
                out[k] = v
        return out


@serde.register
class WeightNoise(IWeightNoise):
    """Additive (default) or multiplicative gaussian noise on weights
    (reference ``WeightNoise.java`` with a normal distribution)."""

    def __init__(self, stddev: float = 0.01, additive: bool = True,
                 apply_to_biases: bool = False):
        self.stddev = float(stddev)
        self.additive = bool(additive)
        self.apply_to_biases = bool(apply_to_biases)

    def apply_to_params(self, params, rng):
        out = {}
        for i, (k, v) in enumerate(sorted(params.items())):
            if (self.apply_to_biases or self._is_weight(k)) and \
                    jnp.issubdtype(v.dtype, jnp.floating):
                noise = self.stddev * jax.random.normal(
                    jax.random.fold_in(rng, i), v.shape, v.dtype
                )
                out[k] = v + noise if self.additive else v * (1.0 + noise)
            else:
                out[k] = v
        return out
